"""Attention ops.

``causal_attention`` dispatches between:
  * a pure-XLA implementation (always correct; XLA fuses the softmax chain
    and maps the two einsums onto the MXU) — also the CPU-test path;
  * a Pallas flash-attention TPU kernel (``ray_tpu.ops.flash_attention``)
    for long sequences where materializing the [T, T] score matrix would be
    HBM-bound.

The reference has no attention ops at all (it defers to torch); this module
exists because on TPU the framework owns the compute path (SURVEY.md §5.7).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Sequence length at/above which the flash kernel pays for itself.
# Measured on v5e (GPT-2 small, batch 16): at seq 1024 the Pallas kernel
# beats XLA attention by ~7 MFU points in-model (fp32 [T,T] score
# materialization is HBM-bound); below 1024 it is unmeasured, so XLA's
# fused attention stays the default there.
_FLASH_MIN_SEQ = 1024


def xla_causal_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, softmax_scale: float | None = None
) -> jax.Array:
    """Causal multi-head attention, pure XLA.

    Args are [batch, seq, heads, head_dim]. Computes in the input dtype
    (bf16 on TPU) with fp32 softmax accumulation.
    """
    *_, t, _h, d = q.shape
    scale = softmax_scale if softmax_scale is not None else d**-0.5
    # [B, H, T, T] scores in fp32 for a stable softmax.
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    mask = jnp.tril(jnp.ones((t, t), dtype=bool))
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    softmax_scale: float | None = None,
    use_flash: bool | None = None,
) -> jax.Array:
    """[B, T, H, D] causal attention with automatic kernel selection."""
    t = q.shape[1]
    explicit = use_flash is True
    if use_flash is None:
        use_flash = (
            t >= _FLASH_MIN_SEQ
            and jax.default_backend() not in ("cpu",)
        )
    if use_flash:
        try:
            from ray_tpu.ops.flash_attention import flash_causal_attention

            return flash_causal_attention(q, k, v, softmax_scale=softmax_scale)
        except (ImportError, NotImplementedError):
            if explicit:
                # The caller asked for flash by name; do not silently degrade.
                raise
    return xla_causal_attention(q, k, v, softmax_scale=softmax_scale)


# -- KV-cache writes (serving decode path) ----------------------------------
#
# Shared by the GPT-2 and Llama decode APIs (``models/gpt2.py`` /
# ``models/llama.py``): the head-count axis differs (full vs GQA
# ``n_kv_head``) but the cursor-write contract is identical, so it lives
# here once.


# decode-path  # jax-hot-path: the KV cache stays in the activation dtype
def cache_write_token(cache: jax.Array, rows: jax.Array,
                      cursor: jax.Array) -> jax.Array:
    """Per-slot ring-cursor write of ONE token's K or V rows.

    cache [S, L, H, hd], rows [S, 1, H, hd], cursor [S] int32 — each
    slot's row lands at its own cursor (vmapped dynamic_update_slice)."""
    return jax.vmap(
        lambda c, r, i: jax.lax.dynamic_update_slice(
            c, r.astype(c.dtype), (i, 0, 0))
    )(cache, rows, cursor)


# decode-path  # jax-hot-path: the KV cache stays in the activation dtype
def cache_write_prompt(cache: jax.Array, rows: jax.Array,
                       slots: jax.Array) -> jax.Array:
    """Prefill-lane write: row block ``rows[i]`` ([P, H, hd]) lands at
    rows ``[0, P)`` of cache slot ``slots[i]``. Sequential over the
    (small, static) prefill-row axis — each write must see the prior
    ones, and distinct slots make the order immaterial."""
    def body(i, c):
        return jax.lax.dynamic_update_slice(
            c, rows[i][None].astype(c.dtype), (slots[i], 0, 0, 0))
    return jax.lax.fori_loop(0, rows.shape[0], body, cache)


def cached_decode_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                            valid: jax.Array, out_dtype) -> jax.Array:
    """One query token per slot over the slot's ring-cache window.

    q [S, H, hd]; k/v [S, L, H, hd] (GQA callers expand KV heads to the
    query heads first); valid [S] = live cache entries (the ring mask).
    fp32 scores/softmax, output cast to the activation dtype — shared
    by both model families' decode steps so the masking/scaling
    contract lives here once."""
    hd = q.shape[-1]
    scores = jnp.einsum(
        "shd,slhd->shl", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / (hd ** 0.5)
    mask = jnp.arange(k.shape[1])[None, :] < valid[:, None]  # [S, L]
    weights = jax.nn.softmax(
        jnp.where(mask[:, None, :], scores, -1e30), axis=-1)
    out = jnp.einsum("shl,slhd->shd", weights, v.astype(jnp.float32))
    return out.astype(out_dtype)
