"""Attention ops.

``causal_attention`` dispatches between:
  * a pure-XLA implementation (always correct; XLA fuses the softmax chain
    and maps the two einsums onto the MXU) — also the CPU-test path;
  * a Pallas flash-attention TPU kernel (``ray_tpu.ops.flash_attention``)
    for long sequences where materializing the [T, T] score matrix would be
    HBM-bound.

The reference has no attention ops at all (it defers to torch); this module
exists because on TPU the framework owns the compute path (SURVEY.md §5.7).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Sequence length at/above which the flash kernel pays for itself.
# Measured on v5e (GPT-2 small, batch 16): at seq 1024 the Pallas kernel
# beats XLA attention by ~7 MFU points in-model (fp32 [T,T] score
# materialization is HBM-bound); below 1024 it is unmeasured, so XLA's
# fused attention stays the default there.
_FLASH_MIN_SEQ = 1024


def xla_causal_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *, softmax_scale: float | None = None
) -> jax.Array:
    """Causal multi-head attention, pure XLA.

    Args are [batch, seq, heads, head_dim]. Computes in the input dtype
    (bf16 on TPU) with fp32 softmax accumulation.
    """
    *_, t, _h, d = q.shape
    scale = softmax_scale if softmax_scale is not None else d**-0.5
    # [B, H, T, T] scores in fp32 for a stable softmax.
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    mask = jnp.tril(jnp.ones((t, t), dtype=bool))
    scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def causal_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    softmax_scale: float | None = None,
    use_flash: bool | None = None,
) -> jax.Array:
    """[B, T, H, D] causal attention with automatic kernel selection."""
    t = q.shape[1]
    explicit = use_flash is True
    if use_flash is None:
        use_flash = (
            t >= _FLASH_MIN_SEQ
            and jax.default_backend() not in ("cpu",)
        )
    if use_flash:
        try:
            from ray_tpu.ops.flash_attention import flash_causal_attention

            return flash_causal_attention(q, k, v, softmax_scale=softmax_scale)
        except (ImportError, NotImplementedError):
            if explicit:
                # The caller asked for flash by name; do not silently degrade.
                raise
    return xla_causal_attention(q, k, v, softmax_scale=softmax_scale)
