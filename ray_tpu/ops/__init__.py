"""Compute ops: attention (XLA + Pallas flash), fused layers, collectives.

The MXU-facing layer: everything here is written for large, static-shaped,
bf16 matmuls that XLA can tile onto the systolic array, with Pallas kernels
for the ops XLA does not fuse well (flash attention with causal masking).
"""

from ray_tpu.ops.attention import causal_attention, xla_causal_attention
from ray_tpu.ops.flash_attention import flash_causal_attention
from ray_tpu.ops.ring_attention import (
    ring_causal_attention,
    ring_causal_attention_local,
    ring_flash_attention_local,
)
from ray_tpu.ops.ulysses import ulysses_attention, ulysses_attention_local
from ray_tpu.ops.moe import init_moe_params, moe_ffn, moe_ffn_ep

__all__ = [
    "causal_attention",
    "xla_causal_attention",
    "flash_causal_attention",
    "ring_causal_attention",
    "ring_causal_attention_local",
    "ring_flash_attention_local",
    "ulysses_attention",
    "ulysses_attention_local",
    "init_moe_params",
    "moe_ffn",
    "moe_ffn_ep",
]
