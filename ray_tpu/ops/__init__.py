"""Compute ops: attention (XLA + Pallas flash), fused layers, collectives.

The MXU-facing layer: everything here is written for large, static-shaped,
bf16 matmuls that XLA can tile onto the systolic array, with Pallas kernels
for the ops XLA does not fuse well (flash attention with causal masking).
"""

from ray_tpu.ops.attention import causal_attention

__all__ = ["causal_attention"]
