"""Pallas TPU fused normalization + elementwise-epilogue kernels.

Attacks PROFILE.md sink #3 (~15ms of the 128ms GPT-2 step, ~1.3ms/layer):
the fp32 layernorm/elementwise *backward* fusions XLA materializes
through HBM. Same playbook as the flash-attention backward that took
39%→52% MFU: fuse the backward chain into one Pallas kernel per
row-block grid cell, keep fp32 statistics in VMEM, never round-trip
fp32 intermediates through HBM.

Three op families, each a ``custom_vjp`` with Pallas forward AND
backward:

* ``fused_layer_norm[_residual]`` — forward computes fp32 mean/rstd in
  VMEM and saves ONLY those per-row statistics (2 floats/row) for
  backward; the fp32 x32/mu/var recompute chain XLA would otherwise
  materialize never reaches HBM. The backward kernel fuses dx (the two
  row-reductions and the recentering), the dscale/dbias column
  reductions (fp32 per-row-block partials, one cheap XLA sum after),
  and — in the ``_residual`` variant — the residual-add gradient, in
  ONE kernel per row-block grid cell.
* ``fused_rms_norm[_residual]`` — the RMSNorm twin (no mean, no bias)
  so ``models/llama.py`` rides the same kernel.
* ``fused_gelu`` — tanh-GELU with a fused backward epilogue for the MLP
  path: saves the pre-activation only, recomputes tanh in VMEM.

The ``_residual`` variants return ``(y, x)`` — pass the second output
into the residual add so its cotangent (the residual gradient) enters
the backward kernel and ``dx = d_residual + d_norm`` happens in VMEM.

Shapes the TPU lane layout can't tile (D not a multiple of 128, or a
row count with no usable sublane-aligned block divisor) fall back to
the plain-XLA chain — numerically identical, just unfused. On CPU
(tests) the kernels run in Pallas interpret mode, exactly like
``flash_attention.py``.
"""

from __future__ import annotations

import collections
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ray_tpu._compat import pallas_tpu_compiler_params

LN_EPS = 1e-5    # matches models/gpt2.py _layer_norm
RMS_EPS = 1e-6   # matches models/llama.py _rms_norm

# Row-block upper bound; the actual block is the largest divisor of the
# row count that respects the dtype's sublane minimum (see _fit_rows).
_MAX_BLOCK_ROWS = 256
# Per-array fp32 VMEM budget for one block. The backward holds ~4 live
# row-blocks (x, dy, dres, dx); wide rows (GELU's [R, 4D]) shrink the
# row block instead of blowing the ~16 MB VMEM.
_BLOCK_BYTES = 2 * 1024 * 1024

# Trace-time kernel-launch counters, keyed by kernel name. Tests and
# fused_norm_bench read these to assert the Pallas path (vs the XLA
# fallback) was actually taken; machine-independent by construction.
KERNEL_INVOCATIONS: collections.Counter = collections.Counter()


def _sublane(dtype) -> int:
    """Minimum second-to-last-dim tile for the dtype (TPU tiling rule)."""
    return 16 if jnp.dtype(dtype).itemsize < 4 else 8


def _fit_rows(r: int, d: int, dtype) -> int | None:
    """Largest row-block that divides ``r``, is sublane-aligned for
    ``dtype``, and keeps one fp32 block under the VMEM budget. None if
    no such block exists (caller falls back to XLA)."""
    cap = max(_sublane(dtype), _BLOCK_BYTES // (4 * d))
    block = min(_MAX_BLOCK_ROWS, cap, r)
    sub = _sublane(dtype)
    block -= block % sub
    while block >= sub and r % block:
        block -= sub
    return block if block >= sub else None


def _should_fuse(r: int, d: int, dtype) -> int | None:
    """Row block to use, or None when the shape can't tile the TPU lane
    layout (D % 128, degenerate row counts) and XLA should run instead."""
    if d % 128 != 0 or r <= 0:
        return None
    return _fit_rows(r, d, dtype)


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


# -- plain-XLA references (fallback path; also the parity oracle) ----------


def ref_layer_norm(x, scale, bias, eps: float = LN_EPS):
    """Bit-for-bit the model's ``_layer_norm`` chain (fallback path)."""
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def ref_rms_norm(x, scale, eps: float = RMS_EPS):
    x32 = x.astype(jnp.float32)
    y = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (y * scale).astype(x.dtype)


def ref_gelu(x):
    return jax.nn.gelu(x, approximate=True)


# -- forward kernels -------------------------------------------------------


def _ln_fwd_kernel(x_ref, scale_ref, bias_ref, y_ref, mu_ref, rstd_ref,
                   *, eps: float):
    """One row-block: fp32 mean/rstd computed and kept in VMEM; only the
    [block, 1] statistics are written for backward."""
    x32 = x_ref[:].astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    xc = x32 - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd * scale_ref[:].astype(jnp.float32) \
        + bias_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mu_ref[:] = mu
    rstd_ref[:] = rstd


def _rms_fwd_kernel(x_ref, scale_ref, y_ref, rstd_ref, *, eps: float):
    x32 = x_ref[:].astype(jnp.float32)
    rstd = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    y_ref[:] = (x32 * rstd * scale_ref[:].astype(jnp.float32)).astype(
        y_ref.dtype)
    rstd_ref[:] = rstd


def _norm_fwd(x2d, scale, bias, *, block: int, eps: float, rms: bool,
              interpret: bool):
    """x2d [R, D] -> (y [R, D], mu [R, 1] | None, rstd [R, 1])."""
    r, d = x2d.shape
    grid = (r // block,)
    row_spec = pl.BlockSpec((block, d), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d), lambda i: (0, 0))
    stat_spec = pl.BlockSpec((block, 1), lambda i: (i, 0))
    stat_shape = jax.ShapeDtypeStruct((r, 1), jnp.float32)
    params = pallas_tpu_compiler_params(dimension_semantics=("parallel",))
    if rms:
        KERNEL_INVOCATIONS["rms_fwd"] += 1
        y, rstd = pl.pallas_call(
            functools.partial(_rms_fwd_kernel, eps=eps),
            grid=grid,
            in_specs=[row_spec, vec_spec],
            out_specs=[row_spec, stat_spec],
            out_shape=[jax.ShapeDtypeStruct((r, d), x2d.dtype), stat_shape],
            compiler_params=params,
            interpret=interpret,
        )(x2d, scale.reshape(1, d))
        return y, None, rstd
    KERNEL_INVOCATIONS["ln_fwd"] += 1
    y, mu, rstd = pl.pallas_call(
        functools.partial(_ln_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[row_spec, vec_spec, vec_spec],
        out_specs=[row_spec, stat_spec, stat_spec],
        out_shape=[jax.ShapeDtypeStruct((r, d), x2d.dtype), stat_shape,
                   stat_shape],
        compiler_params=params,
        interpret=interpret,
    )(x2d, scale.reshape(1, d), bias.reshape(1, d))
    return y, mu, rstd


# -- backward kernel -------------------------------------------------------


def _norm_bwd_kernel(x_ref, mu_ref, rstd_ref, scale_ref, dy_ref, dres_ref,
                     dx_ref, dscale_ref, dbias_ref, *, rms: bool):
    """ONE kernel per row-block: recenters xhat from the saved fp32
    statistics, computes the two row-reductions (c1 = mean(dxhat),
    c2 = mean(dxhat·xhat)), emits dx — fused with the residual-add
    gradient when a dres ref is present — plus the per-block
    dscale/dbias column partials, all without an fp32 HBM round-trip."""
    x32 = x_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:]
    xhat = x32 * rstd if rms else (x32 - mu_ref[:]) * rstd
    dy32 = dy_ref[:].astype(jnp.float32)
    dxhat = dy32 * scale_ref[:].astype(jnp.float32)
    c2 = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    if rms:
        dx = rstd * (dxhat - xhat * c2)
    else:
        c1 = jnp.mean(dxhat, axis=-1, keepdims=True)
        dx = rstd * (dxhat - c1 - xhat * c2)
    if dres_ref is not None:
        dx = dx + dres_ref[:].astype(jnp.float32)
    dx_ref[:] = dx.astype(dx_ref.dtype)
    dscale_ref[:] = jnp.sum(dy32 * xhat, axis=0, keepdims=True)
    if dbias_ref is not None:
        dbias_ref[:] = jnp.sum(dy32, axis=0, keepdims=True)


def _norm_bwd(x2d, mu, rstd, scale, dy, dres, *, block: int, rms: bool,
              interpret: bool):
    """-> (dx [R, D], dscale [D] fp32, dbias [D] fp32 | None).

    dscale/dbias come back as per-row-block fp32 partials ([n_blocks, D])
    that one XLA sum collapses — the same partials-then-reduce shape as
    the flash backward's dQ path."""
    r, d = x2d.shape
    n_blocks = r // block
    with_res = dres is not None
    with_bias = not rms

    row_spec = pl.BlockSpec((block, d), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, d), lambda i: (0, 0))
    stat_spec = pl.BlockSpec((block, 1), lambda i: (i, 0))
    part_spec = pl.BlockSpec((1, d), lambda i: (i, 0))
    part_shape = jax.ShapeDtypeStruct((n_blocks, d), jnp.float32)

    inputs, in_specs = [x2d], [row_spec]
    if not rms:
        inputs.append(mu)
        in_specs.append(stat_spec)
    inputs += [rstd, scale.reshape(1, d), dy]
    in_specs += [stat_spec, vec_spec, row_spec]
    if with_res:
        inputs.append(dres)
        in_specs.append(row_spec)
    out_specs = [row_spec, part_spec]
    out_shape = [jax.ShapeDtypeStruct((r, d), x2d.dtype), part_shape]
    if with_bias:
        out_specs.append(part_spec)
        out_shape.append(part_shape)

    def body(*refs):
        it = iter(refs)
        x_ref = next(it)
        mu_ref = None if rms else next(it)
        rstd_ref, scale_ref, dy_ref = next(it), next(it), next(it)
        dres_ref = next(it) if with_res else None
        dx_ref, dscale_ref = next(it), next(it)
        dbias_ref = next(it) if with_bias else None
        _norm_bwd_kernel(x_ref, mu_ref, rstd_ref, scale_ref, dy_ref,
                         dres_ref, dx_ref, dscale_ref, dbias_ref, rms=rms)

    KERNEL_INVOCATIONS["rms_bwd" if rms else "ln_bwd"] += 1
    out = pl.pallas_call(
        body,
        grid=(n_blocks,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(*inputs)
    if with_bias:
        dx, dscale_p, dbias_p = out
        return dx, jnp.sum(dscale_p, axis=0), jnp.sum(dbias_p, axis=0)
    dx, dscale_p = out
    return dx, jnp.sum(dscale_p, axis=0), None


# -- GELU kernels ----------------------------------------------------------

_GELU_C = math.sqrt(2.0 / math.pi)
_GELU_A = 0.044715


def _gelu_fwd_kernel(x_ref, y_ref):
    x32 = x_ref[:].astype(jnp.float32)
    t = jnp.tanh(_GELU_C * (x32 + _GELU_A * x32 * x32 * x32))
    y_ref[:] = (0.5 * x32 * (1.0 + t)).astype(y_ref.dtype)


def _gelu_bwd_kernel(x_ref, g_ref, dx_ref):
    """Fused tanh-GELU backward epilogue: recompute tanh from the saved
    pre-activation in VMEM, one multiply-out to dx — no fp32 tanh/sech
    intermediates in HBM."""
    x32 = x_ref[:].astype(jnp.float32)
    g32 = g_ref[:].astype(jnp.float32)
    u = _GELU_C * (x32 + _GELU_A * x32 * x32 * x32)
    t = jnp.tanh(u)
    du = _GELU_C * (1.0 + 3.0 * _GELU_A * x32 * x32)
    dgelu = 0.5 * (1.0 + t) + 0.5 * x32 * (1.0 - t * t) * du
    dx_ref[:] = (g32 * dgelu).astype(dx_ref.dtype)


def _gelu_call(kernel, args, r, d, block, dtype, name, interpret):
    row_spec = pl.BlockSpec((block, d), lambda i: (i, 0))
    KERNEL_INVOCATIONS[name] += 1
    return pl.pallas_call(
        kernel,
        grid=(r // block,),
        in_specs=[row_spec] * len(args),
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((r, d), dtype),
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(*args)


# -- custom VJP wiring -----------------------------------------------------
#
# Static args (block, eps, interpret) ride nondiff_argnums, exactly like
# flash attention. The 2D reshape happens in the public wrappers; the
# vjp ops see [R, D].


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _norm_op(x2d, scale, bias, block, eps, rms, interpret):
    y, _, _ = _norm_fwd(x2d, scale, bias, block=block, eps=eps, rms=rms,
                        interpret=interpret)
    return y


def _norm_op_fwd(x2d, scale, bias, block, eps, rms, interpret):
    y, mu, rstd = _norm_fwd(x2d, scale, bias, block=block, eps=eps, rms=rms,
                            interpret=interpret)
    return y, (x2d, scale, mu, rstd)


def _norm_op_bwd(block, eps, rms, interpret, res, dy):
    x2d, scale, mu, rstd = res
    dx, dscale, dbias = _norm_bwd(
        x2d, mu, rstd, scale, dy, None, block=block, rms=rms,
        interpret=interpret)
    dscale = dscale.astype(scale.dtype)
    if rms:
        return dx, dscale, None
    return dx, dscale, dbias.astype(scale.dtype)


_norm_op.defvjp(_norm_op_fwd, _norm_op_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _norm_res_op(x2d, scale, bias, block, eps, rms, interpret):
    """Returns (y, x_passthrough): route the second output into the
    residual add so its cotangent reaches the fused backward kernel."""
    y, _, _ = _norm_fwd(x2d, scale, bias, block=block, eps=eps, rms=rms,
                        interpret=interpret)
    return y, x2d


def _norm_res_op_fwd(x2d, scale, bias, block, eps, rms, interpret):
    y, mu, rstd = _norm_fwd(x2d, scale, bias, block=block, eps=eps, rms=rms,
                            interpret=interpret)
    return (y, x2d), (x2d, scale, mu, rstd)


def _norm_res_op_bwd(block, eps, rms, interpret, res, cts):
    x2d, scale, mu, rstd = res
    dy, dres = cts
    dx, dscale, dbias = _norm_bwd(
        x2d, mu, rstd, scale, dy, dres, block=block, rms=rms,
        interpret=interpret)
    dscale = dscale.astype(scale.dtype)
    if rms:
        return dx, dscale, None
    return dx, dscale, dbias.astype(scale.dtype)


_norm_res_op.defvjp(_norm_res_op_fwd, _norm_res_op_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _gelu_op(x2d, block, interpret):
    r, d = x2d.shape
    return _gelu_call(_gelu_fwd_kernel, (x2d,), r, d, block, x2d.dtype,
                      "gelu_fwd", interpret)


def _gelu_op_fwd(x2d, block, interpret):
    return _gelu_op(x2d, block, interpret), x2d


def _gelu_op_bwd(block, interpret, x2d, g):
    r, d = x2d.shape
    dx = _gelu_call(_gelu_bwd_kernel, (x2d, g), r, d, block, x2d.dtype,
                    "gelu_bwd", interpret)
    return (dx,)


_gelu_op.defvjp(_gelu_op_fwd, _gelu_op_bwd)


# -- public API ------------------------------------------------------------


def _to_2d(x):
    d = x.shape[-1]
    return x.reshape(-1, d), x.shape


def fused_layer_norm(x, scale, bias, *, eps: float = LN_EPS):
    """LayerNorm over the last dim of ``x`` [..., D]; fp32 statistics,
    output in ``x.dtype``. Pallas-fused where the shape tiles; plain-XLA
    fallback otherwise."""
    x2d, shape = _to_2d(x)
    block = _should_fuse(x2d.shape[0], x2d.shape[1], x.dtype)
    if block is None:
        return ref_layer_norm(x, scale, bias, eps)
    return _norm_op(x2d, scale, bias, block, eps, False,
                    _interpret()).reshape(shape)


def fused_layer_norm_residual(x, scale, bias, *, eps: float = LN_EPS):
    """(LayerNorm(x), x): feed the second output into the residual add —
    its cotangent is summed into dx inside the one backward kernel."""
    x2d, shape = _to_2d(x)
    block = _should_fuse(x2d.shape[0], x2d.shape[1], x.dtype)
    if block is None:
        return ref_layer_norm(x, scale, bias, eps), x
    y, x_skip = _norm_res_op(x2d, scale, bias, block, eps, False,
                             _interpret())
    return y.reshape(shape), x_skip.reshape(shape)


def fused_rms_norm(x, scale, *, eps: float = RMS_EPS):
    """RMSNorm twin of ``fused_layer_norm`` (no mean, no bias)."""
    x2d, shape = _to_2d(x)
    block = _should_fuse(x2d.shape[0], x2d.shape[1], x.dtype)
    if block is None:
        return ref_rms_norm(x, scale, eps)
    return _norm_op(x2d, scale, None, block, eps, True,
                    _interpret()).reshape(shape)


def fused_rms_norm_residual(x, scale, *, eps: float = RMS_EPS):
    x2d, shape = _to_2d(x)
    block = _should_fuse(x2d.shape[0], x2d.shape[1], x.dtype)
    if block is None:
        return ref_rms_norm(x, scale, eps), x
    y, x_skip = _norm_res_op(x2d, scale, None, block, eps, True,
                             _interpret())
    return y.reshape(shape), x_skip.reshape(shape)


def fused_gelu(x):
    """tanh-GELU with the fused Pallas backward epilogue (MLP path)."""
    x2d, shape = _to_2d(x)
    block = _should_fuse(x2d.shape[0], x2d.shape[1], x.dtype)
    if block is None:
        return ref_gelu(x)
    return _gelu_op(x2d, block, _interpret()).reshape(shape)
