"""Ring attention: causal attention over a sequence-sharded axis.

Long-context scaling (SURVEY.md §5.7 — absent from the reference, required
here): Q/K/V are sharded over mesh axis ``sp`` ([B, T/sp, H, D] per
device). Each device computes blockwise attention of its Q shard against
the K/V shard it currently holds, then rotates K/V around the ring with
``ppermute`` — sp steps visit every KV block while only ever holding
O(T/sp) keys, and the permute overlaps with the next block's compute (XLA
schedules the collective-permute concurrently with the matmuls).

Causal masking across shards: a KV shard strictly *ahead* of the Q shard
contributes nothing (skipped by masking the whole block), the diagonal
shard uses the triangular mask, and shards behind contribute fully.
Online-softmax merging keeps fp32 running (max, denom, acc) — the same
math as flash attention, at ring granularity.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from ray_tpu._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _block_attend(q, k, v, q_chunk_idx, kv_chunk_idx, chunk, scale):
    """Scores of local q against one kv chunk with cross-chunk causality.
    q,k,v: [B, C, H, D]; returns (scores_max m, exp-sum l, weighted acc)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    q_pos = q_chunk_idx * chunk + jnp.arange(chunk)
    k_pos = kv_chunk_idx * chunk + jnp.arange(chunk)
    mask = q_pos[:, None] >= k_pos[None, :]
    s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # [B,H,Q,1]
    # Guard fully-masked rows (kv chunk entirely in the future).
    m_safe = jnp.maximum(m, _NEG_INF / 2)
    p = jnp.exp(s - m_safe)
    p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return m_safe, l, acc


def ring_causal_attention_local(q, k, v, *, axis_size: int, axis: str = "sp",
                                softmax_scale: float | None = None):
    """The per-device body: call INSIDE shard_map over ``axis``.

    q/k/v per-device: [B, C, H, D] where C = T / sp. The ring loop is
    unrolled (sp is small and static) so the whole op stays reverse-mode
    differentiable and XLA can overlap each ppermute with the next
    block's compute.
    """
    b, c, h, d = q.shape
    scale = softmax_scale if softmax_scale is not None else d**-0.5
    sp = axis_size
    my_idx = jax.lax.axis_index(axis)
    perm = [(i, (i - 1) % sp) for i in range(sp)]  # kv travels backward

    qf = q.astype(jnp.float32)
    m = jnp.full((b, h, c, 1), _NEG_INF / 2, jnp.float32)
    l = jnp.zeros((b, h, c, 1), jnp.float32)
    acc = jnp.zeros((b, c, h, d), jnp.float32)
    k_cur, v_cur = k, v
    for i in range(sp):
        kv_idx = (my_idx + i) % sp
        bm, bl, bacc = _block_attend(qf, k_cur, v_cur, my_idx, kv_idx, c, scale)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(bm - m_new)
        l = l * alpha + bl * beta
        acc = acc * jnp.swapaxes(alpha, 1, 2) + bacc * jnp.swapaxes(beta, 1, 2)
        m = m_new
        if i != sp - 1:
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)
    out = acc / jnp.maximum(jnp.swapaxes(l, 1, 2), 1e-30)
    return out.astype(q.dtype)


# -- Pallas-fused ring attention (SURVEY §7 hard-part 5) -------------------
#
# Each ring step runs the flash kernel on (local Q) x (current KV block):
# per-block scores live only in VMEM tiles, never [B,H,C,C] in HBM. The
# per-block results are BLOCK-normalized (out_i, lse_i); merging in log
# space reconstructs the global softmax exactly:
#     lse = logaddexp_i(lse_i);  out = sum_i exp(lse_i - lse) * out_i.
# KV blocks strictly ahead of the Q shard are masked out of the merge with
# lse_i = -inf (same FLOPs as the dense ring variant, which also computed
# every block; skipping them is a load-balancing follow-up — cf. striped
# attention).
#
# Backward is a second ring pass: _flash_bwd with the GLOBAL (out, lse)
# yields this block's exact (dq, dk, dv) contributions (p = exp(s - lse)
# is the true global probability of the tile). dQ accumulates locally;
# dK/dV accumulators travel WITH their KV block and take one final
# ppermute home.


def _lse_to_weights(lse_bh, b, h, c):
    """[B*H, C, 1] fp32 -> broadcastable [B, C, H, 1] weight exponent."""
    return lse_bh.reshape(b, h, c, 1).transpose(0, 2, 1, 3)


def _ring_flash_fwd(q, k, v, axis, axis_size, scale, interpret):
    from ray_tpu.ops.flash_attention import _fit_block, _flash_fwd

    b, c, h, d = q.shape
    block_q = _fit_block(1024, c)
    block_k = _fit_block(1024, c)
    sp = axis_size
    my_idx = jax.lax.axis_index(axis)
    perm = [(i, (i - 1) % sp) for i in range(sp)]  # kv travels backward

    kwargs = dict(block_q=block_q, block_k=block_k, softmax_scale=scale,
                  interpret=interpret)
    out_r, lse_r = _flash_fwd(q, k, v, causal=True, **kwargs)
    out_r = out_r.astype(jnp.float32)
    k_cur, v_cur = k, v
    for i in range(1, sp):
        k_cur = jax.lax.ppermute(k_cur, axis, perm)
        v_cur = jax.lax.ppermute(v_cur, axis, perm)
        kv_idx = (my_idx + i) % sp
        o_i, lse_i = _flash_fwd(q, k_cur, v_cur, causal=False, **kwargs)
        lse_i = jnp.where(kv_idx > my_idx, _NEG_INF, lse_i)
        lse_new = jnp.logaddexp(lse_r, lse_i)
        w_r = jnp.exp(_lse_to_weights(lse_r - lse_new, b, h, c))
        w_i = jnp.exp(_lse_to_weights(lse_i - lse_new, b, h, c))
        out_r = out_r * w_r + o_i.astype(jnp.float32) * w_i
        lse_r = lse_new
    return out_r.astype(q.dtype), lse_r


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_flash_attention(q, k, v, axis, axis_size, scale, interpret):
    out, _ = _ring_flash_fwd(q, k, v, axis, axis_size, scale, interpret)
    return out


def _ring_vjp_fwd(q, k, v, axis, axis_size, scale, interpret):
    out, lse = _ring_flash_fwd(q, k, v, axis, axis_size, scale, interpret)
    return out, (q, k, v, out, lse)


def _ring_vjp_bwd(axis, axis_size, scale, interpret, res, g):
    from ray_tpu.ops.flash_attention import _fit_block, _flash_bwd

    q, k, v, out, lse = res
    b, c, h, d = q.shape
    block_q = _fit_block(1024, c)
    block_k = _fit_block(1024, c)
    sp = axis_size
    my_idx = jax.lax.axis_index(axis)
    perm = [(i, (i - 1) % sp) for i in range(sp)]

    kwargs = dict(block_q=block_q, block_k=block_k, softmax_scale=scale,
                  interpret=interpret)
    dq = jnp.zeros(q.shape, jnp.float32)
    k_cur, v_cur = k, v
    dk_acc = jnp.zeros(k.shape, jnp.float32)
    dv_acc = jnp.zeros(v.shape, jnp.float32)
    for i in range(sp):
        if i:
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)
            dk_acc = jax.lax.ppermute(dk_acc, axis, perm)
            dv_acc = jax.lax.ppermute(dv_acc, axis, perm)
        if i:
            # Blocks ahead of this Q shard contributed nothing forward.
            # Masking through the lse (p = exp(s - huge) -> 0) zeroes
            # their gradients WITHOUT the overflow risk of computing
            # exp(s - lse) against an unrelated lse and multiplying by 0
            # afterwards (0 * inf = nan).
            kv_idx = (my_idx + i) % sp
            ahead = kv_idx > my_idx
            lse_use = jnp.where(ahead, jnp.full_like(lse, -_NEG_INF), lse)
            keep = (~ahead).astype(jnp.float32)
        else:
            lse_use, keep = lse, 1.0
        dq_i, dk_i, dv_i = _flash_bwd(
            q, k_cur, v_cur, out, lse_use, g, causal=(i == 0), **kwargs)
        dq_i = dq_i * keep
        dk_i = dk_i * keep
        dv_i = dv_i * keep
        dq = dq + dq_i.astype(jnp.float32)
        dk_acc = dk_acc + dk_i.astype(jnp.float32)
        dv_acc = dv_acc + dv_i.astype(jnp.float32)
    # One more hop returns each accumulator to its KV block's owner.
    dk_acc = jax.lax.ppermute(dk_acc, axis, perm)
    dv_acc = jax.lax.ppermute(dv_acc, axis, perm)
    return (dq.astype(q.dtype), dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype))


_ring_flash_attention.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def ring_flash_attention_local(q, k, v, *, axis_size: int, axis: str = "sp",
                               softmax_scale: float | None = None):
    """Pallas-fused per-device ring attention body (call inside shard_map
    over ``axis``); differentiable. Falls back implicitly to interpret
    mode on CPU."""
    d = q.shape[-1]
    scale = softmax_scale if softmax_scale is not None else d**-0.5
    interpret = jax.default_backend() == "cpu"
    return _ring_flash_attention(
        q, k, v, axis, axis_size, scale, interpret)


def ring_causal_attention(q, k, v, mesh: Mesh, *, axis: str = "sp",
                          softmax_scale: float | None = None,
                          batch_axes=("dp", "fsdp"), impl: str = "fused"):
    """Full-array entry: q/k/v [B, T, H, D] with T sharded over ``axis``.

    ``impl="fused"`` (default) runs the flash kernel on every ring block;
    ``impl="dense"`` keeps the einsum body (debug/fallback — materializes
    [B,H,C,C] scores per block)."""
    local = (ring_flash_attention_local if impl == "fused"
             else ring_causal_attention_local)
    spec = P(batch_axes, axis, None, None)
    fn = shard_map(
        functools.partial(
            local, axis=axis,
            axis_size=mesh.shape[axis], softmax_scale=softmax_scale,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
