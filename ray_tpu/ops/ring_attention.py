"""Ring attention: causal attention over a sequence-sharded axis.

Long-context scaling (SURVEY.md §5.7 — absent from the reference, required
here): Q/K/V are sharded over mesh axis ``sp`` ([B, T/sp, H, D] per
device). Each device computes blockwise attention of its Q shard against
the K/V shard it currently holds, then rotates K/V around the ring with
``ppermute`` — sp steps visit every KV block while only ever holding
O(T/sp) keys, and the permute overlaps with the next block's compute (XLA
schedules the collective-permute concurrently with the matmuls).

Causal masking across shards: a KV shard strictly *ahead* of the Q shard
contributes nothing (skipped by masking the whole block), the diagonal
shard uses the triangular mask, and shards behind contribute fully.
Online-softmax merging keeps fp32 running (max, denom, acc) — the same
math as flash attention, at ring granularity.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _block_attend(q, k, v, q_chunk_idx, kv_chunk_idx, chunk, scale):
    """Scores of local q against one kv chunk with cross-chunk causality.
    q,k,v: [B, C, H, D]; returns (scores_max m, exp-sum l, weighted acc)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    q_pos = q_chunk_idx * chunk + jnp.arange(chunk)
    k_pos = kv_chunk_idx * chunk + jnp.arange(chunk)
    mask = q_pos[:, None] >= k_pos[None, :]
    s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # [B,H,Q,1]
    # Guard fully-masked rows (kv chunk entirely in the future).
    m_safe = jnp.maximum(m, _NEG_INF / 2)
    p = jnp.exp(s - m_safe)
    p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return m_safe, l, acc


def ring_causal_attention_local(q, k, v, *, axis_size: int, axis: str = "sp",
                                softmax_scale: float | None = None):
    """The per-device body: call INSIDE shard_map over ``axis``.

    q/k/v per-device: [B, C, H, D] where C = T / sp. The ring loop is
    unrolled (sp is small and static) so the whole op stays reverse-mode
    differentiable and XLA can overlap each ppermute with the next
    block's compute.
    """
    b, c, h, d = q.shape
    scale = softmax_scale if softmax_scale is not None else d**-0.5
    sp = axis_size
    my_idx = jax.lax.axis_index(axis)
    perm = [(i, (i - 1) % sp) for i in range(sp)]  # kv travels backward

    qf = q.astype(jnp.float32)
    m = jnp.full((b, h, c, 1), _NEG_INF / 2, jnp.float32)
    l = jnp.zeros((b, h, c, 1), jnp.float32)
    acc = jnp.zeros((b, c, h, d), jnp.float32)
    k_cur, v_cur = k, v
    for i in range(sp):
        kv_idx = (my_idx + i) % sp
        bm, bl, bacc = _block_attend(qf, k_cur, v_cur, my_idx, kv_idx, c, scale)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(bm - m_new)
        l = l * alpha + bl * beta
        acc = acc * jnp.swapaxes(alpha, 1, 2) + bacc * jnp.swapaxes(beta, 1, 2)
        m = m_new
        if i != sp - 1:
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)
    out = acc / jnp.maximum(jnp.swapaxes(l, 1, 2), 1e-30)
    return out.astype(q.dtype)


def ring_causal_attention(q, k, v, mesh: Mesh, *, axis: str = "sp",
                          softmax_scale: float | None = None,
                          batch_axes=("dp", "fsdp")):
    """Full-array entry: q/k/v [B, T, H, D] with T sharded over ``axis``."""
    spec = P(batch_axes, axis, None, None)
    fn = shard_map(
        functools.partial(
            ring_causal_attention_local, axis=axis,
            axis_size=mesh.shape[axis], softmax_scale=softmax_scale,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
