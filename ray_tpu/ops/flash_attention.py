"""Pallas TPU flash attention (causal) with Pallas forward AND backward.

Forward: one Pallas kernel per (batch*head, q-block) grid cell streams K/V
blocks through VMEM with online-softmax accumulation — the [T, T] score
matrix never exists in HBM (the reason XLA attention OOMs at long T).

Backward, short sequences (<= _DQ_PARTIALS_MAX_KB k-blocks): ONE Pallas
kernel, grid (bh, k-block, q-block): recomputes P for each (q, k) tile
from the saved logsumexp ONCE, accumulates dK/dV in VMEM scratch across
the q sweep, and writes fp32 per-k-block dQ partial contributions that a
single XLA reduction sums afterwards. The standard two-kernel flash
backward recomputes P twice (once for dKV, once for dQ); at short
sequence lengths the recompute (exp on the VPU) dominates, so trading
the second recompute for a small dQ-partials HBM roundtrip is a measured
win on v5e.

Backward, long sequences: the dQ-partials tensor ([bh, n_kb, t, d])
would grow O(T^2 / block_k), so past the threshold the standard
two-kernel split runs instead — dKV kernel plus a dQ kernel with in-VMEM
accumulation — preserving the O(T) memory property that makes flash
attention viable at long context. Nothing [T, T]-shaped ever reaches
HBM on either path. All matmuls run in the
input dtype (bf16 on TPU => full MXU rate) with fp32 accumulation;
softmax statistics stay fp32. Causal tiles that need no masking skip
the mask arithmetic entirely (VPU, not MXU, is the bottleneck at short
sequence lengths — measured on v5e).

On CPU (tests) the kernels run in Pallas interpret mode.

Reference parity: the reference has no attention kernels at all (torch
owns its compute path); this module exists because on TPU the framework
owns the compute path (SURVEY.md §5.7).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ray_tpu._compat import pallas_tpu_compiler_params

_NEG_INF = -1e30

# Backward dQ strategy switch: up to this many k-blocks the fused dKV+dQ
# kernel writes fp32 dQ partials ([bh, n_kb, t, d] HBM, P computed once);
# beyond it the O(T)-memory two-kernel path is used (see _flash_bwd).
_DQ_PARTIALS_MAX_KB = 4


def _block_classes(q_start, k_start, block_q: int, block_k: int,
                   causal: bool):
    """Causal tile classification shared by all kernels: (needed, on_diag).
    Fully-future tiles contribute nothing; only diagonal-straddling tiles
    pay for mask arithmetic."""
    if not causal:
        return True, False
    needed = q_start + block_q - 1 >= k_start
    on_diag = k_start + block_k - 1 > q_start
    return needed, on_diag


def _dispatch_causal(causal: bool, needed, on_diag, accumulate):
    """Run ``accumulate(masked)`` under the right pl.when branch so
    off-diagonal tiles skip the iota mask (VPU) entirely."""
    if causal:
        @pl.when(needed & jnp.logical_not(on_diag))
        def _full():
            accumulate(False)

        @pl.when(needed & on_diag)
        def _diag():
            accumulate(True)
    else:
        accumulate(False)


# -- forward ---------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, block_q: int, block_k: int, n_kb: int,
                softmax_scale: float, causal: bool):
    """Grid (bh, q_block, k_block), k innermost: pallas double-buffers the
    K/V block DMAs while the previous block's matmuls run. Running
    (max, denom, acc) live in VMEM scratch that persists across the k
    sweep; outputs are finalized on the last k block."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    q_start = qi * block_q
    k_start = kb * block_k

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    needed, on_diag = _block_classes(
        q_start, k_start, block_q, block_k, causal)

    def _accumulate(masked: bool):
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * softmax_scale  # [bq, bk] fp32
        if masked:
            s = _apply_causal_mask(s, q_start, k_start, block_q, block_k)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    _dispatch_causal(causal, needed, on_diag, _accumulate)

    @pl.when(kb == n_kb - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[:] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[:] = m_scr[:] + jnp.log(l)


def _flash_fwd(q, k, v, *, block_q: int, block_k: int, softmax_scale: float,
               causal: bool, interpret: bool):
    """q,k,v: [B, T, H, D] -> (out [B,T,H,D], lse [B*H,T,1])."""
    b, t, h, d = q.shape
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    n_kb = t // block_k
    grid = (b * h, t // block_q, n_kb)
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_q=block_q, block_k=block_k, n_kb=n_kb,
            softmax_scale=softmax_scale, causal=causal,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi, kb: (bh, qi, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, qi, kb: (bh, kb, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, qi, kb: (bh, kb, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi, kb: (bh, qi, 0)),
            # lse rides a trailing singleton lane dim to satisfy the TPU
            # block-tiling rule (last dim == array dim).
            pl.BlockSpec((None, block_q, 1), lambda bh, qi, kb: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qr, kr, vr)
    out = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    return out, lse


# -- backward --------------------------------------------------------------


def _apply_causal_mask(s, q_start, k_start, block_q: int, block_k: int):
    q_pos = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


def _recompute_p_ds(q, k, v, g, lse, delta, q_start, k_start,
                    block_q, block_k, softmax_scale, masked):
    """Shared tile math for both backward kernels.

    Returns (p, ds) both cast to the matmul dtype. lse/delta: [bq, 1] fp32.
    """
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * softmax_scale  # [bq, bk]
    if masked:
        s = _apply_causal_mask(s, q_start, k_start, block_q, block_k)
    p = jnp.exp(s - lse)  # [bq, bk] fp32
    dp = jax.lax.dot_general(
        g, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bq, bk]
    ds = p * (dp - delta) * softmax_scale
    return p.astype(q.dtype), ds.astype(q.dtype)


def _dkv_kernel(q_ref, g_ref, lse_ref, delta_ref, k_ref, v_ref,
                dk_ref, dv_ref, dqp_ref, dk_scr, dv_scr,
                *, block_q: int, block_k: int, n_qb: int,
                softmax_scale: float, causal: bool, with_dqp: bool):
    """Grid (bh, k_block, q_block), q innermost: for one fixed K/V tile,
    dK/dV accumulate in VMEM across the q sweep. With ``with_dqp`` each
    cell also writes its fp32 dQ contribution (one per (k-block,
    q-block)) for the XLA post-reduction, so P/dS are recomputed exactly
    once per tile (fused path for short sequences)."""
    kb = pl.program_id(1)
    qi = pl.program_id(2)
    q_start = qi * block_q
    k_start = kb * block_k

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    needed, on_diag = _block_classes(
        q_start, k_start, block_q, block_k, causal)

    def _accumulate(masked: bool):
        q = q_ref[:]
        g = g_ref[:]
        k = k_ref[:]
        p, ds = _recompute_p_ds(
            q, k, v_ref[:], g, lse_ref[:], delta_ref[:],
            q_start, k_start, block_q, block_k, softmax_scale, masked)
        # dV += P^T dO ; dK += dS^T Q   (contract over the q dim)
        dv_scr[:] += jax.lax.dot_general(
            p, g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if with_dqp:
            dqp_ref[:] = jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

    if causal and with_dqp:
        # Skipped tiles still own their dQ-partials output block.
        @pl.when(jnp.logical_not(needed))
        def _skip():
            dqp_ref[:] = jnp.zeros_like(dqp_ref)

    _dispatch_causal(causal, needed, on_diag, _accumulate)

    @pl.when(qi == n_qb - 1)
    def _finalize():
        dk_ref[:] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_scr[:].astype(dv_ref.dtype)


def _dq_kernel(q_ref, g_ref, lse_ref, delta_ref, k_ref, v_ref,
               dq_ref, dq_scr,
               *, block_q: int, block_k: int, n_kb: int,
               softmax_scale: float, causal: bool):
    """Grid (bh, q_block, k_block), k innermost: dQ accumulates in VMEM
    across the k sweep for one fixed Q tile (O(T)-memory path for long
    sequences; recomputes P a second time)."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    q_start = qi * block_q
    k_start = kb * block_k

    @pl.when(kb == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    needed, on_diag = _block_classes(
        q_start, k_start, block_q, block_k, causal)

    def _accumulate(masked: bool):
        q = q_ref[:]
        g = g_ref[:]
        k = k_ref[:]
        _, ds = _recompute_p_ds(
            q, k, v_ref[:], g, lse_ref[:], delta_ref[:],
            q_start, k_start, block_q, block_k, softmax_scale, masked)
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    _dispatch_causal(causal, needed, on_diag, _accumulate)

    @pl.when(kb == n_kb - 1)
    def _finalize():
        dq_ref[:] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd(q, k, v, out, lse, g, *, block_q: int, block_k: int,
               softmax_scale: float, causal: bool, interpret: bool):
    """q,k,v,out,g: [B,T,H,D]; lse: [B*H,T,1] fp32 -> (dq, dk, dv)."""
    b, t, h, d = q.shape
    bh = b * h

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(bh, t, d)

    qr, kr, vr, gr = to_bh(q), to_bh(k), to_bh(v), to_bh(g)
    # delta = rowsum(dO * O): the softmax-jacobian diagonal term, fp32.
    delta = jnp.einsum(
        "bthd,bthd->bht", g, out, preferred_element_type=jnp.float32
    ).reshape(bh, t, 1)

    n_qb = t // block_q
    n_kb = t // block_k

    q_spec = pl.BlockSpec((None, block_q, d), lambda bhi, a, b_: (bhi, b_, 0))
    r_spec = pl.BlockSpec((None, block_q, 1), lambda bhi, a, b_: (bhi, b_, 0))
    kfix_spec = pl.BlockSpec((None, block_k, d), lambda bhi, a, b_: (bhi, a, 0))

    # dQ strategy: the fused path writes fp32 per-k-block dQ partials
    # ([bh, n_kb, t, d] in HBM) so P/dS are computed once — fastest, but
    # O(n_kb * T) memory. Past _DQ_PARTIALS_MAX_KB k-blocks that tensor
    # outgrows the activations it sits next to, so long sequences take a
    # second kernel with in-VMEM dQ accumulation (O(T) memory, P
    # recomputed twice) instead.
    with_dqp = n_kb <= _DQ_PARTIALS_MAX_KB

    out_specs = [
        pl.BlockSpec((None, block_k, d), lambda bhi, a, b_: (bhi, a, 0)),
        pl.BlockSpec((None, block_k, d), lambda bhi, a, b_: (bhi, a, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((bh, t, d), k.dtype),
        jax.ShapeDtypeStruct((bh, t, d), v.dtype),
    ]
    if with_dqp:
        out_specs.append(pl.BlockSpec(
            (None, None, block_q, d), lambda bhi, a, b_: (bhi, a, b_, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((bh, n_kb, t, d), jnp.float32))

    dkv_body = functools.partial(
        _dkv_kernel, block_q=block_q, block_k=block_k, n_qb=n_qb,
        softmax_scale=softmax_scale, causal=causal, with_dqp=with_dqp,
    )
    if not with_dqp:
        # Without the dQ-partials output the ref list is one shorter.
        dkv_body = functools.partial(
            lambda body, q, g, l, dl, k, v, dk, dv, dks, dvs:
                body(q, g, l, dl, k, v, dk, dv, None, dks, dvs),
            dkv_body,
        )

    dkv_out = pl.pallas_call(
        dkv_body,
        grid=(bh, n_kb, n_qb),
        in_specs=[q_spec, q_spec, r_spec, r_spec, kfix_spec, kfix_spec],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qr, gr, lse, delta, kr, vr)

    if with_dqp:
        dk, dv, dq_part = dkv_out
        dq = jnp.sum(dq_part, axis=1).astype(q.dtype)
    else:
        dk, dv = dkv_out
        qfix_spec = pl.BlockSpec(
            (None, block_q, d), lambda bhi, a, b_: (bhi, a, 0))
        rfix_spec = pl.BlockSpec(
            (None, block_q, 1), lambda bhi, a, b_: (bhi, a, 0))
        k_spec = pl.BlockSpec(
            (None, block_k, d), lambda bhi, a, b_: (bhi, b_, 0))
        dq = pl.pallas_call(
            functools.partial(
                _dq_kernel, block_q=block_q, block_k=block_k, n_kb=n_kb,
                softmax_scale=softmax_scale, causal=causal,
            ),
            grid=(bh, n_qb, n_kb),
            in_specs=[qfix_spec, qfix_spec, rfix_spec, rfix_spec,
                      k_spec, k_spec],
            out_specs=pl.BlockSpec(
                (None, block_q, d), lambda bhi, a, b_: (bhi, a, 0)),
            out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            compiler_params=pallas_tpu_compiler_params(
                dimension_semantics=("parallel", "parallel", "arbitrary"),
            ),
            interpret=interpret,
        )(qr, gr, lse, delta, kr, vr)

    def from_bh(x):
        return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)

    return from_bh(dq), from_bh(dk), from_bh(dv)


# -- custom VJP wiring -----------------------------------------------------


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash_attention(q, k, v, block_q, block_k, softmax_scale, causal,
                     interpret):
    out, _ = _flash_fwd(
        q, k, v, block_q=block_q, block_k=block_k,
        softmax_scale=softmax_scale, causal=causal, interpret=interpret,
    )
    return out


def _vjp_fwd(q, k, v, block_q, block_k, softmax_scale, causal, interpret):
    out, lse = _flash_fwd(
        q, k, v, block_q=block_q, block_k=block_k,
        softmax_scale=softmax_scale, causal=causal, interpret=interpret,
    )
    return out, (q, k, v, out, lse)


def _vjp_bwd(block_q, block_k, softmax_scale, causal, interpret, res, g):
    q, k, v, out, lse = res
    return _flash_bwd(
        q, k, v, out, lse, g, block_q=block_q, block_k=block_k,
        softmax_scale=softmax_scale, causal=causal, interpret=interpret,
    )


_flash_attention.defvjp(_vjp_fwd, _vjp_bwd)


def flash_causal_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *,
    softmax_scale: float | None = None,
    block_q: int = 1024, block_k: int = 1024,
) -> jax.Array:
    # Default block sizes: 1024x1024 measured fastest on v5e at seq 1024
    # (4 MB fp32 score tile in VMEM; fewer grid cells beats finer causal
    # skipping — per-cell overhead dominates below ~512).
    """[B, T, H, D] causal flash attention (differentiable)."""
    b, t, h, d = q.shape
    scale = softmax_scale if softmax_scale is not None else d**-0.5
    block_q = _fit_block(block_q, t)
    block_k = _fit_block(block_k, t)
    interpret = jax.default_backend() == "cpu"
    return _flash_attention(
        q, k, v, block_q, block_k, scale, True, interpret
    )


def _fit_block(requested: int, t: int) -> int:
    """Largest divisor of t that is <= requested (so any T works, e.g.
    T=1536 -> 768 with the 1024 default). Degenerate T whose largest
    usable divisor is < 8 (primes etc.) can't tile the TPU lane layout —
    raise so `causal_attention`'s auto path falls back to XLA attention."""
    block = min(requested, t)
    while block > 1 and t % block:
        block -= 1
    if block < 8:
        raise NotImplementedError(
            f"seq len {t} has no block divisor >= 8 (<= {requested})"
        )
    return block
