"""Pallas TPU flash attention (causal) with a blockwise backward.

Forward: one Pallas kernel per (batch*head, q-block) grid cell streams K/V
blocks through VMEM with online-softmax accumulation — the [T, T] score
matrix never exists in HBM (the reason XLA attention OOMs at long T).

Backward: custom VJP that recomputes attention blockwise with `lax.scan`
over key blocks (pure XLA, fp32 accumulators). It keeps the same O(T)
memory property; the recompute trades FLOPs for HBM exactly like
`jax.checkpoint` (SURVEY.md "HBM bandwidth" note).

On CPU (tests) the kernel runs in Pallas interpret mode.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, block_q: int, block_k: int, n_kb: int,
                softmax_scale: float, causal: bool):
    """Grid (bh, q_block, k_block), k innermost: pallas double-buffers the
    K/V block DMAs while the previous block's matmuls run. Running
    (max, denom, acc) live in VMEM scratch that persists across the k
    sweep; outputs are finalized on the last k block."""
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    q_start = qi * block_q
    k_start = kb * block_k

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal skip: a k block entirely in the future contributes nothing.
    needed = (k_start <= q_start + block_q - 1) if causal else True

    @pl.when(needed)
    def _accumulate():
        q = q_ref[:].astype(jnp.float32) * softmax_scale
        k = k_ref[:].astype(jnp.float32)
        v = v_ref[:].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        if causal:
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[:] = m_new
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kb == n_kb - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[:] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[:] = m_scr[:] + jnp.log(l)


def _flash_fwd(q, k, v, *, block_q: int, block_k: int, softmax_scale: float,
               causal: bool, interpret: bool):
    """q,k,v: [B, T, H, D] -> (out [B,T,H,D], lse [B,H,T])."""
    b, t, h, d = q.shape
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    n_kb = t // block_k
    grid = (b * h, t // block_q, n_kb)
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, block_q=block_q, block_k=block_k, n_kb=n_kb,
            softmax_scale=softmax_scale, causal=causal,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi, kb: (bh, qi, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, qi, kb: (bh, kb, 0)),
            pl.BlockSpec((None, block_k, d), lambda bh, qi, kb: (bh, kb, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda bh, qi, kb: (bh, qi, 0)),
            # lse rides a trailing singleton lane dim to satisfy the TPU
            # block-tiling rule (last dim == array dim).
            pl.BlockSpec((None, block_q, 1), lambda bh, qi, kb: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, t, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(qr, kr, vr)
    out = out.reshape(b, h, t, d).transpose(0, 2, 1, 3)
    lse = lse.reshape(b, h, t)
    return out, lse


def _blockwise_bwd(q, k, v, out, lse, g, *, block_q: int,
                   softmax_scale: float, causal: bool):
    """Gradients via blockwise recompute (XLA scan over q blocks).

    Memory: O(T * block_q) scores at a time instead of O(T^2).
    """
    b, t, h, d = q.shape
    f32 = jnp.float32
    qf = q.astype(f32)
    kf = k.astype(f32)
    vf = v.astype(f32)
    gf = g.astype(f32)
    of = out.astype(f32)
    # delta = rowsum(dO * O) — the softmax-jacobian diagonal term.
    delta = jnp.einsum("bthd,bthd->bht", gf, of)

    n_q = t // block_q
    k_pos = jnp.arange(t)

    def per_qblock(carry, qi):
        dk_acc, dv_acc = carry
        qs = qi * block_q
        q_blk = jax.lax.dynamic_slice_in_dim(qf, qs, block_q, 1)
        g_blk = jax.lax.dynamic_slice_in_dim(gf, qs, block_q, 1)
        lse_blk = jax.lax.dynamic_slice_in_dim(lse, qs, block_q, 2)
        delta_blk = jax.lax.dynamic_slice_in_dim(delta, qs, block_q, 2)
        s = jnp.einsum("bqhd,bkhd->bhqk", q_blk, kf) * softmax_scale
        if causal:
            q_pos = qs + jnp.arange(block_q)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        p = jnp.exp(s - lse_blk[..., None])  # [B,H,bq,T]
        dv = jnp.einsum("bhqk,bqhd->bkhd", p, g_blk)
        dp = jnp.einsum("bqhd,bkhd->bhqk", g_blk, vf)
        ds = p * (dp - delta_blk[..., None]) * softmax_scale
        dq_blk = jnp.einsum("bhqk,bkhd->bqhd", ds, kf)
        dk = jnp.einsum("bhqk,bqhd->bkhd", ds, q_blk)
        return (dk_acc + dk, dv_acc + dv), dq_blk

    (dk, dv), dq_blocks = jax.lax.scan(
        per_qblock,
        (jnp.zeros_like(kf), jnp.zeros_like(vf)),
        jnp.arange(n_q),
    )
    # [n_q, B, bq, H, D] -> [B, T, H, D]
    dq = dq_blocks.transpose(1, 0, 2, 3, 4).reshape(b, t, h, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash_attention(q, k, v, block_q, block_k, softmax_scale, causal,
                     interpret):
    out, _ = _flash_fwd(
        q, k, v, block_q=block_q, block_k=block_k,
        softmax_scale=softmax_scale, causal=causal, interpret=interpret,
    )
    return out


def _vjp_fwd(q, k, v, block_q, block_k, softmax_scale, causal, interpret):
    out, lse = _flash_fwd(
        q, k, v, block_q=block_q, block_k=block_k,
        softmax_scale=softmax_scale, causal=causal, interpret=interpret,
    )
    return out, (q, k, v, out, lse)


def _vjp_bwd(block_q, block_k, softmax_scale, causal, interpret, res, g):
    q, k, v, out, lse = res
    return _blockwise_bwd(
        q, k, v, out, lse, g, block_q=block_q,
        softmax_scale=softmax_scale, causal=causal,
    )


_flash_attention.defvjp(_vjp_fwd, _vjp_bwd)


def flash_causal_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *,
    softmax_scale: float | None = None,
    block_q: int = 256, block_k: int = 256,
) -> jax.Array:
    """[B, T, H, D] causal flash attention (differentiable)."""
    b, t, h, d = q.shape
    scale = softmax_scale if softmax_scale is not None else d**-0.5
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    if t % block_q or t % block_k:
        raise NotImplementedError(
            f"seq len {t} must be divisible by block sizes ({block_q},{block_k})"
        )
    interpret = jax.default_backend() == "cpu"
    return _flash_attention(
        q, k, v, block_q, block_k, scale, True, interpret
    )
