"""Mixture-of-Experts layer with expert parallelism.

SURVEY.md §2.4: expert-axis sharding + all_to_all dispatch — absent from
the reference, first-class here. Design (switch-style top-1 / top-2):

  * router: [tokens, E] logits -> top-k experts per token + combine weights;
  * capacity: each expert takes at most C = capacity_factor * tokens/E
    tokens per device shard; overflow tokens are dropped (standard switch
    behavior) — keeps shapes static for XLA;
  * dispatch: one-hot combine matrices turn gather/scatter into einsums
    (MXU-friendly; no dynamic shapes);
  * expert parallelism: experts shard over mesh axis ``ep``; the dispatch
    einsum's tokens flow through ``all_to_all`` so each device computes
    only its local experts' FFNs.

The dense path (``moe_ffn``) works on any mesh; ``moe_ffn_ep`` adds the
all_to_all when an ``ep`` axis exists.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from ray_tpu._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def init_moe_params(rng, d_model: int, d_ff: int, n_experts: int,
                    dtype=jnp.float32) -> dict:
    k1, k2, k3 = jax.random.split(rng, 3)
    std = 0.02
    return {
        "router": (jax.random.normal(k1, (d_model, n_experts)) * std).astype(dtype),
        "w_in": (jax.random.normal(k2, (n_experts, d_model, d_ff)) * std).astype(dtype),
        "w_out": (jax.random.normal(k3, (n_experts, d_ff, d_model)) * std).astype(dtype),
    }


def moe_param_axes() -> dict:
    """Logical axes: experts shard over ep; ffn dim over tp."""
    return {
        "router": ("embed", None),
        "w_in": ("experts", "embed", "mlp"),
        "w_out": ("experts", "mlp", "embed"),
    }


def _route(x2d, router_w, n_experts, top_k, capacity):
    """Returns (dispatch [T, E, C] one-hot, combine [T, E, C] weights,
    aux_loss). Shapes static; overflow dropped."""
    logits = x2d.astype(jnp.float32) @ router_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)  # [T, E]
    t = x2d.shape[0]

    gates, experts = jax.lax.top_k(probs, top_k)  # [T, k]
    if top_k > 1:
        # GShard-style top-k gating: renormalize over the selected experts
        # so the combined output isn't attenuated by dropped probability
        # mass (sum of selected gates == 1).
        gates = gates / (jnp.sum(gates, axis=-1, keepdims=True) + 1e-9)
    # Load-balancing auxiliary loss (Switch Transformer eq. 4).
    density = jnp.mean(probs, axis=0)
    top1_mask = jax.nn.one_hot(experts[:, 0], n_experts)
    density_proxy = jnp.mean(top1_mask, axis=0)
    aux_loss = n_experts * jnp.sum(density * density_proxy)

    dispatch = jnp.zeros((t, n_experts, capacity), jnp.float32)
    combine = jnp.zeros((t, n_experts, capacity), jnp.float32)
    # Position of each token within its expert's capacity buffer: running
    # per-expert counts across the k routing slots keep positions unique.
    counts = jnp.zeros((n_experts,), jnp.float32)
    for j in range(top_k):
        onehot = jax.nn.one_hot(experts[:, j], n_experts)  # [T, E]
        prior = jnp.cumsum(onehot, axis=0) - onehot + counts[None, :]
        pos = jnp.sum(prior * onehot, axis=1).astype(jnp.int32)  # [T]
        counts = counts + jnp.sum(onehot, axis=0)
        keep = pos < capacity
        pos_oh = jax.nn.one_hot(pos, capacity)  # [T, C]
        sel = (onehot * keep[:, None])[:, :, None] * pos_oh[:, None, :]
        dispatch = dispatch + sel
        combine = combine + sel * gates[:, j][:, None, None]
    return dispatch, combine, aux_loss


def moe_ffn(params: dict, x: jax.Array, *, top_k: int = 1,
            capacity_factor: float = 1.25,
            activation=jax.nn.gelu) -> tuple[jax.Array, jax.Array]:
    """Dense-mesh MoE FFN. x: [B, T, D] -> ([B, T, D], aux_loss).

    All experts computed on every device (XLA partitions the expert einsum
    by the param shardings); for explicit expert parallelism use
    ``moe_ffn_ep``.
    """
    b, t, d = x.shape
    e = params["router"].shape[1]
    x2d = x.reshape(b * t, d)
    capacity = max(1, int(capacity_factor * (b * t) / e))
    dispatch, combine, aux = _route(x2d, params["router"], e, top_k, capacity)
    # [E, C, D] expert inputs via einsum dispatch.
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x2d.astype(jnp.float32))
    h = jnp.einsum("ecd,edf->ecf", expert_in, params["w_in"].astype(jnp.float32))
    h = activation(h)
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["w_out"].astype(jnp.float32))
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    return out.reshape(b, t, d).astype(x.dtype), aux


def moe_ffn_ep(params: dict, x: jax.Array, mesh: Mesh, *,
               axis: str = "ep", top_k: int = 1,
               capacity_factor: float = 1.25,
               activation=jax.nn.gelu,
               batch_axes=("dp", "fsdp")) -> tuple[jax.Array, jax.Array]:
    """Expert-parallel MoE: tokens all_to_all to their experts' devices.

    x: [B, T, D] with B sharded over batch_axes; experts sharded over
    ``axis``. Per-device: route locally, all_to_all token buffers so each
    device holds only its E/ep experts' inputs, compute FFN, route back.
    """
    ep = mesh.shape[axis]
    e = params["router"].shape[1]
    if e % ep:
        raise ValueError(f"n_experts {e} must divide by ep={ep}")

    local = functools.partial(
        moe_ffn_ep_local, n_experts=e, axis=axis, top_k=top_k,
        capacity_factor=capacity_factor, activation=activation,
    )
    xspec = P(batch_axes, None, None)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(xspec, P(), P(axis, None, None), P(axis, None, None)),
        out_specs=(xspec, P()),
        check_vma=False,
    )
    return fn(x, params["router"], params["w_in"], params["w_out"])


def moe_ffn_ep_local(px, p_router, p_win, p_wout, *, n_experts: int,
                     axis: str = "ep", top_k: int = 1,
                     capacity_factor: float = 1.25,
                     activation=jax.nn.gelu):
    """Per-device expert-parallel FFN body — usable inside ANY shard_map
    whose mesh has an ``axis`` dimension (e.g. a pipeline stage under the
    ``pp`` shard_map), not just the one ``moe_ffn_ep`` builds. w_in/w_out
    carry this device's E/ep expert slices; the router is replicated."""
    e = n_experts
    b, t, d = px.shape
    x2d = px.reshape(b * t, d)
    capacity = max(1, int(capacity_factor * (b * t) / e))
    dispatch, combine, aux = _route(x2d, p_router, e, top_k, capacity)
    # [E, C, D] on this device -> exchange so device i holds expert
    # rows for its local experts from ALL devices' tokens:
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x2d.astype(jnp.float32))
    # [E, C, D] -> [E/ep, ep*C, D]: split experts, concat capacity.
    expert_in = jax.lax.all_to_all(
        expert_in, axis, split_axis=0, concat_axis=1, tiled=True
    )
    h = activation(jnp.einsum(
        "ecd,edf->ecf", expert_in, p_win.astype(jnp.float32)
    ))
    expert_out = jnp.einsum("ecf,efd->ecd", h, p_wout.astype(jnp.float32))
    # Route back: [E/ep, ep*C, D] -> [E, C, D].
    expert_out = jax.lax.all_to_all(
        expert_out, axis, split_axis=1, concat_axis=0, tiled=True
    )
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    aux = jax.lax.pmean(aux, axis)
    return out.reshape(b, t, d).astype(px.dtype), aux
