"""Ulysses sequence parallelism: all-to-all head scatter.

The second SP scheme (SURVEY.md §2.4/§5.7): inputs arrive sequence-sharded
[B, T/sp, H, D]; an ``all_to_all`` over the ``sp`` axis re-shards to
head-sharded [B, T, H/sp, D], each device runs FULL-sequence attention on
its head subset (any kernel — XLA or flash), and a second all_to_all
restores sequence sharding. Two collectives bound the whole exchange, vs
sp ppermutes for ring attention; preferable when H >= sp and the ICI
all-to-all bandwidth is good (intra-slice).
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
from ray_tpu._compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import xla_causal_attention


def ulysses_attention_local(q, k, v, *, axis: str = "sp",
                            attn_fn: Callable = xla_causal_attention,
                            softmax_scale: float | None = None):
    """Per-device body (call inside shard_map over ``axis``).

    q/k/v: [B, T/sp, H, D] -> out [B, T/sp, H, D].
    """

    def scatter_heads(x):
        # [B, C, H, D] -> [B, sp*C, H/sp, D]: split heads, gather sequence.
        return jax.lax.all_to_all(
            x, axis, split_axis=2, concat_axis=1, tiled=True
        )

    def gather_heads(x):
        return jax.lax.all_to_all(
            x, axis, split_axis=1, concat_axis=2, tiled=True
        )

    qh, kh, vh = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    out = attn_fn(qh, kh, vh, softmax_scale=softmax_scale)
    return gather_heads(out)


def ulysses_attention(q, k, v, mesh: Mesh, *, axis: str = "sp",
                      attn_fn: Callable = xla_causal_attention,
                      softmax_scale: float | None = None,
                      batch_axes=("dp", "fsdp")):
    """Full-array entry: [B, T, H, D] with T sharded over ``axis``."""
    if q.shape[2] % mesh.shape[axis]:
        raise ValueError(
            f"n_heads {q.shape[2]} must divide by sp={mesh.shape[axis]}"
        )
    spec = P(batch_axes, axis, None, None)
    fn = shard_map(
        functools.partial(
            ulysses_attention_local, axis=axis, attn_fn=attn_fn,
            softmax_scale=softmax_scale,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
