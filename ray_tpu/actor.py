"""Actor classes and handles (reference: ``python/ray/actor.py:377,657,1020``)."""

from __future__ import annotations

from typing import Any

from ray_tpu._private import worker as _worker
from ray_tpu._private.options import validate_actor_options


class ActorMethod:
    """Bound method proxy: ``handle.method.remote(args)``."""

    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1,
                 concurrency_group: str | None = None):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def remote(self, *args, **kwargs):
        extra = {}
        if self._concurrency_group is not None:
            extra["concurrency_group"] = self._concurrency_group
        refs = _worker.backend().submit_actor_task(
            self._handle._actor_id,
            self._method_name,
            args,
            kwargs,
            num_returns=self._num_returns,
            **extra,
        )
        return refs[0] if self._num_returns == 1 else refs

    def options(self, num_returns: int = 1,
                concurrency_group: str | None = None) -> "ActorMethod":
        """Per-call overrides (reference ``@ray.method`` options):
        ``concurrency_group`` routes the call to one of the actor's
        declared executor groups instead of the default queue."""
        if not isinstance(num_returns, int) or isinstance(num_returns, bool):
            raise ValueError(
                "actor methods do not support streaming returns; "
                f"num_returns must be an int, got {num_returns!r}"
            )
        return ActorMethod(self._handle, self._method_name, num_returns,
                           concurrency_group)


class ActorHandle:
    def __init__(self, actor_id: str, class_name: str = "Actor"):
        self._actor_id = actor_id
        self._class_name = class_name

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self) -> str:
        return f"ActorHandle({self._class_name}, {self._actor_id[:12]}…)"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name))


class ActorClass:
    def __init__(self, cls: type, options: dict[str, Any] | None = None):
        self._cls = cls
        self._options = validate_actor_options(options or {})

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._cls.__name__} cannot be instantiated directly; "
            f"use {self._cls.__name__}.remote()."
        )

    def remote(self, *args, **kwargs) -> ActorHandle:
        actor_id = _worker.backend().create_actor(
            self._cls, args, kwargs, **self._options
        )
        return ActorHandle(actor_id, self._cls.__name__)

    def options(self, **new_options) -> "ActorClass":
        merged = {**self._options, **validate_actor_options(new_options)}
        return ActorClass(self._cls, merged)

    @property
    def cls(self) -> type:
        return self._cls
