"""ray_tpu — a TPU-native distributed compute framework.

Capability parity target: the Ray framework (reference analyzed in SURVEY.md) —
tasks, actors, immutable distributed objects, placement groups, and an ML
library stack (train/tune/data/serve/rllib) — rebuilt TPU-first:

* The **tensor plane is XLA**: collectives ride ICI via ``psum``/``ppermute``/
  ``all_to_all`` inside jitted step functions over a `jax.sharding.Mesh`,
  instead of NCCL/Gloo between worker processes (reference:
  ``python/ray/util/collective/collective_group/nccl_collective_group.py``).
* The **control plane** mirrors Ray's GCS + raylet + core-worker split
  (reference: ``src/ray/gcs``, ``src/ray/raylet``, ``src/ray/core_worker``)
  with a head metadata service, per-node daemon with a worker pool, and an
  in-process core runtime per driver/worker.
* The **resource model is topology-aware**: TPU slices, hosts and chips are
  first-class, and placement groups understand ICI contiguity.

Public API (mirrors reference ``python/ray/__init__.py``):
    ray_tpu.init / shutdown
    @ray_tpu.remote        -> RemoteFunction / ActorClass
    ray_tpu.get / put / wait
    ray_tpu.get_actor, ray_tpu.kill, ray_tpu.cancel
"""

from ray_tpu.version import __version__

from ray_tpu.core.object_ref import (
    ActorError,
    ObjectRefGenerator,
    GetTimeoutError,
    ObjectLostError,
    OutOfMemoryError,
    TaskCancelledError,
    TaskError,
)
from ray_tpu import cross_language
from ray_tpu.api import (
    ObjectRef,
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    remote,
    shutdown,
    wait,
)

__all__ = [
    "__version__",
    "cross_language",
    "ActorError",
    "ObjectRefGenerator",
    "GetTimeoutError",
    "ObjectLostError",
    "ObjectRef",
    "OutOfMemoryError",
    "TaskCancelledError",
    "TaskError",
    "available_resources",
    "cancel",
    "cluster_resources",
    "get",
    "get_actor",
    "init",
    "is_initialized",
    "kill",
    "nodes",
    "put",
    "remote",
    "shutdown",
    "wait",
]
