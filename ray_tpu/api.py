"""Public task/actor/object API (reference: ``python/ray/_private/worker.py``
``init:1031, get:2242, put:2335, wait:2391, get_actor:2508``)."""

from __future__ import annotations

import inspect
from typing import Any, Sequence

from ray_tpu._private import worker as _worker
from ray_tpu.actor import ActorClass, ActorHandle
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.remote_function import RemoteFunction


def init(address: str | None = None, **kwargs):
    """Connect this process to a runtime.

    address=None -> in-process backend (single node).
    address="tcp://host:port" -> cluster backend (control-plane address).
    """
    return _worker.init(address, **kwargs)


def is_initialized() -> bool:
    return _worker.is_initialized()


def shutdown():
    _worker.shutdown()


def remote(*args, **options):
    """``@remote`` decorator for functions and classes, with or without args."""

    def wrap(target):
        if inspect.isclass(target):
            return ActorClass(target, options)
        if callable(target):
            return RemoteFunction(target, options)
        raise TypeError(f"@remote target must be a function or class: {target}")

    if len(args) == 1 and not options and (inspect.isclass(args[0]) or callable(args[0])):
        return wrap(args[0])
    if args:
        raise TypeError("@remote() takes keyword options only")
    return wrap


def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("Calling put() on an ObjectRef is not allowed")
    return _worker.backend().put(value)


def get(refs, timeout: float | None = None):
    single = isinstance(refs, ObjectRef)
    if single:
        refs = [refs]
    refs = list(refs)
    if not all(isinstance(r, ObjectRef) for r in refs):
        raise TypeError("get() accepts an ObjectRef or a list of ObjectRefs")
    values = _worker.backend().get(refs, timeout)
    return values[0] if single else values


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: float | None = None,
    fetch_local: bool = True,
):
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    if num_returns > len(refs):
        raise ValueError(f"num_returns={num_returns} > len(refs)={len(refs)}")
    return _worker.backend().wait(list(refs), num_returns, timeout, fetch_local)


def get_actor(name: str) -> ActorHandle:
    actor_id = _worker.backend().get_named_actor(name)
    return ActorHandle(actor_id, name)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    _worker.backend().kill_actor(actor._actor_id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False):
    _worker.backend().cancel(ref, force)


def cluster_resources() -> dict:
    return _worker.backend().cluster_resources()


def available_resources() -> dict:
    return _worker.backend().available_resources()


def nodes() -> list[dict]:
    return _worker.backend().nodes()
