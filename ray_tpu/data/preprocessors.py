"""Preprocessors: fit on a Dataset, transform Datasets/batches.

Reference parity: ``python/ray/data/preprocessors/`` — the fit/transform
contract of ``Preprocessor``, with the most-used concrete ones
(StandardScaler, MinMaxScaler, LabelEncoder, Concatenator, BatchMapper,
Chain).
"""

from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np


class Preprocessor:
    _fitted = False

    def fit(self, ds) -> "Preprocessor":
        self._fit(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} must be fit first")
        return ds.map_batches(self._transform_batch, batch_format="numpy")

    def fit_transform(self, ds):
        return self.fit(ds).transform(ds)

    def transform_batch(self, batch: dict) -> dict:
        if not self._fitted and self._needs_fit():
            raise RuntimeError(f"{type(self).__name__} must be fit first")
        return self._transform_batch(batch)

    def _needs_fit(self) -> bool:
        return True

    def _fit(self, ds) -> None:
        pass

    def _transform_batch(self, batch: dict) -> dict:
        raise NotImplementedError


class BatchMapper(Preprocessor):
    def __init__(self, fn: Callable[[dict], dict]):
        self.fn = fn

    def _needs_fit(self) -> bool:
        return False

    def _transform_batch(self, batch: dict) -> dict:
        return self.fn(batch)


class StandardScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: dict = {}

    def _fit(self, ds) -> None:
        sums = {c: (0.0, 0.0, 0) for c in self.columns}
        for batch in ds.iter_batches(batch_format="numpy"):
            for c in self.columns:
                v = np.asarray(batch[c], dtype=np.float64)
                s, sq, n = sums[c]
                sums[c] = (s + v.sum(), sq + (v * v).sum(), n + v.size)
        for c, (s, sq, n) in sums.items():
            mean = s / n
            var = max(sq / n - mean * mean, 0.0)
            self.stats_[c] = (mean, np.sqrt(var) or 1.0)

    def _transform_batch(self, batch: dict) -> dict:
        out = dict(batch)
        for c in self.columns:
            mean, std = self.stats_[c]
            out[c] = (np.asarray(batch[c], np.float64) - mean) / std
        return out


class MinMaxScaler(Preprocessor):
    def __init__(self, columns: List[str]):
        self.columns = columns
        self.stats_: dict = {}

    def _fit(self, ds) -> None:
        mins = {c: np.inf for c in self.columns}
        maxs = {c: -np.inf for c in self.columns}
        for batch in ds.iter_batches(batch_format="numpy"):
            for c in self.columns:
                v = np.asarray(batch[c], dtype=np.float64)
                mins[c] = min(mins[c], v.min())
                maxs[c] = max(maxs[c], v.max())
        for c in self.columns:
            span = maxs[c] - mins[c]
            self.stats_[c] = (mins[c], span if span else 1.0)

    def _transform_batch(self, batch: dict) -> dict:
        out = dict(batch)
        for c in self.columns:
            lo, span = self.stats_[c]
            out[c] = (np.asarray(batch[c], np.float64) - lo) / span
        return out


class LabelEncoder(Preprocessor):
    def __init__(self, label_column: str):
        self.label_column = label_column
        self.classes_: Optional[list] = None

    def _fit(self, ds) -> None:
        seen = set()
        for batch in ds.iter_batches(batch_format="numpy"):
            seen.update(np.asarray(batch[self.label_column]).tolist())
        self.classes_ = sorted(seen)

    def _transform_batch(self, batch: dict) -> dict:
        idx = {c: i for i, c in enumerate(self.classes_)}
        out = dict(batch)
        out[self.label_column] = np.asarray(
            [idx[v] for v in np.asarray(batch[self.label_column]).tolist()]
        )
        return out


class Concatenator(Preprocessor):
    """Merge feature columns into one float matrix column (the standard
    last step before tensor ingest)."""

    def __init__(self, output_column_name: str = "concat_out",
                 exclude: Optional[List[str]] = None, dtype=np.float32):
        self.output_column_name = output_column_name
        self.exclude = set(exclude or [])
        self.dtype = dtype

    def _needs_fit(self) -> bool:
        return False

    def _transform_batch(self, batch: dict) -> dict:
        cols = [c for c in batch if c not in self.exclude]
        mat = np.stack(
            [np.asarray(batch[c], dtype=self.dtype) for c in sorted(cols)],
            axis=-1,
        )
        out = {c: batch[c] for c in self.exclude}
        out[self.output_column_name] = mat
        return out


class Chain(Preprocessor):
    def __init__(self, *stages: Preprocessor):
        self.stages = list(stages)

    def fit(self, ds) -> "Chain":
        for stage in self.stages:
            stage.fit(ds)
            ds = stage.transform(ds)
        self._fitted = True
        return self

    def transform(self, ds):
        for stage in self.stages:
            ds = stage.transform(ds)
        return ds

    def _transform_batch(self, batch: dict) -> dict:
        for stage in self.stages:
            batch = stage.transform_batch(batch)
        return batch
