"""Blocks: the unit of distributed data (reference: ``python/ray/data/block.py``).

Three physical layouts, mirroring the reference's simple vs Arrow blocks:
  * list block — ``list`` of rows (arbitrary Python objects / dicts);
  * columnar block — ``dict[str, np.ndarray]`` (tensor-friendly);
  * arrow block — ``pyarrow.Table`` (the reference's default block type;
    zero-copy through the shm object store — Arrow buffers ride the
    pickle-5 out-of-band path like numpy arrays do).

``BlockAccessor``-style helpers are plain functions here.
"""

from __future__ import annotations

from typing import Any, Iterable, List, Union

import numpy as np

Block = Union[List[Any], dict, "pyarrow.Table"]


def is_arrow(block: Block) -> bool:
    # Cheap check without importing pyarrow for non-arrow blocks.
    return type(block).__module__.startswith("pyarrow")


def is_columnar(block: Block) -> bool:
    return isinstance(block, dict)


def num_rows(block: Block) -> int:
    if is_arrow(block):
        return block.num_rows
    if is_columnar(block):
        if not block:
            return 0
        return len(next(iter(block.values())))
    return len(block)


def size_bytes(block: Block) -> int:
    """Approximate in-memory bytes of a block (exact for arrow/columnar
    via nbytes; list blocks are sampled — the stats plane wants
    distribution shape, not an accountant)."""
    if is_arrow(block):
        return int(block.nbytes)
    if is_columnar(block):
        return int(sum(getattr(v, "nbytes", 0) for v in block.values()))
    import sys as _sys

    n = len(block)
    if n == 0:
        return 0
    k = min(n, 64)
    sampled = sum(_sys.getsizeof(r) for r in block[:k])
    return int(sampled * n / k)


def slice_block(block: Block, start: int, end: int) -> Block:
    if is_arrow(block):
        return block.slice(start, end - start)
    if is_columnar(block):
        return {k: v[start:end] for k, v in block.items()}
    return block[start:end]


def split_block(block: Block, target_bytes: int) -> List[Block]:
    """Dynamic block splitting: row-range slices of a block whose
    estimated size exceeds ``target_bytes``, each at most ~target-sized.
    Slices are views (arrow ``slice`` / numpy basic indexing), so the
    split itself copies nothing — the pieces only become independent
    bytes when they are serialized into the store as separate objects.
    A block at or under target (or with a single row) passes through
    unsplit."""
    n = num_rows(block)
    total = size_bytes(block)
    if target_bytes <= 0 or n <= 1 or total <= target_bytes:
        return [block]
    parts = min(n, -(-total // target_bytes))  # ceil division
    cuts = [round(i * n / parts) for i in range(parts + 1)]
    return [
        slice_block(block, cuts[i], cuts[i + 1])
        for i in range(parts)
        if cuts[i + 1] > cuts[i]
    ]


def concat_blocks(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if num_rows(b) > 0]
    if not blocks:
        return []
    if is_arrow(blocks[0]):
        import pyarrow as pa

        return pa.concat_tables(blocks)
    if is_columnar(blocks[0]):
        keys = blocks[0].keys()
        return {k: np.concatenate([b[k] for b in blocks]) for k in keys}
    out: list = []
    for b in blocks:
        out.extend(b)
    return out


def rows_of(block: Block) -> Iterable[Any]:
    if is_arrow(block):
        yield from block.to_pylist()
    elif is_columnar(block):
        keys = list(block.keys())
        for i in range(num_rows(block)):
            yield {k: block[k][i] for k in keys}
    else:
        yield from block


def from_rows(rows: List[Any], like: Block) -> Block:
    """Rebuild a block from rows, keeping the input layout when possible."""
    if is_arrow(like) and rows and isinstance(rows[0], dict):
        import pyarrow as pa

        return pa.Table.from_pylist(rows)
    if is_columnar(like) and rows and isinstance(rows[0], dict):
        keys = rows[0].keys()
        return {k: np.asarray([r[k] for r in rows]) for k in keys}
    return list(rows)


def to_batch(block: Block, batch_format: str):
    """Materialize a block in the requested batch format
    (``iter_batches(batch_format=...)`` parity: numpy / pandas / default)."""
    if batch_format in ("default", "native"):
        return block
    if batch_format == "numpy":
        if is_arrow(block):
            return {
                name: block.column(name).to_numpy(zero_copy_only=False)
                for name in block.column_names
            }
        if is_columnar(block):
            return block
        if block and isinstance(block[0], dict):
            keys = block[0].keys()
            return {k: np.asarray([r[k] for r in block]) for k in keys}
        return np.asarray(block)
    if batch_format == "pandas":
        import pandas as pd

        if is_arrow(block):
            return block.to_pandas()
        if is_columnar(block):
            return pd.DataFrame({k: list(v) for k, v in block.items()})
        if block and isinstance(block[0], dict):
            return pd.DataFrame(block)
        return pd.DataFrame({"value": block})
    if batch_format == "pyarrow":
        import pyarrow as pa

        if is_arrow(block):
            return block
        if is_columnar(block):
            return pa.table({k: np.asarray(v) for k, v in block.items()})
        if block and isinstance(block[0], dict):
            return pa.Table.from_pylist(list(block))
        return pa.table({"value": list(block)})
    raise ValueError(f"unknown batch_format {batch_format!r}")


def from_batch(batch) -> Block:
    """Normalize a user-returned batch back into a block. Arrow tables
    stay Arrow (the block type is preserved end to end)."""
    import pandas as pd

    if is_arrow(batch):
        return batch
    if isinstance(batch, pd.DataFrame):
        return {k: batch[k].to_numpy() for k in batch.columns}
    if isinstance(batch, dict):
        return {k: np.asarray(v) for k, v in batch.items()}
    if isinstance(batch, np.ndarray):
        return list(batch)
    if isinstance(batch, list):
        return batch
    raise TypeError(f"cannot convert batch of type {type(batch)} to a block")


def schema_of(block: Block):
    if is_arrow(block):
        return block.schema
    if is_columnar(block):
        return {k: v.dtype for k, v in block.items()}
    if block and isinstance(block[0], dict):
        return {k: type(v).__name__ for k, v in block[0].items()}
    return type(block[0]).__name__ if block else None
