"""Data library: distributed datasets over object-store blocks.

Reference parity: ``python/ray/data`` (SURVEY.md §2.3) — lazy plans with
stage fusion, all-to-all shuffles, equal splits for Train ingest, actor-pool
compute, preprocessors — built purely on tasks/actors/objects, with a
TPU-native device-feeding path (``iter_device_batches``).
"""

from ray_tpu._private.usage import record_library_usage as _rlu
_rlu("data")

from ray_tpu.data.dataset import (
    ActorPoolStrategy,
    Dataset,
    from_items,
    from_arrow,
    from_numpy,
    from_pandas,
    range,
    range_tensor,
    read_binary_files,
    read_csv,
    read_json,
    read_parquet,
    read_text,
)
from ray_tpu.data.dataset_pipeline import DatasetPipeline
from ray_tpu.data import preprocessors

__all__ = [
    "ActorPoolStrategy",
    "Dataset",
    "DatasetPipeline",
    "from_items",
    "from_arrow",
    "from_numpy",
    "from_pandas",
    "range",
    "range_tensor",
    "read_binary_files",
    "read_csv",
    "read_json",
    "read_parquet",
    "read_text",
    "preprocessors",
]
