"""Dataset: lazy distributed data over object-store blocks.

Reference parity (``python/ray/data/dataset.py:139``):
  * blocks live in the object store as ObjectRefs; transformations are
    ``@remote`` tasks over blocks (``_internal/remote_fn.py`` invariant);
  * the plan is LAZY with stage fusion — consecutive one-to-one stages run
    as a single task per block (``_internal/plan.py:288``);
  * all-to-all ops (shuffle / sort / repartition) follow the two-phase
    map+reduce shape of the push-based shuffle
    (``_internal/push_based_shuffle.py``);
  * ``split(equal=True)`` yields row-balanced per-worker shards
    (``_internal/equalize.py``) for Train ingestion;
  * compute strategies: task pool (default) or an actor pool
    (``_internal/compute.py:58,173``).

TPU addition: ``iter_device_batches`` — double-buffered host->HBM feeding
of jax arrays with a target sharding.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Iterable, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import block as B

_py_range = range  # the builtin; the public range() API below shadows it


def _remote_apply(fns, blk):
    """One task: run the fused chain of block fns."""
    for fn in fns:
        blk = fn(blk)
    return blk


def _remote_apply_meta(fns, blk):
    """One task: run the fused chain AND measure it. Returns
    ``[block, meta]`` (two object refs via ``num_returns=2`` — the
    per-block stats never ride the block itself, so downstream
    consumers see blocks, not tuples)."""
    t0 = time.perf_counter()
    for fn in fns:
        blk = fn(blk)
    meta = {
        "duration_s": time.perf_counter() - t0,
        "rows": B.num_rows(blk),
        "bytes": B.size_bytes(blk),
    }
    return [blk, meta]


def _remote_apply_split(name, fns, blk, target):
    """Streaming task: run the fused chain, then DYNAMIC BLOCK
    SPLITTING — an output bigger than ``target`` bytes yields as N
    store-friendly blocks (each its own object, stored as produced) so
    one skewed multi-GiB block never lands in the store whole. The
    LAST yielded item is the stage meta dict (the driver pops it off;
    the per-task stats never ride a data block)."""
    t0 = time.perf_counter()
    for fn in fns:
        blk = fn(blk)
    parts = B.split_block(blk, target)
    meta = {
        "duration_s": time.perf_counter() - t0,
        "rows": B.num_rows(blk),
        "bytes": B.size_bytes(blk),
        "splits": len(parts) - 1,
        # Per-part counts, in yield order: the driver caches them so
        # count()/split(equal=True) never re-derive rows with a
        # task-per-block fan-out.
        "part_rows": [B.num_rows(p) for p in parts],
    }
    if len(parts) > 1:
        gp = _goodput()
        if gp is not None:
            try:
                gp.record_block_split(name, len(parts) - 1)
            except Exception:
                pass
    for p in parts:
        yield p
    yield meta


class _Stage:
    """One-to-one stage: fuseable block -> block function."""

    def __init__(self, name: str, fn: Callable):
        self.name = name
        self.fn = fn


def _goodput():
    """The shared goodput recording plane (never a hard dependency:
    stats objects must work even if the metrics plane is broken)."""
    try:
        from ray_tpu.util import goodput

        return goodput
    except Exception:
        return None


class StageStats:
    """One executed stage: total wall time plus per-block duration and
    size (rows/bytes) distributions. Block samples are BOUNDED
    (``DatasetStats.MAX_BLOCK_SAMPLES``); totals stay exact."""

    __slots__ = ("name", "wall_s", "n_blocks", "block_seconds",
                 "block_rows", "block_bytes", "rows_total",
                 "bytes_total", "sampled", "extra")

    def __init__(self, name: str, wall_s: float, n_blocks: int,
                 blocks: Optional[list] = None, max_samples: int = 256,
                 extra: Optional[dict] = None):
        self.name = name
        self.wall_s = float(wall_s)
        self.n_blocks = int(n_blocks)
        # Stage-shape facts that aren't per-block samples: dynamic
        # split count, autoscaling-pool peak size / scale events.
        self.extra: dict = dict(extra or {})
        self.block_seconds: List[float] = []
        self.block_rows: List[int] = []
        self.block_bytes: List[int] = []
        self.rows_total = 0
        self.bytes_total = 0
        self.sampled = False  # True when samples were clipped
        for i, (dur, rows, nbytes) in enumerate(blocks or ()):
            self.rows_total += int(rows)
            self.bytes_total += int(nbytes)
            if i < max_samples:
                if dur is not None:  # None = duration unknown (pool)
                    self.block_seconds.append(float(dur))
                self.block_rows.append(int(rows))
                self.block_bytes.append(int(nbytes))
            else:
                self.sampled = True

    @property
    def rows_per_s(self) -> float:
        return self.rows_total / self.wall_s if self.wall_s > 0 else 0.0

    @property
    def bytes_per_s(self) -> float:
        return self.bytes_total / self.wall_s if self.wall_s > 0 else 0.0

    def _dist(self, vals: list) -> Optional[dict]:
        if not vals:
            return None
        from ray_tpu.util.metrics import percentile

        s = sorted(vals)
        return {"min": s[0], "p50": percentile(s, 0.5),
                "max": s[-1], "mean": sum(s) / len(s)}

    def to_dict(self) -> dict:
        out = {
            "name": self.name,
            "wall_s": round(self.wall_s, 6),
            "n_blocks": self.n_blocks,
            "rows_total": self.rows_total,
            "bytes_total": self.bytes_total,
            "rows_per_s": round(self.rows_per_s, 1),
            "bytes_per_s": round(self.bytes_per_s, 1),
            "sampled": self.sampled,
        }
        if self.extra:
            out.update(self.extra)
        for key, vals in (("block_seconds", self.block_seconds),
                          ("block_rows", self.block_rows),
                          ("block_bytes", self.block_bytes)):
            d = self._dist(vals)
            if d:
                out[key] = d
        return out

    def summary_lines(self, index: int) -> List[str]:
        # First line keeps the pre-v2 string format verbatim (callers
        # grep it); detail lines are indented below.
        lines = [f"stage {index}: {self.name} — {self.wall_s * 1000:.1f}"
                 f" ms over {self.n_blocks} blocks"]
        if self.rows_total or self.bytes_total:
            lines.append(
                f"    {self.rows_total} rows, {self.bytes_total} bytes "
                f"({self.rows_per_s:,.0f} rows/s, "
                f"{self.bytes_per_s / 1e6:,.1f} MB/s)")
        d = self._dist(self.block_seconds)
        if d:
            clipped = " (sampled)" if self.sampled else ""
            lines.append(
                f"    per-block: min {d['min'] * 1e3:.2f} / p50 "
                f"{d['p50'] * 1e3:.2f} / max {d['max'] * 1e3:.2f} ms"
                f"{clipped}")
        if self.extra:
            lines.append("    " + ", ".join(
                f"{k}={v}" for k, v in sorted(self.extra.items())))
        return lines


class IterationStats:
    """One consumer loop over ``iter_batches``/``iter_device_batches``:
    data-wait vs consumer time, host->device transfer seconds, prefetch
    occupancy, and the derived stall fraction."""

    __slots__ = ("batches", "wait_s", "user_s", "transfer_s",
                 "occupancy", "device")

    def __init__(self, device: bool = False):
        self.batches = 0
        self.wait_s = 0.0
        self.user_s = 0.0
        self.transfer_s = 0.0
        self.occupancy: List[int] = []
        self.device = device

    @property
    def stall_fraction(self) -> float:
        denom = self.wait_s + self.user_s
        return self.wait_s / denom if denom > 0 else 0.0

    def to_dict(self) -> dict:
        out = {
            "batches": self.batches,
            "wait_s": round(self.wait_s, 6),
            "user_s": round(self.user_s, 6),
            "stall_fraction": round(self.stall_fraction, 4),
        }
        if self.device:
            out["transfer_s"] = round(self.transfer_s, 6)
        if self.occupancy:
            out["mean_occupancy"] = round(
                sum(self.occupancy) / len(self.occupancy), 2)
        return out

    def summary_line(self) -> str:
        extra = (f", transfer {self.transfer_s * 1e3:.1f} ms"
                 if self.device else "")
        return (f"iterator: {self.batches} batches, stall "
                f"{self.stall_fraction:.1%} (wait "
                f"{self.wait_s * 1e3:.1f} ms / user "
                f"{self.user_s * 1e3:.1f} ms{extra})")


class DatasetStats:
    """Structured execution stats (v2). Derived datasets hold a
    parent-LINKED child (never a shared mutable object — pre-v2, every
    ``split``/``repartition``/``union``/``map_batches`` result aliased
    one stats object, so one branch's stage records polluted its
    siblings and the stage list grew without bound across reuse).

    ``Dataset.stats()`` returns this object; ``summary()`` (also
    ``str()``/``in``) keeps the old per-stage string format as its
    first line per stage."""

    MAX_BLOCK_SAMPLES = 256
    MAX_STAGES = 64
    MAX_ITERATIONS = 16

    def __init__(self, parents: Optional[List["DatasetStats"]] = None):
        self.stages: List[StageStats] = []
        self.parents: List["DatasetStats"] = list(parents or [])
        self.dropped_stages = 0
        self.iterations: List[IterationStats] = []

    def child(self, *extra_parents: "DatasetStats") -> "DatasetStats":
        return DatasetStats(parents=[self, *extra_parents])

    def record(self, name, seconds, n_blocks, blocks=None, extra=None):
        self.stages.append(StageStats(
            name, seconds, n_blocks, blocks,
            max_samples=self.MAX_BLOCK_SAMPLES, extra=extra))
        if len(self.stages) > self.MAX_STAGES:
            del self.stages[0]
            self.dropped_stages += 1
        gp = _goodput()
        if gp is not None:
            try:
                gp.record_stage(name, seconds, blocks)
            except Exception:
                pass

    def start_iteration(self, device: bool = False) -> IterationStats:
        it = IterationStats(device=device)
        self.iterations.append(it)
        if len(self.iterations) > self.MAX_ITERATIONS:
            del self.iterations[0]
        return it

    def lineage(self) -> List[StageStats]:
        """Stages of this dataset AND its ancestry, execution order,
        each ancestor visited once (a ``union`` of two branches of one
        root must not double-report the root)."""
        out: List[StageStats] = []
        seen: set = set()

        def walk(st: "DatasetStats"):
            if id(st) in seen:
                return
            seen.add(id(st))
            for p in st.parents:
                walk(p)
            out.extend(st.stages)

        walk(self)
        return out

    def to_dict(self) -> dict:
        out: dict = {
            "stages": [s.to_dict() for s in self.lineage()],
        }
        if self.dropped_stages:
            out["dropped_stages"] = self.dropped_stages
        if self.iterations:
            out["iterations"] = [it.to_dict() for it in self.iterations]
        return out

    def summary(self) -> str:
        lines: List[str] = []
        for i, stage in enumerate(self.lineage()):
            lines.extend(stage.summary_lines(i))
        if self.dropped_stages:
            lines.append(f"({self.dropped_stages} older stage record(s) "
                         f"dropped at the {self.MAX_STAGES}-stage cap)")
        for it in self.iterations:
            lines.append(it.summary_line())
        return "\n".join(lines) or "(no stages executed)"

    def __str__(self) -> str:
        return self.summary()

    def __contains__(self, item) -> bool:
        # Pre-v2 ``ds.stats()`` was the summary string; keep substring
        # membership working for existing callers.
        return item in self.summary()

    def __repr__(self) -> str:
        return (f"DatasetStats(stages={len(self.stages)}, "
                f"parents={len(self.parents)}, "
                f"iterations={len(self.iterations)})")


class Dataset:
    def __init__(self, blocks: List, stages: Optional[List[_Stage]] = None,
                 stats: Optional[DatasetStats] = None,
                 block_rows: Optional[List[int]] = None):
        self._blocks = blocks  # list[ObjectRef]
        self._stages: List[_Stage] = list(stages or [])
        self._stats = stats or DatasetStats()
        self._computed: Optional[List] = None if self._stages else blocks
        # Per-block row counts when the producing stage reported them
        # (task metas / pool probes): count() and split(equal=True)
        # read this instead of fanning out one num_rows task per block
        # — with dynamic splitting multiplying block counts, that
        # fan-out is a worker-pool storm on a saturated node.
        self._block_rows = block_rows if (
            block_rows is not None and len(block_rows) == len(blocks)
        ) else None

    # -- plan execution (lazy, with stage fusion) -------------------------

    def _execute(self) -> List:
        if self._computed is not None:
            return self._computed
        fns = [s.fn for s in self._stages]
        name = "+".join(s.name for s in self._stages)
        from ray_tpu.core.config import config as _config
        from ray_tpu.util import tracing

        target = _config.target_block_size_bytes
        start = time.perf_counter()
        with tracing.span(f"data:{name}",
                          {"blocks": len(self._blocks)}, cat="data"):
            if target > 0:
                out, meta_refs = self._execute_split(name, fns, target)
            else:
                apply_task = ray_tpu.remote(_remote_apply_meta).options(
                    num_returns=2)
                pairs = [apply_task.remote(fns, b) for b in self._blocks]
                out = [p[0] for p in pairs]
                meta_refs = [p[1] for p in pairs]
                ray_tpu.wait(out, num_returns=len(out), timeout=None)
            wall = time.perf_counter() - start
        # Per-task (duration, rows, bytes) metas are tiny side returns;
        # best-effort — a stats fetch failure must not fail the plan.
        blocks_meta = None
        extra = None
        block_rows = None
        try:
            metas = ray_tpu.get(meta_refs)
            blocks_meta = [(m["duration_s"], m["rows"], m["bytes"])
                           for m in metas]
            splits = sum(m.get("splits", 0) for m in metas)
            if splits:
                extra = {"splits": splits}
            block_rows = [
                r for m in metas
                for r in m.get("part_rows", [m["rows"]])
            ]
        except Exception:
            pass
        self._stats.record(name, wall, len(out), blocks=blocks_meta,
                           extra=extra)
        self._computed = out
        self._blocks, self._stages = out, []
        self._block_rows = block_rows if (
            block_rows is not None and len(block_rows) == len(out)
        ) else None
        return out

    def _execute_split(self, name: str, fns: list, target: int):
        """Run the fused chain as STREAMING tasks so oversized outputs
        split into N independent store objects as they are produced (the
        reference's dynamic block splitting rides its streaming
        generators the same way). Returns ``(block_refs, meta_refs)``;
        a mid-stream task error raises here — the same user call
        (count/take/iter) that would have surfaced it at fetch time."""
        split_task = ray_tpu.remote(_remote_apply_split).options(
            num_returns="streaming")
        gens = [split_task.remote(name, fns, b, target)
                for b in self._blocks]
        out: List = []
        meta_refs: List = []
        for gen in gens:
            refs = list(gen)  # blocks in production order, meta last
            meta_refs.append(refs[-1])
            out.extend(refs[:-1])
        return out, meta_refs

    def _with_stage(self, name: str, fn: Callable) -> "Dataset":
        return Dataset(self._blocks, self._stages + [_Stage(name, fn)],
                       self._stats.child())

    def materialize(self) -> "Dataset":
        self._execute()
        return self

    def stats(self) -> "DatasetStats":
        """Structured execution stats; ``str(ds.stats())`` (or substring
        ``in``) keeps the old summary-string contract."""
        return self._stats

    @property
    def num_blocks(self) -> int:
        return len(self._blocks)

    # -- one-to-one transformations ---------------------------------------

    def map(self, fn: Callable) -> "Dataset":
        def do(blk):
            return B.from_rows([fn(r) for r in B.rows_of(blk)], blk)

        return self._with_stage("map", do)

    def flat_map(self, fn: Callable) -> "Dataset":
        def do(blk):
            rows: list = []
            for r in B.rows_of(blk):
                rows.extend(fn(r))
            return B.from_rows(rows, blk)

        return self._with_stage("flat_map", do)

    def filter(self, fn: Callable) -> "Dataset":
        def do(blk):
            return B.from_rows([r for r in B.rows_of(blk) if fn(r)], blk)

        return self._with_stage("filter", do)

    def map_batches(
        self,
        fn: Callable,
        *,
        batch_size: Optional[int] = None,
        batch_format: str = "numpy",
        compute: Optional["ActorPoolStrategy"] = None,
        **_kw,
    ) -> "Dataset":
        def do(blk):
            n = B.num_rows(blk)
            size = n if batch_size is None else batch_size
            outs = []
            for s in _py_range(0, max(n, 1), max(size, 1)):
                batch = B.to_batch(B.slice_block(blk, s, min(s + size, n)),
                                   batch_format)
                outs.append(B.from_batch(fn(batch)))
                if n == 0:
                    break
            return B.concat_blocks(outs) if outs else blk

        if compute is not None:
            return self._map_with_actor_pool(do, compute)
        return self._with_stage("map_batches", do)

    def _map_with_actor_pool(self, do: Callable, compute) -> "Dataset":
        """ActorPoolStrategy: blocks stream through an AUTOSCALING pool
        of worker actors (``_internal/compute.py:173``) — the pool grows
        on queue depth up to ``max_size`` and shrinks back to
        ``min_size`` on idle. Results stay in the object store as the
        actors' return refs (pre-round-14 this path round-tripped every
        block through driver memory: get + re-put, an extra two copies
        of the whole dataset exactly where memory pressure lives)."""
        from ray_tpu.util.actor_pool import AutoscalingActorPool

        blocks = self._execute()

        class _BlockWorker:
            def apply(self, fns, blk):
                out = _remote_apply(fns, blk)
                # (rows, bytes) rides as a tiny second return, computed
                # where the block is local — the stats probe costs zero
                # extra tasks (a per-block probe fan-out is a worker-
                # pool storm once splitting multiplies block counts).
                return [out, (B.num_rows(out), B.size_bytes(out))]

        worker_cls = ray_tpu.remote(_BlockWorker)
        pool = AutoscalingActorPool(
            worker_cls.remote,
            min_size=min(compute.min_size, max(1, len(blocks))),
            max_size=compute.max_size,
            scale_up_queue_depth=compute.scale_up_queue_depth,
            name="map_batches(actors)")
        start = time.perf_counter()
        meta_by_ref: dict = {}

        def _submit(a, b):
            blk_ref, meta_ref = a.apply.options(
                num_returns=2).remote([do], b)
            meta_by_ref[blk_ref.id] = meta_ref
            return blk_ref

        for blk in blocks:
            pool.submit(_submit, blk)
        out = []
        while pool.has_next():
            out.append(pool.get_next_ref())
        wall = time.perf_counter() - start
        peak = pool.peak_size
        scale_ups = sum(1 for d, _s in pool.scale_events if d == "up")
        scale_downs = len(pool.scale_events) - scale_ups
        pool.shutdown()
        stats = self._stats.child()
        # Per-block durations are unknown on the pool path (the pool
        # interleaves blocks across actors); sizes rode along as the
        # apply calls' second returns so the blocks themselves never
        # leave the store. A meta failure must not fail the map.
        blocks_meta = None
        block_rows = None
        try:
            sizes = ray_tpu.get([meta_by_ref[r.id] for r in out])
            blocks_meta = [(None, rows, nbytes) for rows, nbytes in sizes]
            block_rows = [rows for rows, _ in sizes]
        except Exception:
            pass
        stats.record("map_batches(actors)", wall, len(out),
                     blocks=blocks_meta,
                     extra={"pool_peak": peak,
                            "pool_scale_ups": scale_ups,
                            "pool_scale_downs": scale_downs})
        return Dataset(out, [], stats, block_rows=block_rows)

    def limit(self, n: int) -> "Dataset":
        blocks = self._execute()
        out, used = [], 0
        for ref in blocks:
            if used >= n:
                break
            blk = ray_tpu.get(ref)
            take = min(n - used, B.num_rows(blk))
            out.append(ray_tpu.put(B.slice_block(blk, 0, take)))
            used += take
        return Dataset(out, [], self._stats.child())

    # -- all-to-all operations --------------------------------------------

    def repartition(self, num_blocks: int) -> "Dataset":
        blocks = self._execute()

        def split_one(blk, n_out):
            n = B.num_rows(blk)
            cuts = [round(i * n / n_out) for i in _py_range(n_out + 1)]
            return [B.slice_block(blk, cuts[i], cuts[i + 1]) for i in _py_range(n_out)]

        split_task = ray_tpu.remote(split_one).options(num_returns=num_blocks)
        concat_task = ray_tpu.remote(lambda *parts: B.concat_blocks(list(parts)))
        start = time.perf_counter()
        if num_blocks == 1:
            parts_per_block = [[ref] for ref in blocks]
        else:
            parts_per_block = [split_task.remote(ref, num_blocks) for ref in blocks]
        out = []
        for j in _py_range(num_blocks):
            parts = [
                (p[j] if isinstance(p, list) else p) for p in parts_per_block
            ]
            out.append(concat_task.remote(*parts))
        ray_tpu.wait(out, num_returns=len(out), timeout=None)
        stats = self._stats.child()
        stats.record("repartition", time.perf_counter() - start,
                     num_blocks)
        return Dataset(out, [], stats)

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Two-phase all-to-all shuffle (push-based shuffle shape)."""
        blocks = self._execute()
        n_out = len(blocks)

        def shuffle_map(blk, i, n, seed_):
            rng = np.random.default_rng(None if seed_ is None else seed_ + i)
            rows = list(B.rows_of(blk))
            perm = rng.permutation(len(rows))
            parts: list = [[] for _ in _py_range(n)]
            for j, pi in enumerate(perm):
                parts[j % n].append(rows[pi])
            return [B.from_rows(p, blk) for p in parts]

        def shuffle_reduce(seed_, j, *parts):
            blk = B.concat_blocks(list(parts))
            rows = list(B.rows_of(blk))
            rng = np.random.default_rng(None if seed_ is None else seed_ * 7919 + j)
            rng.shuffle(rows)
            return B.from_rows(rows, blk)

        map_task = ray_tpu.remote(shuffle_map).options(num_returns=n_out)
        reduce_task = ray_tpu.remote(shuffle_reduce)
        start = time.perf_counter()
        parts = [map_task.remote(ref, i, n_out, seed) for i, ref in enumerate(blocks)]
        if n_out == 1:
            parts = [[p] for p in parts]
        out = [
            reduce_task.remote(seed, j, *[p[j] for p in parts])
            for j in _py_range(n_out)
        ]
        ray_tpu.wait(out, num_returns=len(out), timeout=None)
        stats = self._stats.child()
        stats.record("random_shuffle", time.perf_counter() - start,
                     n_out)
        return Dataset(out, [], stats)

    def sort(self, key: Optional[Any] = None, descending: bool = False) -> "Dataset":
        """Sample-partition-sort (range-partitioned distributed sort)."""
        blocks = self._execute()
        n_out = len(blocks)
        keyfn = self._make_keyfn(key)

        sample_task = ray_tpu.remote(
            lambda blk: [keyfn(r) for r in list(B.rows_of(blk))[:: max(1, B.num_rows(blk) // 20)]]
        )
        samples = sorted(
            x for s in ray_tpu.get([sample_task.remote(b) for b in blocks])
            for x in s
        )
        if not samples:
            return self
        bounds = [
            samples[int(len(samples) * (i + 1) / n_out)]
            for i in _py_range(n_out - 1)
            if int(len(samples) * (i + 1) / n_out) < len(samples)
        ]

        def part_map(blk, bounds_):
            parts: list = [[] for _ in _py_range(len(bounds_) + 1)]
            for r in B.rows_of(blk):
                k = keyfn(r)
                import bisect

                parts[bisect.bisect_right(bounds_, k)].append(r)
            return [B.from_rows(p, blk) for p in parts]

        def part_reduce(*parts):
            blk = B.concat_blocks(list(parts))
            rows = sorted(B.rows_of(blk), key=keyfn, reverse=descending)
            return B.from_rows(rows, blk)

        n_parts = len(bounds) + 1
        map_task = ray_tpu.remote(part_map).options(num_returns=n_parts)
        reduce_task = ray_tpu.remote(part_reduce)
        start = time.perf_counter()
        parts = [map_task.remote(b, bounds) for b in blocks]
        if n_parts == 1:
            parts = [[p] for p in parts]
        out = [reduce_task.remote(*[p[j] for p in parts]) for j in _py_range(n_parts)]
        if descending:
            out = out[::-1]
        ray_tpu.wait(out, num_returns=len(out), timeout=None)
        stats = self._stats.child()
        stats.record("sort", time.perf_counter() - start, len(out))
        return Dataset(out, [], stats)

    @staticmethod
    def _make_keyfn(key):
        if key is None:
            return lambda r: r
        if isinstance(key, str):
            return lambda r: r[key]
        return key

    def groupby(self, key) -> "GroupedData":
        return GroupedData(self, key)

    # -- combining --------------------------------------------------------

    def union(self, other: "Dataset") -> "Dataset":
        return Dataset(self._execute() + other._execute(), [],
                       self._stats.child(other._stats))

    def zip(self, other: "Dataset") -> "Dataset":
        a, b = self._execute(), other._execute()
        zip_task = ray_tpu.remote(
            lambda x, y: [(r1, r2) for r1, r2 in zip(B.rows_of(x), B.rows_of(y))]
        )
        if len(a) != len(b):
            a_rows = self.take_all()
            b_rows = other.take_all()
            return from_items(list(zip(a_rows, b_rows)))
        return Dataset([zip_task.remote(x, y) for x, y in zip(a, b)], [],
                       self._stats.child(other._stats))

    def window(self, *, blocks_per_window: int = 10) -> "DatasetPipeline":
        """Windowed pipeline over this dataset's blocks: each window's
        plan executes while the previous window is consumed
        (``dataset_pipeline.py``; reference ``Dataset.window``)."""
        from ray_tpu.data.dataset_pipeline import DatasetPipeline

        return DatasetPipeline.from_dataset(self, blocks_per_window)

    def repeat(self, times: int = 2) -> "DatasetPipeline":
        """Multi-epoch pipeline (reference ``Dataset.repeat``)."""
        return self.window(blocks_per_window=max(1, len(self._blocks))
                           ).repeat(times)

    def split(self, n: int, *, equal: bool = False,
              locality_hints=None) -> List["Dataset"]:
        """N sub-datasets; ``equal=True`` balances rows exactly
        (Train per-worker shards, ``_internal/equalize.py``)."""
        blocks = self._execute()
        if not equal:
            return [
                Dataset(blocks[i::n], [], self._stats.child(),
                        block_rows=None if self._block_rows is None
                        else self._block_rows[i::n])
                for i in _py_range(n)
            ]
        if self._block_rows is not None:
            counts = list(self._block_rows)
        else:
            counts = ray_tpu.get(
                [ray_tpu.remote(B.num_rows).remote(b) for b in blocks]
            )
        total = sum(counts)
        per = total // n
        slice_task = ray_tpu.remote(B.slice_block)
        shards: List[List] = [[] for _ in _py_range(n)]
        shard_rows: List[List[int]] = [[] for _ in _py_range(n)]
        shard_idx, filled = 0, 0
        for ref, cnt in zip(blocks, counts):
            offset = 0
            while offset < cnt and shard_idx < n:
                room = per - filled
                take = min(room, cnt - offset)
                if take > 0:
                    shards[shard_idx].append(
                        slice_task.remote(ref, offset, offset + take)
                    )
                    shard_rows[shard_idx].append(take)
                offset += take
                filled += take
                if filled >= per:
                    shard_idx += 1
                    filled = 0
        return [Dataset(s, [], self._stats.child(), block_rows=rows)
                for s, rows in zip(shards, shard_rows)]

    # -- consumption ------------------------------------------------------

    def count(self) -> int:
        self._execute()
        if self._block_rows is not None:
            return sum(self._block_rows)
        counts = ray_tpu.get(
            [ray_tpu.remote(B.num_rows).remote(b) for b in self._blocks]
        )
        return sum(counts)

    def take(self, n: int = 20) -> list:
        out: list = []
        for ref in self._execute():
            for r in B.rows_of(ray_tpu.get(ref)):
                out.append(r)
                if len(out) >= n:
                    return out
        return out

    def take_all(self) -> list:
        return self.take(float("inf"))  # type: ignore[arg-type]

    def show(self, n: int = 20) -> None:
        for r in self.take(n):
            print(r)

    def schema(self):
        for ref in self._execute():
            blk = ray_tpu.get(ref)
            if B.num_rows(blk):
                return B.schema_of(blk)
        return None

    def iter_rows(self) -> Iterable:
        for ref in self._execute():
            yield from B.rows_of(ray_tpu.get(ref))

    def iter_batches(
        self,
        *,
        batch_size: int = 256,
        batch_format: str = "numpy",
        prefetch_blocks: int = 1,
        drop_last: bool = False,
        _iter_stats: Optional[IterationStats] = None,
    ) -> Iterable:
        """Batches with background block prefetch (the pipelined-ingest
        analog of ``DatasetPipeline`` windows).

        Instrumented for the goodput plane: per yielded batch the loop
        records consumer data-wait (time starved inside ``next()``) vs
        consumer time (between batches), and the prefetch-buffer
        occupancy it observed — the derived stall fraction is the
        input-pipeline health number (``state.data_stats()``). Waits
        also accrue to the active train session's ``data_wait`` step
        phase."""
        refs = self._execute()
        it_stats = _iter_stats if _iter_stats is not None \
            else self._stats.start_iteration()
        gp = _goodput()
        fetched: "dict[int, Any]" = {}
        cv = threading.Condition()

        def prefetcher():
            for i, ref in enumerate(refs):
                blk = ray_tpu.get(ref)
                with cv:
                    fetched[i] = blk
                    cv.notify_all()
                    while len(fetched) > prefetch_blocks + 1:
                        cv.wait(0.1)

        threading.Thread(target=prefetcher, daemon=True).start()

        def _record_wait(wait: float, occ: int):
            # Recorded BEFORE the yield so the wait lands in the step
            # the consumer is actually starved in (the session's
            # data_wait phase attributes per report).
            it_stats.wait_s += wait
            it_stats.occupancy.append(occ)
            if gp is not None:
                try:
                    gp.record_iter_batch(wait_s=wait, occupancy=occ)
                except Exception:
                    pass
            # Accrue to the active train session WITHOUT importing the
            # heavy train package from the data path: if no session
            # module is loaded, no session can be active.
            import sys as _sys

            _session = _sys.modules.get("ray_tpu.train.session")
            if _session is not None:
                try:
                    _session.add_data_wait(wait)
                except Exception:
                    pass

        def _record_user(user: float):
            it_stats.batches += 1
            it_stats.user_s += user
            if gp is not None:
                try:
                    gp.record_iter_batch(user_s=user)
                except Exception:
                    pass

        # t_request marks when the consumer asked for the next batch
        # (generator resume); wait = produce-ready - t_request, user =
        # next resume - yield. Both this recorder and an outside client
        # timing next() count exactly one wait + one user sample per
        # yielded batch.
        t_request = time.perf_counter()
        carry: Optional[B.Block] = None
        for i in _py_range(len(refs)):
            with cv:
                while i not in fetched:
                    cv.wait(0.1)
                blk = fetched.pop(i)
                # Occupancy AFTER taking the current block: blocks the
                # producer is ahead by. 0 = every batch starves (the
                # documented starved bucket must be reachable).
                occ = len(fetched)
                cv.notify_all()
            if carry is not None and B.num_rows(carry):
                blk = B.concat_blocks([carry, blk])
                carry = None
            n = B.num_rows(blk)
            pos = 0
            while n - pos >= batch_size:
                batch = B.to_batch(
                    B.slice_block(blk, pos, pos + batch_size),
                    batch_format)
                produced = time.perf_counter()
                _record_wait(produced - t_request, occ)
                yield batch
                resumed = time.perf_counter()
                _record_user(resumed - produced)
                t_request = resumed
                pos += batch_size
            if pos < n:
                carry = B.slice_block(blk, pos, n)
        if carry is not None and B.num_rows(carry) and not drop_last:
            batch = B.to_batch(carry, batch_format)
            produced = time.perf_counter()
            _record_wait(produced - t_request, 0)
            yield batch
            resumed = time.perf_counter()
            _record_user(resumed - produced)

    def iter_torch_batches(
        self,
        *,
        batch_size: int = 256,
        dtypes: Optional[dict] = None,
        device: Optional[str] = None,
        prefetch_blocks: int = 1,
        drop_last: bool = False,
    ) -> Iterable:
        """Batches as torch tensors (reference ``iter_torch_batches``):
        numpy batches converted zero-copy via ``torch.as_tensor``. A
        columnar batch yields a dict of tensors; a plain array batch
        yields one tensor. ``dtypes``: optional per-column torch dtypes."""
        import torch

        def convert(name, arr):
            t = torch.as_tensor(arr)
            if dtypes and name in dtypes:
                t = t.to(dtypes[name])
            if device:
                t = t.to(device)
            return t

        for batch in self.iter_batches(
                batch_size=batch_size, batch_format="numpy",
                prefetch_blocks=prefetch_blocks, drop_last=drop_last):
            if isinstance(batch, dict):
                yield {k: convert(k, v) for k, v in batch.items()}
            else:
                yield convert(None, batch)

    def iter_device_batches(self, *, batch_size: int, sharding=None,
                            dtype=None, drop_last: bool = True) -> Iterable:
        """Double-buffered host->device feeding: batch i+1 is transferred
        while batch i is being consumed (TPU ingest path).

        Goodput instrumentation: the host-side ``device_put`` dispatch
        seconds per batch land in the ``transfer`` phase of
        ``ray_tpu_data_iter_seconds`` (the transfer itself is async —
        overlap working means this stays small and the consumer's wait
        stays near zero)."""
        import jax

        it_stats = self._stats.start_iteration(device=True)
        gp = _goodput()

        def to_device(batch):
            def put(x):
                x = np.asarray(x)
                if dtype is not None:
                    x = x.astype(dtype)
                return (jax.device_put(x, sharding) if sharding is not None
                        else jax.device_put(x))

            t0 = time.perf_counter()
            try:
                if isinstance(batch, dict):
                    return {k: put(v) for k, v in batch.items()}
                return put(batch)
            finally:
                dt = time.perf_counter() - t0
                it_stats.transfer_s += dt
                if gp is not None:
                    try:
                        gp.record_iter_batch(transfer_s=dt)
                    except Exception:
                        pass

        it = self.iter_batches(batch_size=batch_size, batch_format="numpy",
                               drop_last=drop_last, _iter_stats=it_stats)
        prev = None
        for batch in it:
            nxt = to_device(batch)  # async transfer starts immediately
            if prev is not None:
                yield prev
            prev = nxt
        if prev is not None:
            yield prev

    # -- writes -----------------------------------------------------------

    def write_parquet(self, path: str) -> None:
        import os

        import pandas as pd

        os.makedirs(path, exist_ok=True)
        for i, ref in enumerate(self._execute()):
            df = B.to_batch(ray_tpu.get(ref), "pandas")
            df.to_parquet(f"{path}/part-{i:05d}.parquet")

    def write_csv(self, path: str) -> None:
        import os

        os.makedirs(path, exist_ok=True)
        for i, ref in enumerate(self._execute()):
            df = B.to_batch(ray_tpu.get(ref), "pandas")
            df.to_csv(f"{path}/part-{i:05d}.csv", index=False)

    def to_pandas(self):
        import pandas as pd

        dfs = [B.to_batch(ray_tpu.get(r), "pandas") for r in self._execute()]
        return pd.concat(dfs, ignore_index=True) if dfs else pd.DataFrame()

    def to_arrow(self):
        """Materialize as one ``pyarrow.Table`` (reference
        ``to_arrow_refs`` flattened — the driver-side convenience form)."""
        import pyarrow as pa

        tables = [B.to_batch(ray_tpu.get(r), "pyarrow")
                  for r in self._execute()]
        tables = [t for t in tables if t.num_rows]
        return pa.concat_tables(tables) if tables else pa.table({})

    def __repr__(self) -> str:
        return f"Dataset(num_blocks={self.num_blocks}, stages={len(self._stages)})"


class GroupedData:
    """Hash-aggregation over a key (``Dataset.groupby`` parity)."""

    def __init__(self, ds: Dataset, key):
        self.ds = ds
        self.keyfn = Dataset._make_keyfn(key)
        self.key = key

    def _aggregate(self, init, acc, merge, final) -> Dataset:
        keyfn = self.keyfn

        def partial(blk):
            groups: dict = {}
            for r in B.rows_of(blk):
                k = keyfn(r)
                groups[k] = acc(groups.get(k, init()), r)
            return groups

        def combine(*partials):
            total: dict = {}
            for p in partials:
                for k, v in p.items():
                    total[k] = merge(total[k], v) if k in total else v
            rows = [
                {"key": k, "value": final(v)} for k, v in sorted(total.items())
            ]
            return rows

        blocks = self.ds._execute()
        partial_task = ray_tpu.remote(partial)
        combine_task = ray_tpu.remote(combine)
        out = combine_task.remote(*[partial_task.remote(b) for b in blocks])
        return Dataset([out], [], self.ds._stats.child())

    def count(self) -> Dataset:
        return self._aggregate(
            lambda: 0, lambda s, r: s + 1, lambda a, b: a + b, lambda s: s
        )

    def sum(self, on: Optional[str] = None) -> Dataset:
        val = (lambda r: r[on]) if on else (lambda r: r)
        return self._aggregate(
            lambda: 0, lambda s, r: s + val(r), lambda a, b: a + b, lambda s: s
        )

    def min(self, on: Optional[str] = None) -> Dataset:
        val = (lambda r: r[on]) if on else (lambda r: r)
        return self._aggregate(
            lambda: None,
            lambda s, r: val(r) if s is None else min(s, val(r)),
            lambda a, b: min(a, b),
            lambda s: s,
        )

    def max(self, on: Optional[str] = None) -> Dataset:
        val = (lambda r: r[on]) if on else (lambda r: r)
        return self._aggregate(
            lambda: None,
            lambda s, r: val(r) if s is None else max(s, val(r)),
            lambda a, b: max(a, b),
            lambda s: s,
        )

    def mean(self, on: Optional[str] = None) -> Dataset:
        val = (lambda r: r[on]) if on else (lambda r: r)
        return self._aggregate(
            lambda: (0.0, 0),
            lambda s, r: (s[0] + val(r), s[1] + 1),
            lambda a, b: (a[0] + b[0], a[1] + b[1]),
            lambda s: s[0] / s[1],
        )


class ActorPoolStrategy:
    """Compute strategy: run map stages on an AUTOSCALING pool of
    long-lived actors (``_internal/compute.py:173``): the pool starts
    at ``min_size``, adds an actor whenever a block queues behind
    ``scale_up_queue_depth`` pending blocks with no idle actor (up to
    ``max_size``), and retires surplus actors on idle."""

    def __init__(self, min_size: int = 1, max_size: int = 4, *,
                 scale_up_queue_depth: int = 2):
        self.min_size = min_size
        self.max_size = max_size
        self.scale_up_queue_depth = scale_up_queue_depth


# -- read API (``python/ray/data/read_api.py``) ----------------------------


def _to_blocks(items: list, parallelism: int) -> List:
    n = max(1, min(parallelism, len(items) or 1))
    cuts = [round(i * len(items) / n) for i in _py_range(n + 1)]
    return [
        ray_tpu.put(items[cuts[i] : cuts[i + 1]]) for i in _py_range(n)
    ]


def from_items(items: list, *, parallelism: int = 8) -> Dataset:
    return Dataset(_to_blocks(list(items), parallelism))


def range(n: int, *, parallelism: int = 8) -> Dataset:  # noqa: A001
    import builtins

    return from_items(list(builtins.range(n)), parallelism=parallelism)


def range_tensor(n: int, *, shape=(1,), parallelism: int = 8) -> Dataset:
    import builtins

    items = [np.full(shape, i) for i in builtins.range(n)]
    return from_items(items, parallelism=parallelism)


def from_numpy(arr: np.ndarray, *, parallelism: int = 8) -> Dataset:
    n = max(1, min(parallelism, len(arr)))
    cuts = [round(i * len(arr) / n) for i in _py_range(n + 1)]
    return Dataset(
        [ray_tpu.put({"data": arr[cuts[i]:cuts[i + 1]]}) for i in _py_range(n)]
    )


def from_arrow(tables) -> Dataset:
    """One or more ``pyarrow.Table``s -> Dataset of Arrow blocks
    (reference ``from_arrow``)."""
    if not isinstance(tables, (list, tuple)):
        tables = [tables]
    return Dataset([ray_tpu.put(t) for t in tables])


def from_pandas(df, *, parallelism: int = 8) -> Dataset:
    n = max(1, min(parallelism, len(df)))
    cuts = [round(i * len(df) / n) for i in _py_range(n + 1)]
    return Dataset(
        [
            ray_tpu.put(
                {k: df[k].to_numpy()[cuts[i]:cuts[i + 1]] for k in df.columns}
            )
            for i in _py_range(n)
        ]
    )


def _read_dataset(name: str, load_fn: Callable, specs: list) -> Dataset:
    """Lazy read: each shard spec (path / byte range / row groups)
    becomes a tiny spec-block and the actual I/O is a fused STAGE — so
    reads execute lazily (a windowed pipeline reads one window at a
    time), downstream maps fuse into the read task, and an oversized
    read output dynamically splits exactly like any map output
    (``target_block_size_bytes``)."""
    return Dataset([ray_tpu.put(spec) for spec in specs],
                   [_Stage(name, load_fn)])


def _expand_paths(paths) -> list:
    import glob
    import os

    if isinstance(paths, str):
        paths = [paths]
    out: list = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "*"))))
        else:
            out.extend(sorted(glob.glob(p)) or [p])
    return out


def _rg_splits(files: list, parallelism: int) -> list:
    """Split parquet files into ~parallelism read tasks at ROW-GROUP
    granularity (reference: ``_internal/datasource/parquet_datasource.py``
    fragment splitting) — a single big file still parallelizes."""
    import pyarrow.parquet as pq

    shards: list = []  # (path, row_group_index)
    for path in files:
        n = pq.ParquetFile(path).metadata.num_row_groups
        shards.extend((path, rg) for rg in _py_range(n))
    per_task = max(1, len(shards) // max(1, parallelism))
    tasks: list = []
    i = 0
    while i < len(shards):
        group = [shards[i]]
        i += 1
        # Grow the group with CONTIGUOUS row groups of the same file so
        # one task does one sequential read.
        while (len(group) < per_task and i < len(shards)
               and shards[i][0] == group[0][0]):
            group.append(shards[i])
            i += 1
        tasks.append((group[0][0], [rg for _p, rg in group]))
    return tasks


def read_parquet(paths, *, parallelism: int = 8,
                 columns: Optional[list] = None) -> Dataset:
    """Parquet -> ARROW blocks (the reference's default block type):
    row-group-split read tasks each return a ``pyarrow.Table`` that
    travels zero-copy through the object store."""
    files = _expand_paths(paths)

    def load(spec):
        import pyarrow.parquet as pq

        path, row_groups = spec
        return pq.ParquetFile(path).read_row_groups(
            row_groups, columns=columns)

    return _read_dataset("read_parquet", load,
                         _rg_splits(files, parallelism))


def _byte_ranges(files: list, parallelism: int) -> list:
    """(path, start, end) splits totaling ~parallelism tasks across the
    byte span of all files; line-oriented readers snap to newline
    boundaries at read time (start seeks past its first partial line,
    end reads through the end of its last full line)."""
    import os

    sizes = [(p, os.path.getsize(p)) for p in files]
    total = sum(s for _p, s in sizes) or 1
    target = max(1, total // max(1, parallelism))
    ranges: list = []
    for path, size in sizes:
        if size == 0:
            continue
        n = max(1, min(size, round(size / target)))
        step = size / n
        for i in _py_range(n):
            start = int(i * step)
            end = int((i + 1) * step) if i < n - 1 else size
            ranges.append((path, start, end))
    return ranges


def _read_lines_range(path: str, start: int, end: int) -> list:
    """Lines whose FIRST byte lies in [start, end) — each line is owned
    by exactly one range, so concatenating ranges reproduces the file."""
    lines = []
    with open(path, "rb") as f:
        if start > 0:
            # Only skip ahead if ``start`` lands MID-line (the line is
            # owned by the previous range). If the byte before start is a
            # newline, start IS a line's first byte — it belongs to us.
            f.seek(start - 1)
            if f.read(1) != b"\n":
                f.readline()
        else:
            f.seek(0)
        while f.tell() < end:
            line = f.readline()
            if not line:
                break
            lines.append(line.rstrip(b"\n").decode())
    return lines


def read_csv(paths, *, parallelism: int = 8,
             quoted_newlines: bool = False) -> Dataset:
    """Byte-range splitting assumes one record per physical line. CSVs
    with newlines INSIDE quoted fields would be mis-split — pass
    ``quoted_newlines=True`` to fall back to one (sound) task per file
    for such data."""
    files = _expand_paths(paths)

    if quoted_newlines:
        def load_file(path):
            import pandas as pd

            df = pd.read_csv(path)
            return {k: df[k].to_numpy() for k in df.columns}

        return _read_dataset("read_csv", load_file, list(files))

    def load(spec):
        import io

        import pandas as pd

        path, start, end, header = spec
        body = _read_lines_range(path, start, end)
        if start == 0 and body:
            body = body[1:]  # drop the header line from the data
        if not body:
            return {name: np.empty(0, dtype=object) for name in header}
        df = pd.read_csv(
            io.StringIO("\n".join(body)), names=header, header=None)
        return {k: df[k].to_numpy() for k in df.columns}

    def header_of(path):
        with open(path) as f:
            import csv as _csv

            return next(_csv.reader([f.readline()]))

    headers = {p: header_of(p) for p in files}
    return _read_dataset("read_csv", load, [
        (path, start, end, headers[path])
        for path, start, end in _byte_ranges(files, parallelism)
    ])


def read_json(paths, *, parallelism: int = 8) -> Dataset:
    files = _expand_paths(paths)

    def load(spec):
        import json

        path, start, end = spec
        return [json.loads(ln) for ln in _read_lines_range(path, start, end)
                if ln.strip()]

    return _read_dataset("read_json", load,
                         _byte_ranges(files, parallelism))


def read_text(paths, *, parallelism: int = 8) -> Dataset:
    files = _expand_paths(paths)

    def load(spec):
        return _read_lines_range(*spec)

    return _read_dataset("read_text", load,
                         _byte_ranges(files, parallelism))


def read_binary_files(paths, *, parallelism: int = 8) -> Dataset:
    files = _expand_paths(paths)

    def load(path):
        with open(path, "rb") as f:
            return [f.read()]

    return _read_dataset("read_binary_files", load, list(files))
