"""DatasetPipeline: windowed, overlapped execution of a Dataset plan.

Reference parity: ``python/ray/data/dataset_pipeline.py`` — split a
dataset into windows of blocks; each window's transform plan executes
while the previous window is being consumed, bounding memory to one
window (plus the prefetched next) instead of the whole dataset. ``repeat``
re-runs the window sequence for multi-epoch training ingest.

Window transforms stay LAZY (they ride Dataset's stage fusion); the
pipeline only adds scheduling: a prefetch thread materializes window
i+1 while the consumer iterates window i.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, List, Optional

from ray_tpu.data.dataset import Dataset


class DatasetPipeline:
    def __init__(self, windows: List[Dataset], *, epochs: int = 1):
        self._windows = windows
        self._epochs = epochs

    # -- construction ------------------------------------------------------

    @staticmethod
    def from_dataset(ds: Dataset, blocks_per_window: int) -> "DatasetPipeline":
        blocks = ds._blocks
        stages = ds._stages
        wins = [
            Dataset(blocks[i:i + blocks_per_window], list(stages),
                    ds._stats.child())
            for i in range(0, len(blocks), blocks_per_window)
        ] or [Dataset([], list(stages), ds._stats.child())]
        return DatasetPipeline(wins)

    def repeat(self, times: int = 2) -> "DatasetPipeline":
        return DatasetPipeline(self._windows, epochs=self._epochs * times)

    # -- lazy per-window transforms ---------------------------------------

    def _lift(self, method: str, *args, **kwargs) -> "DatasetPipeline":
        return DatasetPipeline(
            [getattr(w, method)(*args, **kwargs) for w in self._windows],
            epochs=self._epochs,
        )

    def map(self, fn: Callable) -> "DatasetPipeline":
        return self._lift("map", fn)

    def flat_map(self, fn: Callable) -> "DatasetPipeline":
        return self._lift("flat_map", fn)

    def filter(self, fn: Callable) -> "DatasetPipeline":
        return self._lift("filter", fn)

    def map_batches(self, fn: Callable, **kw) -> "DatasetPipeline":
        return self._lift("map_batches", fn, **kw)

    # -- consumption (window i+1 materializes while i is consumed) ---------

    def _iter_windows(self) -> Iterable[Dataset]:
        order = [w for _ in range(self._epochs) for w in self._windows]
        prefetched: Optional[threading.Thread] = None
        for i, win in enumerate(order):
            if prefetched is not None:
                prefetched.join()
            if i + 1 < len(order):
                nxt = order[i + 1]
                prefetched = threading.Thread(
                    target=lambda d=nxt: d._execute(), daemon=True)
                prefetched.start()
            else:
                prefetched = None
            yield win

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy") -> Iterable:
        for win in self._iter_windows():
            yield from win.iter_batches(
                batch_size=batch_size, batch_format=batch_format)

    def iter_rows(self) -> Iterable:
        for win in self._iter_windows():
            yield from win.iter_rows()

    def take(self, n: int = 20) -> list:
        out: list = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def count(self) -> int:
        return sum(w.count() for w in self._windows) * self._epochs

    @property
    def num_windows(self) -> int:
        return len(self._windows) * self._epochs

    def stats(self) -> str:
        return "\n".join(str(w.stats()) for w in self._windows)
