"""@remote functions (reference: ``python/ray/remote_function.py:35``)."""

from __future__ import annotations

import functools
from typing import Any, Callable

from ray_tpu._private import worker as _worker
from ray_tpu._private.options import validate_task_options


class RemoteFunction:
    def __init__(self, func: Callable, options: dict[str, Any] | None = None):
        self._func = func
        self._options = validate_task_options(options or {})
        functools.update_wrapper(self, func)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._func.__name__}() cannot be called directly; "
            f"use {self._func.__name__}.remote()."
        )

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._options)

    def options(self, **new_options) -> "RemoteFunction":
        merged = {**self._options, **validate_task_options(new_options)}
        return RemoteFunction(self._func, merged)

    def _remote(self, args, kwargs, options):
        refs = _worker.backend().submit_task(
            self._func, args, kwargs, **options
        )
        num_returns = options.get("num_returns", 1)
        if num_returns == "streaming":
            from ray_tpu.core.ids import task_of_object
            from ray_tpu.core.object_ref import ObjectRefGenerator

            return ObjectRefGenerator(task_of_object(refs[0].id)[0],
                                      first_ref=refs[0])
        return refs[0] if num_returns == 1 else refs

    @property
    def func(self) -> Callable:
        return self._func
