"""Workflow: durable DAG execution with checkpointed task outputs.

Reference parity: ``python/ray/workflow`` — every task's output is
persisted to storage (``workflow_storage.py:229,315``); re-running (or
``resume``-ing) a workflow id skips completed tasks and recomputes only
what's missing (``workflow_executor.py``). Storage is a local/NFS
directory; task identity is the node's deterministic structural id.
"""

from __future__ import annotations

import hashlib
import os
import pickle
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.dag import DAGNode, InputNode, MultiOutputNode

_DEFAULT_STORAGE = os.path.expanduser("~/.ray_tpu/workflows")


def _node_ids(root: DAGNode) -> Dict[DAGNode, str]:
    """Deterministic structural ids: name + dep ids + literal args hash,
    disambiguated by visit order for identical structures."""
    ids: Dict[DAGNode, str] = {}
    counter: Dict[str, int] = {}

    def visit(node: DAGNode) -> str:
        if node in ids:
            return ids[node]
        dep_ids = []
        literals = []
        values = list(node._bound_args) + [
            v for _, v in sorted(node._bound_kwargs.items())
        ]
        for v in values:
            if isinstance(v, DAGNode):
                dep_ids.append(visit(v))
            else:
                try:
                    literals.append(pickle.dumps(v))
                except Exception:
                    literals.append(repr(v).encode())
        basis = node._structure_name().encode() + b"|".join(
            d.encode() for d in dep_ids
        ) + b"#" + b"|".join(literals)
        digest = hashlib.sha1(basis).hexdigest()[:12]
        key = f"{node._structure_name()}_{digest}"
        n = counter.get(key, 0)
        counter[key] = n + 1
        if n:
            key = f"{key}_{n}"
        ids[node] = key
        return key

    visit(root)
    return ids


class _Storage:
    def __init__(self, base: str, workflow_id: str):
        self.dir = os.path.join(base, workflow_id)
        os.makedirs(self.dir, exist_ok=True)

    def _path(self, task_id: str) -> str:
        return os.path.join(self.dir, task_id + ".pkl")

    def has(self, task_id: str) -> bool:
        return os.path.exists(self._path(task_id))

    def load(self, task_id: str):
        with open(self._path(task_id), "rb") as f:
            return pickle.load(f)

    def save(self, task_id: str, value) -> None:
        tmp = self._path(task_id) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, self._path(task_id))  # atomic commit

    def mark_status(self, status: str) -> None:
        with open(os.path.join(self.dir, "STATUS"), "w") as f:
            f.write(status)

    def status(self) -> Optional[str]:
        try:
            with open(os.path.join(self.dir, "STATUS")) as f:
                return f.read().strip()
        except FileNotFoundError:
            return None


def run(
    dag: DAGNode,
    *args,
    workflow_id: str = "default",
    storage: Optional[str] = None,
    **kwargs,
) -> Any:
    """Execute the DAG durably; completed node outputs are checkpointed
    and skipped on re-run/resume."""
    store = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    store.mark_status("RUNNING")
    ids = _node_ids(dag)
    results: Dict[DAGNode, Any] = {}

    def resolve(node: DAGNode):
        if node in results:
            return results[node]
        if isinstance(node, InputNode):
            value = args[0] if args else kwargs
            results[node] = value
            return value
        task_id = ids[node]
        if store.has(task_id):
            value = store.load(task_id)
            results[node] = value
            return value
        rargs = [
            resolve(a) if isinstance(a, DAGNode) else a
            for a in node._bound_args
        ]
        rkwargs = {
            k: resolve(v) if isinstance(v, DAGNode) else v
            for k, v in node._bound_kwargs.items()
        }
        if isinstance(node, MultiOutputNode):
            results[node] = list(rargs)
            return results[node]
        ref = node._submit(rargs, rkwargs)
        value = ray_tpu.get(ref) if isinstance(ref, ray_tpu.ObjectRef) else ref
        store.save(task_id, value)
        results[node] = value
        return value

    try:
        out = resolve(dag)
    except BaseException:
        store.mark_status("FAILED")
        raise
    store.mark_status("SUCCESSFUL")
    return out


def resume(workflow_id: str, dag: DAGNode, *args,
           storage: Optional[str] = None, **kwargs) -> Any:
    """Re-drive a workflow: completed tasks load from storage, the rest
    execute (``workflow.resume`` parity — the DAG is re-supplied because
    we persist outputs, not code)."""
    return run(dag, *args, workflow_id=workflow_id, storage=storage, **kwargs)


def get_status(workflow_id: str, storage: Optional[str] = None) -> Optional[str]:
    store = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    return store.status()


def delete(workflow_id: str, storage: Optional[str] = None) -> None:
    import shutil

    path = os.path.join(storage or _DEFAULT_STORAGE, workflow_id)
    shutil.rmtree(path, ignore_errors=True)


__all__ = ["run", "resume", "get_status", "delete"]
