"""Workflow: durable DAG execution with checkpointed task outputs.

Reference parity: ``python/ray/workflow`` — every task's output is
persisted to storage (``workflow_storage.py:229,315``); re-running (or
``resume``-ing) a workflow id skips completed tasks and recomputes only
what's missing (``workflow_executor.py``). Storage is a local/NFS
directory; task identity is the node's deterministic structural id.
Also covered: per-task retry/catch policies (the reference's
``workflow.options(max_retries, catch_exceptions)``), external events
(``event_listener.py``: a workflow blocks on ``wait_for_event`` and the
delivered payload is checkpointed, so resume never re-waits), and the
metadata API (``list_all`` / ``get_metadata`` / ``get_output``).
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.dag import DAGNode, InputNode, MultiOutputNode

_DEFAULT_STORAGE = os.path.expanduser("~/.ray_tpu/workflows")


def _walk_values(node):
    return list(node._bound_args) + [
        v for _, v in sorted(node._bound_kwargs.items())
    ]


def _assign_event_ids(root: DAGNode) -> dict:
    """Deterministic ids for every _EventNode by STRUCTURAL position: one
    full DFS over the whole DAG (never short-circuited by checkpoints, so
    a resumed run numbers the same events the same way the first run
    did)."""
    ev_ids: dict = {}
    counter: Dict[str, int] = {}
    seen: set = set()

    def visit(node):
        if isinstance(node, _EventNode):
            if node in ev_ids:
                return
            base = node._structure_name()
            n = counter.get(base, 0)
            counter[base] = n + 1
            ev_ids[node] = f"{base}_{n}" if n else base
            return
        if not isinstance(node, DAGNode) or id(node) in seen:
            return
        seen.add(id(node))
        for v in _walk_values(node):
            visit(v)

    visit(root)
    return ev_ids


def _node_ids(root: DAGNode, ev_ids: Optional[dict] = None) -> Dict[DAGNode, str]:
    """Deterministic structural ids: name + dep ids + literal args hash,
    disambiguated by visit order for identical structures. Event args
    contribute their ASSIGNED ids (hashing the listener object would bake
    a memory address into the id and break resume)."""
    ids: Dict[DAGNode, str] = {}
    counter: Dict[str, int] = {}
    ev_ids = ev_ids or {}

    def visit(node: DAGNode) -> str:
        if node in ids:
            return ids[node]
        dep_ids = []
        literals = []
        for v in _walk_values(node):
            if isinstance(v, DAGNode):
                dep_ids.append(visit(v))
            elif isinstance(v, _EventNode):
                dep_ids.append(ev_ids.get(v, v._structure_name()))
            else:
                try:
                    literals.append(pickle.dumps(v))
                except Exception:
                    literals.append(repr(v).encode())
        basis = node._structure_name().encode() + b"|".join(
            d.encode() for d in dep_ids
        ) + b"#" + b"|".join(literals)
        digest = hashlib.sha1(basis).hexdigest()[:12]
        key = f"{node._structure_name()}_{digest}"
        n = counter.get(key, 0)
        counter[key] = n + 1
        if n:
            key = f"{key}_{n}"
        ids[node] = key
        return key

    visit(root)
    return ids


class _Storage:
    def __init__(self, base: str, workflow_id: str):
        self.dir = os.path.join(base, workflow_id)
        os.makedirs(self.dir, exist_ok=True)

    def _path(self, task_id: str) -> str:
        return os.path.join(self.dir, task_id + ".pkl")

    def has(self, task_id: str) -> bool:
        return os.path.exists(self._path(task_id))

    def load(self, task_id: str):
        with open(self._path(task_id), "rb") as f:
            return pickle.load(f)

    def save(self, task_id: str, value) -> None:
        tmp = self._path(task_id) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, self._path(task_id))  # atomic commit

    def mark_status(self, status: str) -> None:
        with open(os.path.join(self.dir, "STATUS"), "w") as f:
            f.write(status)
        self.update_meta(status=status, **(
            {"end_time": time.time()}
            if status in ("SUCCESSFUL", "FAILED") else {}))

    def update_meta(self, _meta: Optional[dict] = None, **fields) -> None:
        meta = self.meta() if _meta is None else _meta
        meta.update(fields)
        tmp = os.path.join(self.dir, "META.json.tmp")
        with open(tmp, "w") as f:
            json.dump(meta, f, default=str)
        os.replace(tmp, os.path.join(self.dir, "META.json"))

    def meta(self) -> dict:
        try:
            with open(os.path.join(self.dir, "META.json")) as f:
                return json.load(f)
        except (FileNotFoundError, ValueError):
            return {}

    def record_task(self, task_id: str, **fields) -> None:
        meta = self.meta()
        meta.setdefault("tasks", {}).setdefault(task_id, {}).update(fields)
        self.update_meta(_meta=meta)

    def status(self) -> Optional[str]:
        try:
            with open(os.path.join(self.dir, "STATUS")) as f:
                return f.read().strip()
        except FileNotFoundError:
            return None


class EventListener:
    """Await an external event (reference ``event_listener.py``): subclass
    and implement ``poll_for_event(*args, **kwargs) -> payload``, which
    BLOCKS until the event arrives (poll a queue, a file, an HTTP
    endpoint...). The payload is checkpointed like any task output, so a
    resumed workflow never waits for an already-delivered event."""

    def poll_for_event(self, *args, **kwargs):
        raise NotImplementedError


class _EventNode:
    """A wait-for-event step usable as an argument to downstream tasks."""

    def __init__(self, listener_cls, args, kwargs):
        self.listener_cls = listener_cls
        self.args = args
        self.kwargs = kwargs

    def _structure_name(self) -> str:
        return f"event_{self.listener_cls.__name__}"


def wait_for_event(listener_cls, *args, **kwargs) -> _EventNode:
    if not (isinstance(listener_cls, type)
            and issubclass(listener_cls, EventListener)):
        raise TypeError("wait_for_event needs an EventListener subclass")
    return _EventNode(listener_cls, args, kwargs)


def run(
    dag: DAGNode,
    *args,
    workflow_id: str = "default",
    storage: Optional[str] = None,
    max_task_retries: int = 0,
    catch_exceptions: bool = False,
    **kwargs,
) -> Any:
    """Execute the DAG durably; completed node outputs are checkpointed
    and skipped on re-run/resume. ``max_task_retries`` re-runs a failed
    task before giving up (reference ``workflow.options(max_retries)``);
    ``catch_exceptions=True`` returns ``(result, None)`` on success or
    ``(None, exception)`` instead of raising."""
    store = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    store.mark_status("RUNNING")
    if not store.meta().get("start_time"):
        store.update_meta(start_time=time.time(),
                          workflow_id=workflow_id)
    ev_ids = _assign_event_ids(dag)
    ids = _node_ids(dag, ev_ids)
    results: Dict[Any, Any] = {}

    def resolve(node):
        if node in results:
            return results[node]
        if isinstance(node, InputNode):
            value = args[0] if args else kwargs
            results[node] = value
            return value
        if isinstance(node, _EventNode):
            task_id = ev_ids[node]
            if store.has(task_id):
                value = store.load(task_id)
            else:
                store.record_task(task_id, state="WAITING")
                value = node.listener_cls().poll_for_event(
                    *node.args, **node.kwargs)
                store.save(task_id, value)
                store.record_task(task_id, state="SUCCESSFUL")
            results[node] = value
            return value
        task_id = ids[node]
        if store.has(task_id):
            value = store.load(task_id)
            results[node] = value
            return value
        rargs = [
            resolve(a) if isinstance(a, (DAGNode, _EventNode)) else a
            for a in node._bound_args
        ]
        rkwargs = {
            k: resolve(v) if isinstance(v, (DAGNode, _EventNode)) else v
            for k, v in node._bound_kwargs.items()
        }
        if isinstance(node, MultiOutputNode):
            results[node] = list(rargs)
            return results[node]
        attempts = 0
        while True:
            try:
                ref = node._submit(rargs, rkwargs)
                value = (ray_tpu.get(ref)
                         if isinstance(ref, ray_tpu.ObjectRef) else ref)
                break
            except Exception as e:  # KeyboardInterrupt etc. abort, not retry
                attempts += 1
                store.record_task(
                    task_id, state="RETRYING", failures=attempts,
                    last_error=repr(e))
                if attempts > max_task_retries:
                    store.record_task(task_id, state="FAILED")
                    raise
        store.save(task_id, value)
        store.record_task(task_id, state="SUCCESSFUL")
        results[node] = value
        return value

    try:
        out = resolve(dag)
    except BaseException as e:  # noqa: BLE001 — status marking only
        store.mark_status("FAILED")
        if catch_exceptions and isinstance(e, Exception):
            return None, e
        raise  # KeyboardInterrupt/SystemExit always propagate
    # Output BEFORE the status flip: SUCCESSFUL must imply get_output works.
    store.save("__output__", out)
    store.mark_status("SUCCESSFUL")
    return (out, None) if catch_exceptions else out


def resume(workflow_id: str, dag: DAGNode, *args,
           storage: Optional[str] = None, **kwargs) -> Any:
    """Re-drive a workflow: completed tasks load from storage, the rest
    execute (``workflow.resume`` parity — the DAG is re-supplied because
    we persist outputs, not code)."""
    return run(dag, *args, workflow_id=workflow_id, storage=storage, **kwargs)


def get_status(workflow_id: str, storage: Optional[str] = None) -> Optional[str]:
    store = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    return store.status()


def get_metadata(workflow_id: str, storage: Optional[str] = None) -> dict:
    """Workflow-level metadata (reference ``workflow.get_metadata``):
    status, start/end times, and per-task states/failure counts."""
    store = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    meta = store.meta()
    meta["status"] = store.status()
    return meta


def get_output(workflow_id: str, storage: Optional[str] = None):
    """The checkpointed final output of a finished workflow."""
    store = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    if not store.has("__output__"):
        raise ValueError(
            f"workflow {workflow_id!r} has no stored output "
            f"(status: {store.status()})")
    return store.load("__output__")


def list_all(storage: Optional[str] = None) -> Dict[str, Optional[str]]:
    """{workflow_id: status} for every workflow in the storage root."""
    base = storage or _DEFAULT_STORAGE
    out: Dict[str, Optional[str]] = {}
    try:
        entries = sorted(os.listdir(base))
    except FileNotFoundError:
        return out
    for wid in entries:
        if os.path.isdir(os.path.join(base, wid)):
            out[wid] = _Storage(base, wid).status()
    return out


def delete(workflow_id: str, storage: Optional[str] = None) -> None:
    import shutil

    path = os.path.join(storage or _DEFAULT_STORAGE, workflow_id)
    shutil.rmtree(path, ignore_errors=True)


__all__ = [
    "run", "resume", "get_status", "get_metadata", "get_output",
    "list_all", "delete", "EventListener", "wait_for_event",
]
