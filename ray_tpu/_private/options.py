"""Central option validation (reference: ``python/ray/_private/ray_option_utils.py``)."""

from __future__ import annotations

from typing import Any

_COMMON = {
    "num_cpus": (int, float, type(None)),
    "num_tpus": (int, float, type(None)),
    "num_gpus": (int, float, type(None)),
    "resources": (dict, type(None)),
    # int, or "streaming"/"dynamic" for generator tasks (both reference
    # spellings accepted; normalized to "streaming" at validation).
    "num_returns": (int, str),
    "max_retries": (int,),
    "retry_exceptions": (bool, tuple),
    "name": (str, type(None)),
    "runtime_env": (dict, type(None)),
    "scheduling_strategy": (object,),
    "placement_group": (object,),
    "placement_group_bundle_index": (int,),
}

_TASK_ONLY: dict[str, tuple] = {}

_ACTOR_ONLY = {
    "max_concurrency": (int,),
    "concurrency_groups": (dict, type(None)),
    "max_restarts": (int,),
    "max_task_retries": (int,),
    "lifetime": (str, type(None)),
    "namespace": (str, type(None)),
}


def _validate(options: dict[str, Any], allowed: dict[str, tuple], kind: str):
    out = {}
    for k, v in options.items():
        if v is None and k not in ("name", "lifetime", "namespace"):
            continue
        if k not in allowed:
            raise ValueError(
                f"Invalid option {k!r} for {kind}. Allowed: {sorted(allowed)}"
            )
        if not isinstance(v, allowed[k]):
            raise TypeError(f"Option {k!r} expects {allowed[k]}, got {type(v)}")
        out[k] = v
    return out


def validate_task_options(options: dict[str, Any]) -> dict[str, Any]:
    out = _validate(options, {**_COMMON, **_TASK_ONLY}, "task")
    nr = out.get("num_returns")
    if isinstance(nr, str):
        if nr not in ("streaming", "dynamic"):
            raise ValueError(
                f'num_returns must be an int or "streaming", got {nr!r}')
        out["num_returns"] = "streaming"
    return out


def validate_actor_options(options: dict[str, Any]) -> dict[str, Any]:
    out = _validate(options, {**_COMMON, **_ACTOR_ONLY}, "actor")
    if isinstance(out.get("num_returns"), str):
        raise ValueError(
            "actors do not support streaming returns; num_returns must "
            "be an int for actor options"
        )
    groups = out.get("concurrency_groups")
    if groups:
        for gname, n in groups.items():
            if not isinstance(gname, str) or not gname:
                raise ValueError(
                    f"concurrency_groups keys must be non-empty strings, "
                    f"got {gname!r}"
                )
            if not isinstance(n, int) or isinstance(n, bool) or n < 1:
                raise ValueError(
                    f"concurrency_groups[{gname!r}] must be a positive int "
                    f"thread count, got {n!r}"
                )
    return out
