"""Global worker/driver state (reference: ``python/ray/_private/worker.py:405``).

Holds the process-wide backend connection. ``init`` wires either the local
in-process backend or (M3) a cluster backend that talks to the control plane.
"""

from __future__ import annotations

import threading
from typing import Any

_lock = threading.Lock()
_backend = None
_address: str | None = None
_init_kwargs: dict[str, Any] = {}


def init(address: str | None = None, **kwargs):
    global _backend, _address, _init_kwargs
    with _lock:
        if _backend is not None:
            if address is not None and address != _address:
                raise RuntimeError(
                    f"ray_tpu is already initialized (address={_address!r}); "
                    f"call shutdown() before init(address={address!r})"
                )
            return _backend
        if address is None or address == "local":
            from ray_tpu.core.local_backend import LocalBackend

            _backend = LocalBackend(
                num_cpus=kwargs.get("num_cpus"),
                resources=kwargs.get("resources"),
            )
        else:
            try:
                from ray_tpu.cluster.client import connect
            except ImportError as e:
                raise NotImplementedError(
                    f"cluster backend not available in this build "
                    f"(address={address!r}): {e}"
                ) from e
            _backend = connect(address, **kwargs)
        _address = address
        _init_kwargs = kwargs
        return _backend


def backend():
    if _backend is None:
        # Auto-init, matching the reference's implicit ray.init() on first use.
        init()
    return _backend


def is_initialized() -> bool:
    return _backend is not None


def shutdown():
    global _backend
    with _lock:
        if _backend is not None:
            _backend.shutdown()
            _backend = None
