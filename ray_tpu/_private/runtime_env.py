"""Runtime environments: per-task/actor env_vars, working_dir, py_modules.

The reference ships these via its runtime-env agent with content-addressed
package URIs cached per node (``python/ray/_private/runtime_env/packaging.py``,
``dashboard/modules/runtime_env/runtime_env_agent.py:160``). Same protocol
here, cluster-KV flavored:

* The DRIVER packages each ``working_dir`` / ``py_modules`` entry into a
  deterministic zip, content-hashes it, and uploads it to the head KV under
  ``rtenv:pkg:<sha256>`` — once per content (put with overwrite=False).
* The task/actor spec carries the resolved env: env_vars + package URIs +
  the env's own hash (``env_key``).
* Each NODE AGENT downloads + extracts packages into a per-hash cache dir
  on first use, and keys its worker pool by ``env_key`` so processes with
  different environments are never mixed (reference: worker pools keyed by
  runtime-env hash in ``worker_pool.cc``).

Scope note: runtime envs apply to the cluster backend; the in-process
local backend cannot give each task its own interpreter environment.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import zipfile

KV_PREFIX = "rtenv:pkg:"
_ALLOWED_KEYS = {"env_vars", "working_dir", "py_modules"}


def validate(env: dict) -> None:
    if not isinstance(env, dict):
        raise TypeError(f"runtime_env must be a dict, got {type(env)}")
    unknown = set(env) - _ALLOWED_KEYS
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys {sorted(unknown)}; "
            f"supported: {sorted(_ALLOWED_KEYS)}"
        )
    ev = env.get("env_vars") or {}
    if not all(isinstance(k, str) and isinstance(v, str)
               for k, v in ev.items()):
        raise TypeError("runtime_env['env_vars'] must be {str: str}")
    wd = env.get("working_dir")
    if wd is not None and not os.path.isdir(wd):
        raise ValueError(f"runtime_env working_dir {wd!r} is not a directory")
    for m in env.get("py_modules") or []:
        if not os.path.exists(m):
            raise ValueError(f"runtime_env py_module {m!r} does not exist")


def _zip_path(root: str) -> bytes:
    """Deterministic zip of a file or directory tree: sorted entries,
    zeroed timestamps — equal content ⇒ equal bytes ⇒ equal URI."""
    buf = io.BytesIO()
    root = os.path.abspath(root)
    base = os.path.basename(root.rstrip(os.sep))
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        if os.path.isfile(root):
            entries = [(root, base)]
        else:
            entries = []
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames.sort()
                for fn in sorted(filenames):
                    full = os.path.join(dirpath, fn)
                    rel = os.path.join(base, os.path.relpath(full, root))
                    entries.append((full, rel))
        for full, rel in entries:
            zi = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
            zi.external_attr = (os.stat(full).st_mode & 0xFFFF) << 16
            with open(full, "rb") as f:
                zf.writestr(zi, f.read())
    return buf.getvalue()


def package(env: dict, kv_put) -> dict:
    """Resolve a user runtime_env into a shippable spec, uploading package
    zips to the cluster KV (content-addressed; no-op when already there).
    ``kv_put(key, value, overwrite)`` is the head KV entry point."""
    validate(env)
    resolved: dict = {"env_vars": dict(env.get("env_vars") or {}),
                      "packages": []}

    def upload(path: str, kind: str) -> None:
        blob = _zip_path(path)
        digest = hashlib.sha256(blob).hexdigest()
        kv_put(KV_PREFIX + digest, blob, False)
        resolved["packages"].append({
            "uri": digest,
            "kind": kind,
            "name": os.path.basename(os.path.abspath(path).rstrip(os.sep)),
        })

    if env.get("working_dir"):
        upload(env["working_dir"], "working_dir")
    for m in env.get("py_modules") or []:
        upload(m, "py_module")
    resolved["env_key"] = env_key(resolved)
    return resolved


def env_key(resolved: dict) -> str:
    canon = json.dumps(
        {"env_vars": resolved.get("env_vars", {}),
         "packages": [(p["uri"], p["kind"]) for p in
                      resolved.get("packages", [])]},
        sort_keys=True,
    )
    return hashlib.sha256(canon.encode()).hexdigest()[:16]


def ensure_local(resolved: dict, kv_get, cache_root: str) -> dict:
    """Materialize a resolved env on this node. Returns the worker-process
    recipe: {"env_vars", "cwd", "py_paths"}. Package extraction is cached
    by content hash — concurrent ensures of the same URI extract into a
    tmp dir and rename (atomic; losers are no-ops)."""
    env_vars = dict(resolved.get("env_vars", {}))
    cwd = None
    py_paths: list[str] = []
    for pkg in resolved.get("packages", []):
        dest = os.path.join(cache_root, pkg["uri"])
        if not os.path.isdir(dest):
            blob = kv_get(KV_PREFIX + pkg["uri"])
            if blob is None:
                raise RuntimeError(
                    f"runtime_env package {pkg['uri'][:12]}… missing from KV"
                )
            tmp = dest + f".tmp.{os.getpid()}"
            with zipfile.ZipFile(io.BytesIO(blob)) as zf:
                zf.extractall(tmp)
            try:
                os.replace(tmp, dest)
            except OSError:
                # Lost the race to a concurrent extraction of the same
                # content — identical bytes, keep the winner.
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
        if pkg["kind"] == "working_dir":
            cwd = os.path.join(dest, pkg["name"])
            py_paths.append(cwd)
        else:  # py_module: importable from the cache dir holding it
            py_paths.append(dest)
    return {"env_vars": env_vars, "cwd": cwd, "py_paths": py_paths}
