"""Runtime environments: per-task/actor env_vars, working_dir, py_modules.

The reference ships these via its runtime-env agent with content-addressed
package URIs cached per node (``python/ray/_private/runtime_env/packaging.py``,
``dashboard/modules/runtime_env/runtime_env_agent.py:160``). Same protocol
here, cluster-KV flavored:

* The DRIVER packages each ``working_dir`` / ``py_modules`` entry into a
  deterministic zip, content-hashes it, and uploads it to the head KV under
  ``rtenv:pkg:<sha256>`` — once per content (put with overwrite=False).
* The task/actor spec carries the resolved env: env_vars + package URIs +
  the env's own hash (``env_key``).
* Each NODE AGENT downloads + extracts packages into a per-hash cache dir
  on first use, and keys its worker pool by ``env_key`` so processes with
  different environments are never mixed (reference: worker pools keyed by
  runtime-env hash in ``worker_pool.cc``).

Scope note: runtime envs apply to the cluster backend; the in-process
local backend cannot give each task its own interpreter environment.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import zipfile

KV_PREFIX = "rtenv:pkg:"
_BASE_KEYS = {"env_vars", "working_dir", "py_modules"}


class RuntimeEnvPlugin:
    """Extension point for runtime_env fields (reference
    ``python/ray/_private/runtime_env/plugin.py``: plugins own one env
    key each; the agent calls them in priority order to set an env up).

    Lifecycle:
      * ``validate(value)`` — driver-side, at options time;
      * ``package(value, kv_put)`` — driver-side: upload any content to
        the cluster KV, return the SHIPPABLE resolved value (must be
        JSON-serializable — it is hashed into ``env_key``, which also
        keys the node agents' worker pools);
      * ``ensure_local(value, ctx)`` — node-side, once per env per node
        (then cached by env_key): materialize state under
        ``ctx["cache_root"]`` and mutate the worker recipe
        ``ctx["recipe"]`` ({"env_vars", "cwd", "py_paths", "python"}).
    """

    #: The runtime_env dict key this plugin owns.
    name: str = ""
    #: Node-side setup order (lower runs first).
    priority: int = 10

    def validate(self, value) -> None:
        pass

    def package(self, value, kv_put):
        return value

    def ensure_local(self, value, ctx: dict) -> None:
        pass


_PLUGINS: dict = {}


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    """Register a plugin cluster-wide for this process (drivers validate
    + package with it; node agents must have it registered too — ship it
    via ``py_modules`` or install it on the image)."""
    if not plugin.name or plugin.name in _BASE_KEYS:
        raise ValueError(f"invalid plugin name {plugin.name!r}")
    _PLUGINS[plugin.name] = plugin


class PipPlugin(RuntimeEnvPlugin):
    """Per-requirements-hash virtualenvs (runtime_env/pip.py analog)."""

    name = "pip"
    priority = 0  # the interpreter choice must precede everything else

    def validate(self, value) -> None:
        _pip_list({"pip": value})

    def package(self, value, kv_put):
        return _pip_list({"pip": value})

    def ensure_local(self, value, ctx: dict) -> None:
        if value:
            ctx["recipe"]["python"] = _ensure_venv(
                value, ctx["cache_root"])


class CondaPlugin(RuntimeEnvPlugin):
    """Conda environments (runtime_env/conda.py analog): an env spec
    dict ({"dependencies": [...]}) or an existing env name/prefix.
    Requires the ``conda`` binary on the node; absent, the env fails at
    setup with a clear error — or, with RAY_TPU_CONDA_DRY_RUN=1, the
    plugin records what it WOULD build and leaves the default
    interpreter in place (CI boxes without conda)."""

    name = "conda"
    priority = 0

    def validate(self, value) -> None:
        if not isinstance(value, (str, dict)):
            raise TypeError(
                "runtime_env['conda'] must be an env name/prefix or a "
                "spec dict")

    def package(self, value, kv_put):
        return value

    def ensure_local(self, value, ctx: dict) -> None:
        import shutil
        import subprocess

        digest = hashlib.sha256(
            json.dumps(value, sort_keys=True).encode()).hexdigest()[:16]
        conda = shutil.which("conda")
        if conda is None:
            if os.environ.get("RAY_TPU_CONDA_DRY_RUN"):
                marker = os.path.join(
                    ctx["cache_root"], f"conda-dryrun-{digest}")
                with open(marker, "w") as f:
                    json.dump(value, f)
                return
            raise RuntimeError(
                "runtime_env['conda'] requires the conda binary on the "
                "node (not installed); use pip instead or set "
                "RAY_TPU_CONDA_DRY_RUN=1 to validate without it")
        if isinstance(value, str):
            # Existing env by name/prefix.
            prefix = value if os.path.isdir(value) else None
            argv = ["conda", "run"] + (
                ["-p", prefix] if prefix else ["-n", value]
            ) + ["python", "-c", "import sys; print(sys.executable)"]
            out = subprocess.run(argv, capture_output=True, text=True)
            if out.returncode != 0:
                raise RuntimeError(
                    f"conda env {value!r} unusable: {out.stderr[-400:]}")
            ctx["recipe"]["python"] = out.stdout.strip()
            return
        prefix = os.path.join(ctx["cache_root"], f"conda-{digest}")
        vpy = os.path.join(prefix, "bin", "python")
        if not os.path.exists(vpy):
            spec_file = prefix + ".yml"
            with open(spec_file, "w") as f:
                json.dump(value, f)
            out = subprocess.run(
                ["conda", "env", "create", "-p", prefix,
                 "-f", spec_file, "-y"],
                capture_output=True, text=True)
            if out.returncode != 0:
                raise RuntimeError(
                    f"conda env create failed: {out.stderr[-800:]}")
        ctx["recipe"]["python"] = vpy


class ContainerPlugin(RuntimeEnvPlugin):
    """Container image envs (runtime_env/container.py analog) — STUB:
    validated and hashed into env_key so pools key correctly, but
    worker-in-container launch needs a container runtime this node
    plane doesn't drive yet. Setup fails with a clear error (or records
    a dry-run marker under RAY_TPU_CONTAINER_DRY_RUN=1)."""

    name = "container"
    priority = 0

    def validate(self, value) -> None:
        if not (isinstance(value, dict) and
                isinstance(value.get("image"), str)):
            raise TypeError(
                "runtime_env['container'] must be {'image': str, ...}")

    def ensure_local(self, value, ctx: dict) -> None:
        if os.environ.get("RAY_TPU_CONTAINER_DRY_RUN"):
            marker = os.path.join(
                ctx["cache_root"],
                "container-dryrun-" + hashlib.sha256(
                    json.dumps(value, sort_keys=True).encode()
                ).hexdigest()[:16])
            with open(marker, "w") as f:
                json.dump(value, f)
            return
        raise RuntimeError(
            "runtime_env['container'] is not supported on this node "
            "(no container runtime integration); set "
            "RAY_TPU_CONTAINER_DRY_RUN=1 to validate the spec only")


register_plugin(PipPlugin())
register_plugin(CondaPlugin())
register_plugin(ContainerPlugin())


def _pip_list(env: dict) -> list:
    """Normalize the ``pip`` field: list[str] or {"packages": [...]}
    (reference ``runtime_env/pip.py`` accepts both shapes)."""
    pip = env.get("pip")
    if pip is None:
        return []
    if isinstance(pip, dict):
        pip = pip.get("packages", [])
    if not (isinstance(pip, (list, tuple))
            and all(isinstance(r, str) for r in pip)):
        raise TypeError(
            "runtime_env['pip'] must be a list of requirement strings "
            "or {'packages': [...]}")
    return list(pip)


def validate(env: dict) -> None:
    if not isinstance(env, dict):
        raise TypeError(f"runtime_env must be a dict, got {type(env)}")
    allowed = _BASE_KEYS | set(_PLUGINS)
    unknown = set(env) - allowed
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys {sorted(unknown)}; "
            f"supported: {sorted(allowed)}"
        )
    ev = env.get("env_vars") or {}
    if not all(isinstance(k, str) and isinstance(v, str)
               for k, v in ev.items()):
        raise TypeError("runtime_env['env_vars'] must be {str: str}")
    wd = env.get("working_dir")
    if wd is not None and not os.path.isdir(wd):
        raise ValueError(f"runtime_env working_dir {wd!r} is not a directory")
    for m in env.get("py_modules") or []:
        if not os.path.exists(m):
            raise ValueError(f"runtime_env py_module {m!r} does not exist")
    if "pip" in env and "conda" in env:
        # Both want to own the worker interpreter; the later one would
        # silently drop the other's packages (the reference rejects the
        # combination too — put pip deps inside the conda spec instead).
        raise ValueError(
            "runtime_env cannot combine 'pip' and 'conda'; add pip "
            "requirements to the conda spec's dependencies instead")
    for name, plugin in _PLUGINS.items():
        if name in env:
            plugin.validate(env[name])


def _zip_path(root: str) -> bytes:
    """Deterministic zip of a file or directory tree: sorted entries,
    zeroed timestamps — equal content ⇒ equal bytes ⇒ equal URI."""
    buf = io.BytesIO()
    root = os.path.abspath(root)
    base = os.path.basename(root.rstrip(os.sep))
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        if os.path.isfile(root):
            entries = [(root, base)]
        else:
            entries = []
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames.sort()
                for fn in sorted(filenames):
                    full = os.path.join(dirpath, fn)
                    rel = os.path.join(base, os.path.relpath(full, root))
                    entries.append((full, rel))
        for full, rel in entries:
            zi = zipfile.ZipInfo(rel, date_time=(1980, 1, 1, 0, 0, 0))
            zi.external_attr = (os.stat(full).st_mode & 0xFFFF) << 16
            with open(full, "rb") as f:
                zf.writestr(zi, f.read())
    return buf.getvalue()


def package(env: dict, kv_put) -> dict:
    """Resolve a user runtime_env into a shippable spec, uploading package
    zips to the cluster KV (content-addressed; no-op when already there).
    ``kv_put(key, value, overwrite)`` is the head KV entry point."""
    validate(env)
    resolved: dict = {"env_vars": dict(env.get("env_vars") or {}),
                      "packages": []}

    def upload(path: str, kind: str) -> None:
        blob = _zip_path(path)
        digest = hashlib.sha256(blob).hexdigest()
        kv_put(KV_PREFIX + digest, blob, False)
        resolved["packages"].append({
            "uri": digest,
            "kind": kind,
            "name": os.path.basename(os.path.abspath(path).rstrip(os.sep)),
        })

    if env.get("working_dir"):
        upload(env["working_dir"], "working_dir")
    for m in env.get("py_modules") or []:
        upload(m, "py_module")
    for name, plugin in _PLUGINS.items():
        if name in env:
            resolved[name] = plugin.package(env[name], kv_put)
    resolved.setdefault("pip", [])  # wire-shape compat
    resolved["env_key"] = env_key(resolved)
    return resolved


def env_key(resolved: dict) -> str:
    canon = json.dumps(
        {"env_vars": resolved.get("env_vars", {}),
         "packages": [(p["uri"], p["kind"]) for p in
                      resolved.get("packages", [])],
         # Every plugin's resolved value keys the env (and with it the
         # node agents' worker pools): two tasks with different plugin
         # state can never share a worker process.
         "plugins": {name: resolved.get(name) for name in sorted(_PLUGINS)
                     if resolved.get(name) is not None}},
        sort_keys=True,
    )
    return hashlib.sha256(canon.encode()).hexdigest()[:16]


def _ensure_venv(pip_reqs: list, cache_root: str) -> str:
    """Per-requirements-hash virtualenv (reference ``runtime_env/pip.py``:
    one venv per pip spec, cached). Returns the venv python executable.

    The venv is seeded with the PARENT interpreter's site-packages via a
    ``.pth`` file rather than ``--system-site-packages`` alone: when the
    cluster itself runs inside a venv (common container layout),
    system-site only exposes the BASE interpreter's packages and jax/numpy
    would vanish from workers. The child venv's own site-packages precede
    the parent's on sys.path, so a pip-installed version shadows the
    cluster-wide one — the isolation property the feature exists for.
    Built in a tmp dir + atomic rename (concurrent builders: one wins,
    losers clean up)."""
    import threading

    digest = hashlib.sha256(
        json.dumps(pip_reqs, sort_keys=True).encode()).hexdigest()[:16]
    dest = os.path.join(cache_root, f"venv-{digest}")
    vpy = os.path.join(dest, "bin", "python")
    if os.path.exists(vpy):
        return vpy
    # Serialize builds in this process: the node agent dispatches tasks on
    # separate THREADS, so a burst of first-use tasks for one env would
    # otherwise race whole venv builds (pid-suffixed tmp dirs don't
    # separate threads). Cross-process the tmp+rename stays the guard.
    lock = _VENV_LOCKS.setdefault(digest, threading.Lock())
    with lock:
        if os.path.exists(vpy):
            return vpy
        return _build_venv(pip_reqs, dest, vpy)


_VENV_LOCKS: dict = {}


def _build_venv(pip_reqs: list, dest: str, vpy: str) -> str:
    import glob
    import shutil
    import site
    import subprocess
    import sys

    tmp = dest + f".tmp.{os.getpid()}"
    subprocess.run(
        [sys.executable, "-m", "venv", "--system-site-packages", tmp],
        check=True, capture_output=True, text=True)
    parents = list(dict.fromkeys(
        p for p in site.getsitepackages() + sys.path
        if p.endswith("site-packages") and os.path.isdir(p)))
    sitedirs = glob.glob(os.path.join(tmp, "lib", "python*",
                                      "site-packages"))
    for sd in sitedirs:
        with open(os.path.join(sd, "zz_parent_site.pth"), "w") as f:
            f.write("\n".join(parents) + "\n")
    proc = subprocess.run(
        [os.path.join(tmp, "bin", "python"), "-m", "pip", "install",
         "--no-warn-script-location", "--disable-pip-version-check",
         *pip_reqs],
        capture_output=True, text=True)
    if proc.returncode != 0:
        shutil.rmtree(tmp, ignore_errors=True)
        raise RuntimeError(
            f"pip install {pip_reqs} failed: {proc.stderr[-800:]}")
    try:
        os.replace(tmp, dest)
    except OSError:
        # Lost the race to a concurrent build of the same spec.
        shutil.rmtree(tmp, ignore_errors=True)
    return vpy


def ensure_local(resolved: dict, kv_get, cache_root: str) -> dict:
    """Materialize a resolved env on this node. Returns the worker-process
    recipe: {"env_vars", "cwd", "py_paths", "python"} ("python" is the
    interpreter to spawn — a per-env virtualenv when pip packages are
    requested, else None for the default). Package extraction is cached
    by content hash — concurrent ensures of the same URI extract into a
    tmp dir and rename (atomic; losers are no-ops)."""
    env_vars = dict(resolved.get("env_vars", {}))
    cwd = None
    py_paths: list[str] = []
    python = None
    for pkg in resolved.get("packages", []):
        dest = os.path.join(cache_root, pkg["uri"])
        if not os.path.isdir(dest):
            blob = kv_get(KV_PREFIX + pkg["uri"])
            if blob is None:
                raise RuntimeError(
                    f"runtime_env package {pkg['uri'][:12]}… missing from KV"
                )
            tmp = dest + f".tmp.{os.getpid()}"
            with zipfile.ZipFile(io.BytesIO(blob)) as zf:
                zf.extractall(tmp)
            try:
                os.replace(tmp, dest)
            except OSError:
                # Lost the race to a concurrent extraction of the same
                # content — identical bytes, keep the winner.
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
        if pkg["kind"] == "working_dir":
            cwd = os.path.join(dest, pkg["name"])
            py_paths.append(cwd)
        else:  # py_module: importable from the cache dir holding it
            py_paths.append(dest)
    recipe = {"env_vars": env_vars, "cwd": cwd, "py_paths": py_paths,
              "python": python}
    known = _BASE_KEYS | set(_PLUGINS) | {"packages", "env_key", "pip"}
    for key in resolved:
        if key not in known and resolved[key]:
            # A plugin the driver had but this node doesn't: running the
            # task without its env state would be silent corruption.
            raise RuntimeError(
                f"runtime_env field {key!r} has no registered plugin on "
                f"this node (register it in the agent process or ship "
                f"it via py_modules)")
    ctx = {"kv_get": kv_get, "cache_root": cache_root, "recipe": recipe}
    for plugin in sorted(_PLUGINS.values(), key=lambda p: p.priority):
        value = resolved.get(plugin.name)
        if value:
            plugin.ensure_local(value, ctx)
    return recipe
