"""Usage stats (reference: ``python/ray/_private/usage/usage_lib.py``).

Opt-out telemetry: a periodic report of cluster shape + which libraries
were imported. Differences from the reference, deliberately: this
environment is zero-egress, so reports are only ever written to a local
JSONL file under the session temp dir (the reference POSTs to a usage
endpoint); and collection is DISABLED by default here — recording starts
only when ``RAY_TPU_USAGE_STATS_ENABLED=1`` (the reference ships
enabled-by-default with an opt-out env, ``usage_lib.py`` usage_stats_enabledness).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Any, Dict, Set

_lock = threading.Lock()
_library_usages: Set[str] = set()
_extra_tags: Dict[str, str] = {}


def usage_stats_enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "0") == "1"


def record_library_usage(library: str) -> None:
    """Called by library entry points (train/tune/data/serve/rllib)."""
    with _lock:
        _library_usages.add(library)


def record_extra_usage_tag(key: str, value: str) -> None:
    with _lock:
        _extra_tags[str(key)] = str(value)


def _report_path() -> str:
    return os.path.join(
        tempfile.gettempdir(), f"ray_tpu_usage_{os.getuid()}.jsonl")


def generate_report() -> Dict[str, Any]:
    """The reference's UsageStatsToReport shape, trimmed to what exists."""
    from ray_tpu.version import __version__

    report: Dict[str, Any] = {
        "schema_version": "0.1",
        "source": "ray_tpu",
        "version": __version__,
        "collect_timestamp_ms": int(time.time() * 1000),
        "os": os.uname().sysname.lower(),
        "python_version": ".".join(map(str, __import__("sys").version_info[:3])),
    }
    with _lock:
        report["library_usages"] = sorted(_library_usages)
        report["extra_usage_tags"] = dict(_extra_tags)
    try:
        import ray_tpu

        if ray_tpu.is_initialized():
            report["total_num_nodes"] = len(ray_tpu.nodes())
            report["cluster_resources"] = {
                k: float(v) for k, v in ray_tpu.cluster_resources().items()
            }
    except Exception:
        pass
    return report


def write_report() -> str | None:
    """Append one report line locally (the zero-egress 'ping'). Returns
    the path, or None when disabled."""
    if not usage_stats_enabled():
        return None
    path = _report_path()
    try:
        with open(path, "a") as f:
            f.write(json.dumps(generate_report()) + "\n")
        return path
    except OSError:
        return None


_reporter_started = False


def start_usage_reporter(interval_s: float = 3600.0) -> bool:
    """Background periodic recording (reference: usage stats agent on the
    head). No-op unless enabled."""
    global _reporter_started
    if not usage_stats_enabled() or _reporter_started:
        return False
    _reporter_started = True

    def loop():
        while True:
            write_report()
            time.sleep(interval_s)

    threading.Thread(target=loop, daemon=True).start()
    return True
