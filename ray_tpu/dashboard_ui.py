"""Dashboard web frontend: a single-file SPA over the REST API.

Reference parity: ``dashboard/client/`` — the reference ships a React/TS
client built to static assets the dashboard server serves. Same
architecture here at a sane scope: one self-contained HTML+JS page
(no build step, no dependencies) that polls the same ``/api/...`` routes
a human would otherwise curl, with tabs for cluster / nodes / actors /
tasks / objects / placement groups / jobs / serve and a live log tail
(cursor-incremental, ``/api/logs`` long-poll analog). All rendering goes
through ``textContent`` — cluster-user-controlled strings (names,
addresses, log lines) are never interpolated as HTML.
"""

INDEX_HTML = r"""<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  :root { --bg:#101418; --panel:#1a2026; --fg:#d8dee6; --dim:#8b98a5;
          --acc:#4fa3ff; --ok:#39c07b; --bad:#e25d5d; --warn:#e2b33d; }
  body { margin:0; background:var(--bg); color:var(--fg);
         font:13px/1.5 system-ui, sans-serif; }
  header { display:flex; align-items:baseline; gap:16px;
           padding:10px 16px; background:var(--panel);
           border-bottom:1px solid #2a323a; }
  header h1 { font-size:15px; margin:0; }
  header .dim { color:var(--dim); font-size:12px; }
  nav { display:flex; gap:2px; padding:0 12px; background:var(--panel); }
  nav button { background:none; border:none; color:var(--dim);
               padding:8px 12px; cursor:pointer; font:inherit;
               border-bottom:2px solid transparent; }
  nav button.active { color:var(--fg); border-color:var(--acc); }
  main { padding:14px 16px; }
  .tiles { display:flex; flex-wrap:wrap; gap:10px; margin-bottom:14px; }
  .tile { background:var(--panel); border:1px solid #2a323a;
          border-radius:6px; padding:10px 14px; min-width:130px; }
  .tile .v { font-size:20px; font-weight:600; }
  .tile .k { color:var(--dim); font-size:11px;
             text-transform:uppercase; letter-spacing:.05em; }
  table { border-collapse:collapse; width:100%; background:var(--panel);
          border:1px solid #2a323a; }
  th, td { text-align:left; padding:5px 10px;
           border-bottom:1px solid #242c34; font-size:12.5px; }
  th { color:var(--dim); font-weight:500; position:sticky; top:0;
       background:var(--panel); }
  td.mono, .mono { font-family:ui-monospace, monospace; font-size:12px; }
  .ALIVE, .FINISHED, .RUNNING_OK, .ok { color:var(--ok); }
  .DEAD, .FAILED, .ERROR, .bad { color:var(--bad); }
  .PENDING, .RESTARTING, .DRAINING, .warn { color:var(--warn); }
  #logs { background:#0b0e11; border:1px solid #2a323a; padding:10px;
          height:60vh; overflow-y:auto; white-space:pre-wrap;
          font-family:ui-monospace, monospace; font-size:12px; }
  .err { color:var(--bad); padding:8px 0; }
  input[type=text] { background:#0b0e11; color:var(--fg);
          border:1px solid #2a323a; border-radius:4px; padding:4px 8px; }
</style>
</head>
<body>
<header>
  <h1>ray_tpu</h1>
  <span class="dim" id="addr"></span>
  <span class="dim" id="updated"></span>
  <span class="err" id="error"></span>
</header>
<nav id="tabs"></nav>
<main>
  <div class="tiles" id="tiles"></div>
  <div id="view"></div>
</main>
<script>
"use strict";
const TABS = ["cluster", "nodes", "workers", "devices", "actors", "tasks",
              "objects", "memory", "placement_groups", "jobs", "serve",
              "train", "signals", "traces", "logs"];
let active = location.hash.slice(1) || "cluster";
let logCursor = 0;
const logBuf = [];

const $ = (id) => document.getElementById(id);

function el(tag, cls, text) {
  const e = document.createElement(tag);
  if (cls) e.className = cls;
  if (text !== undefined) e.textContent = String(text);
  return e;
}

function table(cols, rows, cellFn) {
  const t = el("table");
  const tr = el("tr");
  cols.forEach(c => tr.appendChild(el("th", "", c)));
  t.appendChild(tr);
  rows.forEach(r => {
    const row = el("tr");
    cols.forEach(c => row.appendChild(cellFn(r, c)));
    t.appendChild(row);
  });
  return t;
}

function stateCell(v) {
  const td = el("td", /^[A-Z_]+$/.test(String(v)) ? String(v) : "", v);
  return td;
}

async function api(path) {
  const r = await fetch(path);
  if (!r.ok) throw new Error(path + " -> " + r.status);
  return r.json();
}

function setTiles(items) {
  const box = $("tiles");
  box.replaceChildren();
  items.forEach(([k, v, cls]) => {
    const t = el("div", "tile");
    t.appendChild(el("div", "v " + (cls || ""), v));
    t.appendChild(el("div", "k", k));
    box.appendChild(t);
  });
}

function short(id) { return id && id.length > 14 ? id.slice(0, 14) + "…" : id; }

const RENDER = {
  async cluster() {
    const s = await api("/api/cluster_status");
    const res = s.resources_total || {}, avail = s.resources_available || {};
    setTiles([
      ["nodes alive", s.alive_nodes ?? "?",
       (s.dead_nodes || 0) > 0 ? "warn" : "ok"],
      ["nodes draining", s.draining_nodes ?? 0,
       (s.draining_nodes || 0) > 0 ? "warn" : ""],
      ["nodes dead", s.dead_nodes ?? 0,
       (s.dead_nodes || 0) > 0 ? "bad" : ""],
      ["CPU avail / total", `${avail.CPU ?? "?"} / ${res.CPU ?? "?"}`],
      ["head", s.head_address ?? "?"],
    ]);
    const rows = Object.entries(s).map(([k, v]) => ({k, v}));
    $("view").replaceChildren(table(["field", "value"], rows, (r, c) => {
      if (c === "field") return el("td", "", r.k);
      const td = el("td", "mono");
      td.textContent = typeof r.v === "object"
        ? JSON.stringify(r.v) : String(r.v);
      return td;
    }));
  },
  async nodes() {
    const [d, fleetD] = await Promise.all(
      [api("/api/nodes"), api("/api/autoscaler")]);
    const fleet = (fleetD || {}).autoscaler || {};
    const quarantined = new Set(Object.entries(fleet.types || {})
      .filter(([, t]) => t.quarantined).map(([name]) => name));
    $("view").replaceChildren(table(
      ["NodeID", "Address", "State", "Type", "Cause", "Resources",
       "StorePath"],
      d.nodes || [], (r, c) => {
        if (c === "State")
          return stateCell(r.State || (r.Alive ? "ALIVE" : "DEAD"));
        if (c === "Type") {
          // node_type/spot from the agent's labels; a quarantined type
          // (autoscaler boot-loop bench) is flagged inline.
          const labels = r.Labels || {};
          let txt = labels.node_type || "";
          if (labels.spot) txt += " (spot)";
          if (quarantined.has(labels.node_type)) txt += " [quarantined]";
          return el("td", "mono", txt);
        }
        if (c === "Cause") {
          // DRAINING shows its reason; DEAD its cause (crash vs drain).
          const td = el("td", "mono");
          td.textContent = r.DeathCause || r.DrainReason || "";
          return td;
        }
        if (c === "Resources") {
          const td = el("td", "mono");
          td.textContent = JSON.stringify(r.Resources || r.resources || {});
          return td;
        }
        const td = el("td", c === "NodeID" ? "mono" : "");
        td.textContent = c === "NodeID" ? short(r[c]) : (r[c] ?? "");
        return td;
      }));
  },
  async workers() {
    // Node reporter pane: per-worker telemetry merged with the log
    // index, plus on-demand log tail / stack dump / profile detail.
    const [statsD, logsD] = await Promise.all(
      [api("/api/worker_stats"), api("/api/worker_logs")]);
    const stats = {};
    (statsD.workers || []).forEach(s => { stats[s.worker_id] = s; });
    const rows = (logsD.workers || []).map(r =>
      ({...r, ...(stats[r.worker_id] || {})}));
    rows.sort((a, b) => (b.alive - a.alive)
      || (b.cpu_percent || 0) - (a.cpu_percent || 0));
    const alive = rows.filter(r => r.alive);
    setTiles([
      ["workers alive", alive.length],
      ["actors", alive.filter(r => r.is_actor).length],
      ["total cpu %", alive.reduce(
        (s, r) => s + (r.cpu_percent || 0), 0).toFixed(0)],
      ["total rss MiB", (alive.reduce(
        (s, r) => s + (r.rss_bytes || 0), 0) / 1048576).toFixed(0)],
    ]);
    const detail = el("pre", "");
    detail.id = "wdetail";
    detail.style.cssText = "background:#0b0e11;border:1px solid #2a323a;" +
      "padding:10px;max-height:45vh;overflow:auto;white-space:pre-wrap;" +
      "font:12px ui-monospace,monospace;";
    detail.textContent =
      "select log / stack / profile on a worker above";
    const show = async (label, path, isJson) => {
      detail.textContent = label + " …";
      try {
        const r = await fetch(path);
        const body = await r.text();
        detail.textContent = label + "\n\n" + (isJson
          ? JSON.stringify(JSON.parse(body), null, 1) : body);
      } catch (e) { detail.textContent = label + " failed: " + e; }
    };
    const t = table(
      ["worker_id", "node", "pid", "state", "cpu %", "rss MiB",
       "uptime s", "actor", "inspect"],
      rows, (r, c) => {
        if (c === "worker_id")
          { const td = el("td", "mono"); td.textContent = r.worker_id; return td; }
        if (c === "node")
          { const td = el("td", "mono"); td.textContent = short(r.node_id || ""); return td; }
        if (c === "pid") return el("td", "", r.pid ?? "");
        if (c === "state") return stateCell(r.alive ? "ALIVE" : "DEAD");
        if (c === "cpu %") return el("td", "", r.cpu_percent ?? "");
        if (c === "rss MiB") return el("td", "",
          r.rss_bytes ? (r.rss_bytes / 1048576).toFixed(1) : "");
        if (c === "uptime s") return el("td", "", r.uptime_s ?? "");
        if (c === "actor") return el("td", "mono",
          r.is_actor ? short(r.actor_id || "") : "");
        const td = el("td");
        const wid = encodeURIComponent(r.worker_id);
        [["out", `/api/worker_log?worker_id=${wid}&stream=out&tail=200`, true],
         ["err", `/api/worker_log?worker_id=${wid}&stream=err&tail=200`, true],
         ...(r.alive ? [
           ["stack", `/api/stack?worker_id=${wid}`, false],
           ["profile", `/api/profile?worker_id=${wid}&duration=0.5`, false],
         ] : [])].forEach(([label, path, isLog]) => {
          const b = el("button", "", label);
          b.style.cssText = "margin-right:4px;background:#0b0e11;" +
            "color:var(--fg);border:1px solid #2a323a;border-radius:3px;" +
            "cursor:pointer;font:11px inherit;padding:2px 6px;";
          b.onclick = async () => {
            if (!isLog) return show(`${label} ${r.worker_id}`, path, false);
            // worker_log returns JSON with a "data" field.
            detail.textContent = `${label} ${r.worker_id} …`;
            try {
              const d = await api(path);
              detail.textContent =
                `${label} ${r.worker_id} (${d.size} bytes)\n\n` + d.data;
            } catch (e) { detail.textContent = "failed: " + e; }
          };
          td.appendChild(b);
        });
        return td;
      });
    const wrap = el("div");
    wrap.appendChild(t);
    wrap.appendChild(el("div", "", " "));
    wrap.appendChild(detail);
    const old = $("wdetail");
    if (old && old.textContent && !old.textContent.startsWith("select"))
      detail.textContent = old.textContent;  // survive the 2s refresh
    $("view").replaceChildren(wrap);
  },
  async devices() {
    // JAX/XLA device telemetry: one row per (jax-loaded worker, device)
    // — HBM in use/peak/limit where the backend reports it — plus a
    // per-worker compile-counter row set. Stub workers (jax never
    // imported) are omitted; the tiles say how many reported.
    const d = await api("/api/device_stats");
    const snaps = (d.devices || []).filter(s => s.available);
    const rows = [];
    snaps.forEach(s => {
      const comp = s.compile || {};
      (s.devices || []).forEach(dev => rows.push({
        worker: s.worker_id, node: s.node_id,
        device: `${dev.platform}:${dev.id}`, kind: dev.device_kind,
        used: dev.bytes_in_use, peak: dev.peak_bytes_in_use,
        limit: dev.bytes_limit,
        compiles: comp.backend_compiles,
        compile_s: comp.compile_seconds,
      }));
    });
    const gib = v => v === undefined ? "" : (v / 2 ** 30).toFixed(2);
    const usedT = rows.reduce((a, r) => a + (r.used || 0), 0);
    const limitT = rows.reduce((a, r) => a + (r.limit || 0), 0);
    setTiles([
      ["jax workers", snaps.length],
      ["devices", rows.length],
      ["HBM used GiB", gib(usedT) || "0.00"],
      ["HBM total GiB", gib(limitT) || "0.00"],
    ]);
    if (!rows.length) {
      $("view").replaceChildren(el("div", "",
        "no jax-loaded workers reported device telemetry yet"));
      return;
    }
    $("view").replaceChildren(table(
      ["worker", "node", "device", "kind", "HBM used GiB",
       "HBM peak GiB", "HBM limit GiB", "compiles", "compile s"],
      rows, (r, c) => {
        if (c === "worker" || c === "node") {
          const td = el("td", "mono");
          td.textContent = c === "node" ? short(r.node || "") : r.worker;
          return td;
        }
        if (c === "HBM used GiB") return el("td", "", gib(r.used));
        if (c === "HBM peak GiB") return el("td", "", gib(r.peak));
        if (c === "HBM limit GiB") return el("td", "", gib(r.limit));
        if (c === "compile s") return el("td", "", r.compile_s ?? "");
        return el("td", c === "device" ? "mono" : "", r[c] ?? "");
      }));
  },
  async actors() {
    const d = await api("/api/actors");
    $("view").replaceChildren(table(
      ["actor_id", "class_name", "name", "state", "node_id", "pid",
       "num_restarts"],
      d.actors || [], (r, c) => {
        if (c === "state") return stateCell(r.state);
        const td = el("td",
          (c === "actor_id" || c === "node_id") ? "mono" : "");
        td.textContent = (c === "actor_id" || c === "node_id")
          ? short(r[c] || "") : (r[c] ?? "");
        return td;
      }));
  },
  async tasks() {
    const d = await api("/api/tasks?limit=500");
    const tasks = d.tasks || [];
    const byState = {};
    tasks.forEach(t => { byState[t.state] = (byState[t.state] || 0) + 1; });
    setTiles(Object.entries(byState).map(([k, v]) =>
      [k.toLowerCase(), v, k === "FAILED" ? "bad" : ""]));
    $("view").replaceChildren(table(
      ["task_id", "name", "type", "state", "node_id", "error"],
      tasks, (r, c) => {
        if (c === "state") return stateCell(r.state);
        const td = el("td",
          (c === "task_id" || c === "node_id") ? "mono" : "");
        td.textContent = (c === "task_id" || c === "node_id")
          ? short(r[c] || "") : (r[c] ?? "");
        return td;
      }));
  },
  async objects() {
    const d = await api("/api/objects?limit=500");
    setTiles([
      ["objects", d.total ?? (d.objects || []).length],
      ...(d.truncated ? [["showing", (d.objects || []).length, "warn"]]
                      : []),
    ]);
    $("view").replaceChildren(table(
      ["object_id", "size", "owner", "task", "callsite", "age s",
       "locations", "error"],
      d.objects || [], (r, c) => {
        const td = el("td",
          (c === "object_id" || c === "callsite") ? "mono" : "");
        if (c === "locations")
          td.textContent = (r.locations || []).map(short).join(", ");
        else if (c === "age s") td.textContent = r.age_s ?? "";
        else if (c === "owner") td.textContent = short(r.owner || "");
        else td.textContent = c === "object_id"
          ? short(r[c] || "") : (r[c] ?? "");
        return td;
      }));
  },
  async memory() {
    // Memory pane: cluster object-store rollup + per-node occupancy +
    // top objects with put-time attribution + the leak sweeper's flags.
    const [d, leaksD] = await Promise.all(
      [api("/api/memory_summary?group_by=callsite"),
       api("/api/memory_leaks")]);
    const t = d.totals || {};
    const mib = v => ((v || 0) / 1048576).toFixed(1);
    const leaks = leaksD.leaks || [];
    setTiles([
      ["store used MiB", mib(t.bytes_used)],
      ["capacity MiB", mib(t.bytes_capacity)],
      ["objects", t.objects ?? 0],
      ["evictions", t.evictions ?? 0, (t.evictions || 0) > 0 ? "warn" : ""],
      ["spilled MiB", mib(t.spilled_bytes)],
      ["leaks", leaks.length, leaks.length > 0 ? "bad" : "ok"],
    ]);
    const wrap = el("div");
    const nodes = Object.entries(d.nodes || {}).map(([id, n]) =>
      ({node: id, ...n}));
    wrap.appendChild(el("h3", "", "per-node occupancy"));
    wrap.appendChild(table(
      ["node", "used MiB", "capacity MiB", "occupancy", "objects",
       "evictions", "spilled MiB", "oom reports"],
      nodes, (r, c) => {
        if (c === "node")
          { const td = el("td", "mono"); td.textContent = short(r.node); return td; }
        if (c === "used MiB") return el("td", "", mib(r.bytes_used));
        if (c === "capacity MiB") return el("td", "", mib(r.bytes_capacity));
        if (c === "occupancy") return el("td",
          (r.occupancy || 0) > 0.8 ? "warn" : "",
          ((r.occupancy || 0) * 100).toFixed(0) + "%");
        if (c === "spilled MiB") return el("td", "", mib(r.spilled_bytes));
        if (c === "oom reports") {
          const td = el("td", "mono");
          td.textContent = (r.oom_reports || []).join(", ");
          return td;
        }
        return el("td", "", r[c.replace(" ", "_")] ?? r[c] ?? "");
      }));
    if (leaks.length) {
      wrap.appendChild(el("h3", "bad", "leaked objects"));
      wrap.appendChild(table(
        ["object_id", "kind", "size MiB", "age s", "task", "owner",
         "callsite"],
        leaks, (r, c) => {
          const td = el("td",
            (c === "object_id" || c === "callsite") ? "mono" : "");
          if (c === "size MiB") td.textContent = mib(r.size);
          else if (c === "age s") td.textContent = r.age_s ?? "";
          else if (c === "object_id") td.textContent = short(r.object_id);
          else if (c === "owner") td.textContent = short(r.owner || "");
          else td.textContent = r[c] ?? "";
          return td;
        }));
    }
    wrap.appendChild(el("h3", "", "top objects by size"));
    wrap.appendChild(table(
      ["object_id", "size MiB", "refs", "pinned", "task", "owner",
       "callsite", "age s", "nodes"],
      d.top_objects || [], (r, c) => {
        const td = el("td",
          (c === "object_id" || c === "callsite") ? "mono" : "");
        if (c === "size MiB") td.textContent = mib(r.size);
        else if (c === "refs")
          td.textContent = r.refcount ?? r.ref_holders ?? "";
        else if (c === "pinned") td.textContent = r.pinned ? "yes" : "";
        else if (c === "age s") td.textContent = r.age_s ?? "";
        else if (c === "object_id") td.textContent = short(r.object_id);
        else if (c === "owner") td.textContent = short(r.owner || "");
        else if (c === "nodes")
          td.textContent = (r.nodes || []).map(short).join(", ");
        else td.textContent = r[c] ?? "";
        return td;
      }));
    wrap.appendChild(el("h3", "",
      "bytes by " + (d.group_by || "callsite")));
    wrap.appendChild(table(
      ["key", "bytes MiB", "objects"],
      d.groups || [], (r, c) => {
        if (c === "bytes MiB") return el("td", "", mib(r.bytes));
        return el("td", c === "key" ? "mono" : "", r[c] ?? "");
      }));
    $("view").replaceChildren(wrap);
  },
  async placement_groups() {
    const d = await api("/api/placement_groups");
    let pgs = d.placement_groups || [];
    if (!Array.isArray(pgs))  // head returns {pg_id: info}
      pgs = Object.entries(pgs).map(([id, info]) =>
        ({pg_id: id, ...info}));
    $("view").replaceChildren(table(
      ["pg_id", "name", "state", "strategy", "bundles", "live",
       "reschedules"],
      pgs, (r, c) => {
        if (c === "state") return stateCell(r.state);
        const td = el("td", c === "pg_id" ? "mono" : "");
        if (c === "bundles")
          td.textContent = JSON.stringify(r.bundles || []);
        else if (c === "live")
          td.textContent = r.bundles
            ? `${(r.live_bundles || []).length}/${r.bundles.length}` : "";
        else if (c === "reschedules")
          td.textContent = r.reschedules ?? 0;
        else td.textContent = c === "pg_id"
          ? short(r.pg_id || r.id || "") : (r[c] ?? "");
        return td;
      }));
  },
  async jobs() {
    const d = await api("/api/jobs");
    const jobs = d.jobs || [];
    $("view").replaceChildren(table(
      ["job_id", "status", "entrypoint", "message"],
      jobs, (r, c) => {
        if (c === "status") return stateCell(r.status);
        const td = el("td", c === "job_id" ? "mono" : "");
        td.textContent = r[c] ?? "";
        return td;
      }));
  },
  async serve() {
    // Serve pane (memory-pane shape): SLO tiles + per-deployment
    // latency/shed table from the request-path plane, then the raw
    // application listing.
    // ?window= answers QPS from the head's metrics history ring —
    // no stall by construction (the route forbids the legacy
    // sleeping double-scrape); without a ring the field is simply
    // absent and the column shows "—".
    const [s, d] = await Promise.all(
      [api("/api/serve_stats?window=30"), api("/api/serve/applications")]);
    const deps = Object.entries(s.deployments || {})
      .map(([name, info]) => ({name, ...info}));
    const totals = deps.reduce((acc, r) => {
      const req = r.requests || {};
      acc.ok += req.ok || 0; acc.err += req.error || 0;
      acc.shed += Object.values(r.shed || {}).reduce((a, b) => a + b, 0);
      acc.ongoing += r.ongoing || 0;
      return acc;
    }, {ok: 0, err: 0, shed: 0, ongoing: 0});
    const worstP99 = Math.max(0, ...deps.map(r => r.p99_ms || 0));
    setTiles([
      ["deployments", deps.length],
      ["requests ok", totals.ok],
      ["errors", totals.err, totals.err > 0 ? "bad" : "ok"],
      ["shed (503)", totals.shed, totals.shed > 0 ? "warn" : ""],
      ["in flight", totals.ongoing],
      ["worst p99 ms", worstP99 ? worstP99.toFixed(1) : "—"],
    ]);
    const wrap = el("div");
    wrap.appendChild(el("h3", "", "per-deployment SLO"));
    wrap.appendChild(table(
      ["deployment", "replicas", "qps", "p50 ms", "p99 ms", "ok",
       "errors", "shed", "ongoing", "queued", "phases"],
      deps, (r, c) => {
        const req = r.requests || {};
        if (c === "deployment") return el("td", "", r.name);
        if (c === "replicas") return el("td", "", r.replicas ?? "?");
        if (c === "qps") return el("td", "", r.qps ?? "—");
        if (c === "p50 ms") return el("td", "", r.p50_ms ?? "—");
        if (c === "p99 ms") return el("td",
          (r.p99_ms || 0) > 1000 ? "warn" : "", r.p99_ms ?? "—");
        if (c === "ok") return el("td", "", req.ok || 0);
        if (c === "errors") return el("td",
          (req.error || 0) > 0 ? "bad" : "", req.error || 0);
        if (c === "shed") {
          const n = Object.values(r.shed || {})
            .reduce((a, b) => a + b, 0);
          return el("td", n > 0 ? "warn" : "", n);
        }
        if (c === "ongoing") return el("td", "", r.ongoing || 0);
        if (c === "queued") return el("td", "", r.queued || 0);
        const td = el("td", "mono");
        td.textContent = Object.entries(r.phases || {})
          .map(([p, v]) => `${p}:${v.p50_ms}ms`).join(" ");
        return td;
      }));
    const apps = d.applications || {};
    const rows = Object.entries(apps).flatMap(([app, info]) =>
      (info.deployments ? Object.entries(info.deployments) : [["", info]])
        .map(([dep, di]) => ({app, dep, info: di})));
    wrap.appendChild(el("h3", "", "applications"));
    wrap.appendChild(table(
      ["application", "deployment", "detail"],
      rows, (r, c) => {
        if (c === "application") return el("td", "", r.app);
        if (c === "deployment") return el("td", "", r.dep);
        const td = el("td", "mono");
        td.textContent = JSON.stringify(r.info);
        return td;
      }));
    $("view").replaceChildren(wrap);
  },
  async signals() {
    // Signal-plane pane: SLO burn-rate table + the `top` rollup, all
    // windowed queries over the head's metrics history ring (the API
    // route performs zero sleeps — pure ring reads).
    const d = await api("/api/signals?window=60");
    const slo = d.slo || {}, top = d.top || {};
    if (slo.ok === false) {
      setTiles([["signal plane", slo.error || "disabled", "warn"]]);
      $("view").replaceChildren(
        el("p", "dim", "enable with RAY_TPU_SIGNAL_SCRAPE_INTERVAL_S"));
      return;
    }
    const slos = Object.entries(slo.slos || {})
      .map(([name, s]) => ({name, ...s}));
    const burning = slos.filter(s => s.state === "burning").length;
    const warning = slos.filter(s => s.state === "warning").length;
    const evict = Object.values(top.evictions || {})
      .reduce((a, b) => a + b, 0);
    setTiles([
      ["series", top.series ?? slo.series ?? "?"],
      ["evictions", evict, evict > 0 ? "warn" : ""],
      ["SLOs", slos.length],
      ["burning", burning, burning > 0 ? "bad" : "ok"],
      ["warning", warning, warning > 0 ? "warn" : ""],
    ]);
    const wrap = el("div");
    wrap.appendChild(el("h3", "", "SLO burn rate"));
    wrap.appendChild(table(
      ["name", "state", "value", "threshold", "window s", "breaches",
       "expr"],
      slos, (r, c) => {
        if (c === "name") return el("td", "", r.name);
        if (c === "state") return el("td",
          r.state === "burning" ? "bad"
            : r.state === "warning" ? "warn" : "ok", r.state);
        if (c === "value") return el("td", "mono",
          r.value != null ? Number(r.value).toPrecision(4) : "—");
        if (c === "threshold") return el("td", "mono",
          `${r.op} ${r.threshold}`);
        if (c === "window s") return el("td", "", r.window_s);
        if (c === "breaches") return el("td", "", r.breach_streak);
        return el("td", "mono", r.expr);
      }));
    const nodes = Object.entries(top.nodes || {})
      .map(([id, n]) => ({id, ...n}));
    wrap.appendChild(el("h3", "", "nodes (windowed)"));
    wrap.appendChild(table(
      ["node", "cpu %", "rss MB", "store", "workers"],
      nodes, (r, c) => {
        if (c === "node") return el("td", "mono", short(r.id));
        if (c === "cpu %") return el("td", "", r.cpu_percent ?? "—");
        if (c === "rss MB") return el("td", "",
          r.rss_bytes != null ? (r.rss_bytes / 1e6).toFixed(1) : "—");
        if (c === "store") return el("td",
          (r.store_occupancy || 0) > 0.8 ? "warn" : "",
          r.store_occupancy != null
            ? (r.store_occupancy * 100).toFixed(1) + "%" : "—");
        return el("td", "", r.workers ?? "—");
      }));
    const deps = Object.entries(top.serve || {})
      .map(([name, s]) => ({name, ...s}));
    if (deps.length) {
      wrap.appendChild(el("h3", "", "serve (windowed)"));
      wrap.appendChild(table(
        ["deployment", "qps", "shed", "ttft p50 ms", "itl p50 ms",
         "latency p50 ms"],
        deps, (r, c) => {
          const ms = (v) => v != null ? (v * 1e3).toFixed(1) : "—";
          if (c === "deployment") return el("td", "", r.name);
          if (c === "qps") return el("td", "", r.qps ?? "—");
          if (c === "shed") return el("td",
            (r.shed_ratio || 0) > 0 ? "warn" : "",
            r.shed_ratio != null
              ? (r.shed_ratio * 100).toFixed(2) + "%" : "—");
          if (c === "ttft p50 ms") return el("td", "", ms(r.ttft_p50_s));
          if (c === "itl p50 ms") return el("td", "", ms(r.itl_p50_s));
          return el("td", "", ms(r.latency_p50_s));
        }));
    }
    $("view").replaceChildren(wrap);
  },
  async traces() {
    // Flight-recorder pane: store health tiles, the windowed TTFT
    // decomposition, and kept-trace rows — click a trace id to render
    // its assembled cross-process span tree inline.
    const d = await api("/api/traces?window=300");
    const st = d.stats || {}, ttft = d.ttft || {};
    const drops = Object.values(st.dropped || {})
      .reduce((a, b) => a + b, 0);
    const ms = (v) => v != null ? (v * 1e3).toFixed(1) : "—";
    setTiles([
      ["kept", st.kept ?? 0],
      ["assembled", st.assembled_total ?? 0],
      ["pending", st.pending ?? 0],
      ["dropped", drops, drops > 0 ? "warn" : ""],
      ["ttft p50 ms", ms(ttft.ttft_p50_s)],
      ["dominant", ttft.dominant || "—"],
    ]);
    const wrap = el("div");
    const phases = Object.entries(ttft.phases || {})
      .map(([name, p]) => ({name, ...p}))
      .sort((a, b) => (b.p50_s || 0) - (a.p50_s || 0));
    if (phases.length) {
      wrap.appendChild(el("h3", "",
        `ttft decomposition (${ttft.traces} traces, 5m window)`));
      wrap.appendChild(table(
        ["phase", "p50 ms", "p99 ms", "mean ms", "count"],
        phases, (r, c) => {
          if (c === "phase") return el("td", "", r.name);
          if (c === "p50 ms") return el("td", "mono", ms(r.p50_s));
          if (c === "p99 ms") return el("td", "mono", ms(r.p99_s));
          if (c === "mean ms") return el("td", "mono", ms(r.mean_s));
          return el("td", "", r.count);
        }));
    }
    wrap.appendChild(el("h3", "", "kept traces"));
    const pre = el("pre", "mono", "");
    wrap.appendChild(table(
      ["trace", "root", "dur ms", "spans", "kept", "dominant"],
      d.traces || [], (r, c) => {
        if (c === "trace") {
          const td = el("td", "mono");
          const a = el("a", "", r.trace_id.slice(0, 16) + "…");
          a.href = "#traces";
          a.onclick = async (ev) => {
            ev.preventDefault();
            const tr = await api("/api/trace?id=" + r.trace_id);
            const spans = tr.spans || [];
            const byId = {};
            spans.forEach(s => { byId[s.span_id] = s; });
            const depth = (s) => {
              let n = 0, p = s.parent_id;
              while (p && byId[p]) { n++; p = byId[p].parent_id; }
              return n;
            };
            const t0 = Math.min(
              ...spans.map(s => s.start_ns || Infinity));
            pre.textContent = "trace " + tr.trace_id + "\n" +
              spans.slice()
                .sort((a2, b2) => (a2.start_ns || 0) - (b2.start_ns || 0))
                .map(s => "  ".repeat(depth(s)) + s.name +
                  "  [+" + (((s.start_ns || t0) - t0) / 1e6).toFixed(1)
                  + "ms  " + (((s.end_ns || s.start_ns || 0)
                  - (s.start_ns || 0)) / 1e6).toFixed(1) + "ms  "
                  + (s.node_id || ("pid " + (s.pid ?? "?"))) + "]"
                  + ((s.status || "OK") !== "OK"
                    ? "  !! " + s.status : ""))
                .join("\n");
          };
          td.appendChild(a);
          return td;
        }
        if (c === "root") return el("td", "mono", r.root || "?");
        if (c === "dur ms") return el("td",
          r.errored ? "bad" : "", (r.duration_s * 1e3).toFixed(1));
        if (c === "spans") return el("td", "", r.spans);
        if (c === "kept") return el("td", "", r.kept_because);
        return el("td", "", r.dominant || "—");
      }));
    wrap.appendChild(pre);
    $("view").replaceChildren(wrap);
  },
  async train() {
    // Training goodput pane (serve-pane shape): stall-fraction /
    // goodput tiles, per-trial step-phase table with the downtime
    // ledger, then the input-pipeline stage rollup.
    const [t, d] = await Promise.all(
      [api("/api/train_stats"), api("/api/data_stats")]);
    const trials = Object.entries(t.trials || {})
      .map(([name, info]) => ({name, ...info}));
    const reports = trials.reduce((a, r) => a + (r.reports || 0), 0);
    const downtime = trials.reduce((a, r) =>
      a + Object.values(r.downtime_s || {}).reduce((x, y) => x + y, 0),
      0);
    const worstSkew = Math.max(0, ...trials.map(r => r.rank_skew || 0));
    const stall = d.stall_fraction;
    setTiles([
      ["trials", trials.length],
      ["reports", reports],
      ["stall fraction", stall != null
        ? (stall * 100).toFixed(1) + "%" : "—",
        stall > 0.3 ? "warn" : ""],
      ["downtime s", downtime.toFixed(1),
        downtime > 0 ? "warn" : ""],
      ["worst rank skew", worstSkew ? worstSkew.toFixed(2) + "x" : "—"],
    ]);
    const wrap = el("div");
    wrap.appendChild(el("h3", "", "per-trial goodput"));
    wrap.appendChild(table(
      ["trial", "reports", "goodput %", "rank skew", "downtime",
       "phases (p50)"],
      trials, (r, c) => {
        if (c === "trial") return el("td", "", r.name);
        if (c === "reports") return el("td", "", r.reports || 0);
        if (c === "goodput %") return el("td",
          (r.goodput_pct || 100) < 95 ? "warn" : "",
          r.goodput_pct ?? "—");
        if (c === "rank skew") return el("td", "", r.rank_skew ?? "—");
        if (c === "downtime") {
          const td = el("td", "mono");
          td.textContent = Object.entries(r.downtime_s || {})
            .map(([cz, s]) => `${cz}:${s.toFixed(1)}s`).join(" ");
          return td;
        }
        const td = el("td", "mono");
        td.textContent = Object.entries(r.phases || {})
          .map(([p, v]) => `${p}:${v.p50_ms}ms`).join(" ");
        return td;
      }));
    const anatRows = trials.flatMap(r => {
      const anat = r.anatomy || {};
      return Object.entries(anat.ranks || {}).map(([rank, phases]) => ({
        trial: r.name, rank, phases,
        mfu: (anat.mfu_pct || {})[rank],
        straggler: anat.straggler,
      }));
    });
    if (anatRows.length) {
      wrap.appendChild(el("h3", "", "step anatomy (per rank)"));
      wrap.appendChild(table(
        ["trial", "rank", "mfu %", "data_wait", "host", "compute",
         "sync", "verdict"],
        anatRows, (r, c) => {
          if (c === "trial") return el("td", "", r.trial);
          if (c === "rank") return el("td", "", r.rank);
          if (c === "mfu %") return el("td",
            r.mfu != null && r.mfu < 40 ? "warn" : "",
            r.mfu != null ? r.mfu.toFixed(1) : "—");
          if (["data_wait", "host", "compute", "sync"].includes(c)) {
            const v = (r.phases || {})[c];
            return el("td", "mono",
              v != null ? (v * 1e3).toFixed(1) + "ms" : "—");
          }
          const s = r.straggler;
          if (!s || String(s.rank) !== String(r.rank)
              || s.cause === "balanced")
            return el("td", "", "—");
          return el("td", "warn",
            s.cause + " +" + ((s.excess_s || 0) * 1e3).toFixed(1)
            + "ms");
        }));
    }
    const stages = Object.entries(d.stages || {})
      .map(([name, info]) => ({name, ...info}));
    wrap.appendChild(el("h3", "", "input-pipeline stages"));
    wrap.appendChild(table(
      ["stage", "executions", "blocks", "rows", "wall ms", "MB/s"],
      stages, (r, c) => {
        if (c === "stage") return el("td", "", r.name);
        if (c === "executions") return el("td", "", r.executions || 0);
        if (c === "blocks") return el("td", "", r.blocks ?? "—");
        if (c === "rows") return el("td", "", r.rows_total ?? "—");
        if (c === "wall ms") return el("td", "", r.wall_ms ?? "—");
        return el("td", "",
          r.bytes_per_s ? (r.bytes_per_s / 1e6).toFixed(1) : "—");
      }));
    const it = d.iterator || {};
    const iterRows = ["wait", "user", "transfer"]
      .filter(p => it[p]).map(p => ({phase: p, ...it[p]}));
    if (iterRows.length) {
      wrap.appendChild(el("h3", "", "consumer loop"));
      wrap.appendChild(table(
        ["phase", "batches", "p50 ms", "mean ms"],
        iterRows, (r, c) => {
          if (c === "phase") return el("td", "", r.phase);
          if (c === "batches") return el("td", "", r.count);
          if (c === "p50 ms") return el("td", "", r.p50_ms ?? "—");
          return el("td", "", r.mean_ms ?? "—");
        }));
    }
    $("view").replaceChildren(wrap);
  },
  async logs() {
    if (!$("logs")) {
      const pre = el("div"); pre.id = "logs";
      $("view").replaceChildren(pre);
      logBuf.forEach(line => pre.appendChild(el("div", "", line)));
    }
    const d = await api(`/api/logs?after_seq=${logCursor}&limit=500`);
    logCursor = d.cursor ?? logCursor;
    const pre = $("logs");
    // Autoscroll ONLY when the user was already at the bottom —
    // scrollback must survive the 2s refresh cadence.
    const pinned = pre.scrollHeight - pre.scrollTop - pre.clientHeight < 40;
    (d.entries || []).forEach(e => {
      const line = typeof e === "string" ? e
        : `[${e.pid ?? "?"}@${short(e.node_id || "")}] ${e.line ?? JSON.stringify(e)}`;
      logBuf.push(line);
      pre.appendChild(el("div", "", line));
    });
    while (logBuf.length > 3000) { logBuf.shift(); pre.firstChild.remove(); }
    if (pinned) pre.scrollTop = pre.scrollHeight;
  },
};

function buildTabs() {
  const nav = $("tabs");
  TABS.forEach(t => {
    const b = el("button", t === active ? "active" : "", t.replace("_", " "));
    b.onclick = () => {
      active = t; location.hash = t;
      [...nav.children].forEach(x => x.classList.remove("active"));
      b.classList.add("active");
      if (t !== "logs") $("view").replaceChildren();
      setTiles([]);
      refresh();
    };
    nav.appendChild(b);
  });
}

async function refresh() {
  try {
    await RENDER[active]();
    $("error").textContent = "";
    $("updated").textContent =
      "updated " + new Date().toLocaleTimeString();
  } catch (e) {
    $("error").textContent = String(e);
  }
}

buildTabs();
$("addr").textContent = location.host;
refresh();
setInterval(refresh, 2000);
</script>
</body>
</html>
"""
