"""Core runtime: IDs, object refs, backends (local in-process / cluster).

Mirrors the reference's core split (SURVEY.md §2.1): the ``Backend`` protocol
is the equivalent of the CoreWorker surface (submit/execute, Put/Get/Wait,
actor lifecycle — ``src/ray/core_worker/core_worker.h:249``); the local
backend is the single-process implementation, the cluster backend (M3) spans
a control plane + node daemons.
"""
