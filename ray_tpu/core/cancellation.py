"""Cooperative task cancellation primitives.

The executing side of ``ray.cancel`` (reference: CancelTask in
``src/ray/protobuf/core_worker.proto``, delivered as KeyboardInterrupt to
the worker's execution thread). CPython lets us raise an exception in a
specific thread at its next bytecode boundary — the right unit here, since
one worker process may run tasks on several executor threads (threaded
actors). The caveat matches the reference's: code blocked in a C call
(socket recv, jitted computation) is not interrupted until it returns to
the interpreter; ``force=True`` escalates to killing the worker process.
"""

from __future__ import annotations

import ctypes


def inject_async_exc(thread_ident: int, exc_type) -> None:
    """Raise ``exc_type`` in the thread with ``thread_ident``; ``None``
    clears a pending not-yet-delivered injection (used when a cancel races
    task completion, so the stale exception cannot land on the thread's
    next task)."""
    ctypes.pythonapi.PyThreadState_SetAsyncExc(
        ctypes.c_ulong(thread_ident),
        ctypes.py_object(exc_type) if exc_type is not None else None,
    )


class CancelRegistry:
    """Tracks cancelled task ids and which thread is running which task.

    Shared by the cluster worker process and the local backend: ``cancel``
    before the task runs parks the id (the runner checks ``begin`` and
    skips execution); ``cancel`` mid-run injects into the executor thread;
    ``end`` clears a raced, undelivered injection.
    """

    _MAX_PARKED = 4096  # late cancels for finished tasks must not leak

    def __init__(self, lock):
        self._lock = lock
        # Insertion-ordered so the bound evicts oldest-first (a parked id
        # whose task already finished is never consumed by begin()).
        self.cancelled: dict[str, bool] = {}
        self._running: dict[str, int] = {}

    def cancel(self, task_id: str, exc_type) -> bool:
        """Returns True if the task was running (exception injected).

        The injection happens UNDER the lock: if it raced ahead of it, the
        task could finish and ``end`` could run its clear-pending pass
        before the injection landed — delivering the stale exception to
        whatever the thread runs next."""
        with self._lock:
            self.cancelled[task_id] = True
            while len(self.cancelled) > self._MAX_PARKED:
                self.cancelled.pop(next(iter(self.cancelled)))
            tid = self._running.get(task_id)
            if tid is not None:
                inject_async_exc(tid, exc_type)
                return True
        return False

    def begin(self, task_id: str, thread_ident: int) -> bool:
        """Register the runner; False means already cancelled — skip
        (the id is consumed so the set stays bounded)."""
        with self._lock:
            if task_id in self.cancelled:
                self.cancelled.pop(task_id, None)
                return False
            self._running[task_id] = thread_ident
        return True

    def end(self, task_id: str, thread_ident: int) -> None:
        with self._lock:
            self._running.pop(task_id, None)
            if task_id in self.cancelled:
                self.cancelled.pop(task_id, None)
                inject_async_exc(thread_ident, None)
