"""In-process backend: tasks on a thread pool, actors on dedicated threads.

This is the ``ray.init(local_mode=...)`` analog but with real asynchrony —
tasks run concurrently and ObjectRefs are genuine futures. It implements the
same ``Backend`` surface the cluster backend (multi-process) implements,
so the public API code is backend-agnostic — preserving the reference's
invariant that libraries sit only on tasks/actors/objects (SURVEY.md §1).

Semantics mirrored from the reference:
* Object table entries are reference-counted against live ``ObjectRef``
  handles plus in-flight task-argument pins, and freed when the count drops
  to zero (``src/ray/core_worker/reference_count.h:61``).
* Actor-task dependencies are resolved on the *caller* side before the call
  is enqueued to the actor, preserving per-caller submission order — the
  ``DependencyResolver`` + sequence-number design of
  ``direct_actor_task_submitter.h``. The actor's execution thread never
  blocks on an unresolved argument.
"""

from __future__ import annotations

import queue
import threading
import traceback
import weakref
from typing import Any, Callable, Sequence

from ray_tpu.core import ids
from ray_tpu.core.object_ref import (
    ActorError,
    GetTimeoutError,
    ObjectRef,
    ObjectLostError,
    TaskError,
)


class _DaemonPool:
    """Thread pool with daemon threads: in-flight tasks never block
    interpreter exit (cf. the raylet worker pool being killable)."""

    def __init__(self, max_workers: int):
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._max = max_workers
        self._count = 0
        self._idle = 0
        self._lock = threading.Lock()

    def submit(self, fn: Callable, *args) -> None:
        self._q.put((fn, args))
        with self._lock:
            if self._idle == 0 and self._count < self._max:
                self._count += 1
                threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            with self._lock:
                self._idle += 1
            try:
                fn, args = self._q.get()
            finally:
                with self._lock:
                    self._idle -= 1
            try:
                fn(*args)
            except BaseException:  # noqa: BLE001 — pool must survive anything
                traceback.print_exc()


class _Entry:
    """Object-table slot: either a concrete value or a pending event."""

    __slots__ = ("event", "value", "error")

    def __init__(self):
        self.event = threading.Event()
        self.value = None
        self.error: BaseException | None = None

    def set(self, value):
        self.value = value
        self.event.set()

    def set_error(self, err: BaseException):
        self.error = err
        self.event.set()


class _ActorState:
    def __init__(self, instance, max_concurrency: int, name: str | None):
        self.instance = instance
        self.name = name
        self.dead = False
        self.death_cause: str | None = None
        self.queue: queue.Queue = queue.Queue()
        self.max_concurrency = max_concurrency
        self.threads: list[threading.Thread] = []
        self.lock = threading.Lock()
        # Per-caller-thread submission chains: tail event of the last deferred
        # dispatch, so a caller's calls enqueue in submission order even when
        # argument resolution happens off-thread.
        self.caller_chains: dict[int, threading.Event] = {}


_POISON = object()


class LocalBackend:
    """Single-process task/actor/object runtime."""

    def __init__(self, num_cpus: int | None = None):
        import os

        self._ncpu = num_cpus or os.cpu_count() or 8
        # Oversized pool: tasks may block waiting on upstream deps.
        self._pool = _DaemonPool(max_workers=max(64, self._ncpu * 8))
        self._objects: dict[str, _Entry] = {}
        self._refcounts: dict[str, int] = {}
        self._objects_lock = threading.Lock()
        self._actors: dict[str, _ActorState] = {}
        self._named_actors: dict[str, str] = {}
        self._lock = threading.Lock()
        self._shutdown = False

    # -- ref counting ------------------------------------------------------

    def make_ref(self, oid: str) -> ObjectRef:
        """Mint an ObjectRef whose lifetime pins the object-table entry."""
        with self._objects_lock:
            self._refcounts[oid] = self._refcounts.get(oid, 0) + 1
        ref = ObjectRef(oid)
        weakref.finalize(ref, self._decref, oid)
        return ref

    def _incref(self, oid: str):
        with self._objects_lock:
            self._refcounts[oid] = self._refcounts.get(oid, 0) + 1

    def _decref(self, oid: str):
        with self._objects_lock:
            n = self._refcounts.get(oid, 0) - 1
            if n <= 0:
                self._refcounts.pop(oid, None)
                e = self._objects.get(oid)
                # Free only resolved entries; a pending task result with no
                # handles left is freed when the task completes (see
                # _store_returns).
                if e is not None and e.event.is_set():
                    del self._objects[oid]
            else:
                self._refcounts[oid] = n

    # -- object plane -----------------------------------------------------

    def _entry(self, oid: str) -> _Entry:
        with self._objects_lock:
            e = self._objects.get(oid)
            if e is None:
                e = self._objects[oid] = _Entry()
            return e

    def put(self, value: Any) -> ObjectRef:
        oid = ids.new_object_id()
        ref = self.make_ref(oid)
        self._entry(oid).set(value)
        return ref

    def get(self, refs: Sequence[ObjectRef], timeout: float | None = None):
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for r in refs:
            with self._objects_lock:
                e = self._objects.get(r.id)
            if e is None:
                if self._refcounts.get(r.id):
                    e = self._entry(r.id)
                else:
                    raise ObjectLostError(
                        f"object {r.id[:16]}… was freed (all references dropped)"
                    )
            remaining = (
                None if deadline is None else max(0.0, deadline - time.monotonic())
            )
            if not e.event.wait(remaining):
                raise GetTimeoutError(f"ray_tpu.get timed out on {r}")
            if e.error is not None:
                raise e.error
            out.append(e.value)
        return out

    def wait(
        self,
        refs: Sequence[ObjectRef],
        num_returns: int,
        timeout: float | None,
        fetch_local: bool = True,
    ):
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        ready: list[ObjectRef] = []
        pending = list(refs)
        while len(ready) < num_returns:
            progressed = False
            for r in list(pending):
                if self._entry(r.id).event.is_set():
                    ready.append(r)
                    pending.remove(r)
                    progressed = True
                    if len(ready) >= num_returns:
                        break
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            if not progressed:
                time.sleep(0.001)
        return ready, pending

    # -- task plane -------------------------------------------------------

    def _pin_ref_args(self, args, kwargs) -> list[str]:
        """Pin ObjectRef arguments for the duration of a task (the lineage-
        pinning analog of TaskManager, ``task_manager.h:87``)."""
        pins = [a.id for a in args if isinstance(a, ObjectRef)]
        pins += [v.id for v in kwargs.values() if isinstance(v, ObjectRef)]
        for oid in pins:
            self._incref(oid)
        return pins

    def _unpin(self, pins: list[str]):
        for oid in pins:
            self._decref(oid)

    def _resolve_args(self, args, kwargs):
        args = [self.get([a])[0] if isinstance(a, ObjectRef) else a for a in args]
        kwargs = {
            k: self.get([v])[0] if isinstance(v, ObjectRef) else v
            for k, v in kwargs.items()
        }
        return args, kwargs

    def _store_returns(self, oids: list[str], result, num_returns: int):
        if num_returns == 1:
            self._entry(oids[0]).set(result)
        else:
            vals = list(result)
            if len(vals) != num_returns:
                raise ValueError(
                    f"task declared num_returns={num_returns} but returned "
                    f"{len(vals)} values"
                )
            for oid, v in zip(oids, vals):
                self._entry(oid).set(v)
        self._gc_unreferenced(oids)

    def _store_error(self, oids: list[str], err: BaseException):
        for oid in oids:
            self._entry(oid).set_error(err)
        self._gc_unreferenced(oids)

    def _gc_unreferenced(self, oids: list[str]):
        with self._objects_lock:
            for oid in oids:
                if not self._refcounts.get(oid):
                    self._objects.pop(oid, None)

    def submit_task(
        self,
        func: Callable,
        args: tuple,
        kwargs: dict,
        *,
        num_returns: int = 1,
        max_retries: int = 0,
        retry_exceptions: bool | tuple = False,
        name: str = "",
        **_options,
    ) -> list[ObjectRef]:
        task_id = ids.new_task_id()
        oids = [ids.object_id_for(task_id, i) for i in range(num_returns)]
        refs = [self.make_ref(o) for o in oids]
        fname = name or getattr(func, "__name__", "task")
        pins = self._pin_ref_args(args, kwargs)

        def run():
            attempts = 0
            try:
                while True:
                    try:
                        a, kw = self._resolve_args(args, kwargs)
                        result = func(*a, **kw)
                        self._store_returns(oids, result, num_returns)
                        return
                    except BaseException as e:  # noqa: BLE001 — stored, not dropped
                        retriable = retry_exceptions is True or (
                            isinstance(retry_exceptions, tuple)
                            and isinstance(e, retry_exceptions)
                        )
                        if retriable and attempts < max_retries:
                            attempts += 1
                            continue
                        if isinstance(e, (TaskError, ActorError)):
                            self._store_error(oids, e)
                        else:
                            self._store_error(
                                oids,
                                TaskError(fname, traceback.format_exc(), repr(e)),
                            )
                        return
            finally:
                self._unpin(pins)

        self._pool.submit(run)
        return refs

    # -- actor plane ------------------------------------------------------

    def create_actor(
        self,
        cls: type,
        args: tuple,
        kwargs: dict,
        *,
        name: str | None = None,
        max_concurrency: int = 1,
        **_options,
    ) -> str:
        actor_id = ids.new_actor_id()
        with self._lock:
            if name is not None:
                if name in self._named_actors:
                    raise ValueError(f"actor name {name!r} already taken")
                self._named_actors[name] = actor_id
        state = _ActorState(None, max_concurrency, name)
        self._actors[actor_id] = state
        pins = self._pin_ref_args(args, kwargs)

        ctor_done = threading.Event()

        def ctor():
            try:
                a, kw = self._resolve_args(args, kwargs)
                state.instance = cls(*a, **kw)
            except BaseException:  # noqa: BLE001
                state.dead = True
                state.death_cause = traceback.format_exc()
            finally:
                self._unpin(pins)
                ctor_done.set()

        def worker_loop():
            ctor_done.wait()
            while True:
                item = state.queue.get()
                if item is _POISON:
                    return
                oids, method_name, m_args, m_kwargs, num_returns, pins = item
                try:
                    if state.dead:
                        self._store_error(
                            oids,
                            ActorError(
                                f"actor {actor_id} is dead: {state.death_cause}"
                            ),
                        )
                        continue
                    try:
                        a, kw = self._resolve_args(m_args, m_kwargs)
                        method = getattr(state.instance, method_name)
                        result = method(*a, **kw)
                        self._store_returns(oids, result, num_returns)
                    except BaseException as e:  # noqa: BLE001
                        self._store_error(
                            oids,
                            TaskError(
                                f"{cls.__name__}.{method_name}",
                                traceback.format_exc(),
                                repr(e),
                            ),
                        )
                finally:
                    self._unpin(pins)

        threading.Thread(target=ctor, daemon=True).start()
        for _ in range(max_concurrency):
            t = threading.Thread(target=worker_loop, daemon=True)
            t.start()
            state.threads.append(t)
        return actor_id

    def submit_actor_task(
        self,
        actor_id: str,
        method_name: str,
        args: tuple,
        kwargs: dict,
        *,
        num_returns: int = 1,
        **_options,
    ) -> list[ObjectRef]:
        state = self._actors.get(actor_id)
        task_id = ids.new_task_id()
        oids = [ids.object_id_for(task_id, i) for i in range(num_returns)]
        refs = [self.make_ref(o) for o in oids]
        if state is None:
            self._store_error(oids, ActorError(f"no such actor: {actor_id}"))
            return refs

        pins = self._pin_ref_args(args, kwargs)
        item = (oids, method_name, args, kwargs, num_returns, pins)
        caller = threading.get_ident()

        # Unresolved ObjectRef args are resolved OFF the actor's execution
        # thread (caller-side dependency resolution), then the call is
        # enqueued — chained per caller thread to preserve submission order.
        has_deps = any(
            isinstance(a, ObjectRef) and not self._entry(a.id).event.is_set()
            for a in list(args) + list(kwargs.values())
        )
        with state.lock:
            if state.dead:
                self._unpin(pins)
                self._store_error(
                    oids, ActorError(f"actor {actor_id} is dead: {state.death_cause}")
                )
                return refs
            prev = state.caller_chains.get(caller)
            if not has_deps and (prev is None or prev.is_set()):
                state.queue.put(item)
                return refs
            done = threading.Event()
            state.caller_chains[caller] = done

        def resolve_then_enqueue():
            try:
                if prev is not None:
                    prev.wait()
                for a in list(args) + list(kwargs.values()):
                    if isinstance(a, ObjectRef):
                        self._entry(a.id).event.wait()
                with state.lock:
                    if state.dead:
                        self._unpin(pins)
                        self._store_error(
                            oids,
                            ActorError(
                                f"actor {actor_id} is dead: {state.death_cause}"
                            ),
                        )
                    else:
                        state.queue.put(item)
            finally:
                done.set()

        self._pool.submit(resolve_then_enqueue)
        return refs

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        state = self._actors.get(actor_id)
        if state is None:
            return
        with state.lock:
            state.dead = True
            state.death_cause = "killed via ray_tpu.kill"
            # Fail everything still queued, then poison the worker threads.
            drained = []
            try:
                while True:
                    drained.append(state.queue.get_nowait())
            except queue.Empty:
                pass
            for item in drained:
                if item is _POISON:
                    continue
                oids, *_rest, pins = item
                self._unpin(pins)
                self._store_error(
                    oids, ActorError(f"actor {actor_id} is dead: killed")
                )
            for _ in state.threads:
                state.queue.put(_POISON)
        with self._lock:
            if state.name and self._named_actors.get(state.name) == actor_id:
                del self._named_actors[state.name]

    def get_named_actor(self, name: str) -> str:
        with self._lock:
            aid = self._named_actors.get(name)
        if aid is None:
            raise ValueError(f"no actor named {name!r}")
        return aid

    def cancel(self, ref: ObjectRef, force: bool = False) -> None:
        # Local mode: best-effort no-op (threads are not interruptible).
        pass

    # -- lifecycle --------------------------------------------------------

    def shutdown(self) -> None:
        self._shutdown = True
        for aid in list(self._actors):
            self.kill_actor(aid)

    # -- introspection ----------------------------------------------------

    def cluster_resources(self) -> dict:
        return {"CPU": float(self._ncpu)}

    def nodes(self) -> list[dict]:
        return [{"NodeID": "local", "Alive": True, "Resources": self.cluster_resources()}]
