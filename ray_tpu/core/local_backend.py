"""In-process backend: tasks on a thread pool, actors on dedicated threads.

This is the ``ray.init(local_mode=...)`` analog but with real asynchrony —
tasks run concurrently and ObjectRefs are genuine futures. It implements the
same ``Backend`` surface the cluster backend (multi-process) implements,
so the public API code is backend-agnostic — preserving the reference's
invariant that libraries sit only on tasks/actors/objects (SURVEY.md §1).

Semantics mirrored from the reference:
* Object table entries are reference-counted against live ``ObjectRef``
  handles plus in-flight task-argument pins, and freed when the count drops
  to zero (``src/ray/core_worker/reference_count.h:61``).
* Actor-task dependencies are resolved on the *caller* side before the call
  is enqueued to the actor, preserving per-caller submission order — the
  ``DependencyResolver`` + sequence-number design of
  ``direct_actor_task_submitter.h``. The actor's execution thread never
  blocks on an unresolved argument.
"""

from __future__ import annotations

import queue
import threading
import time
import traceback
import weakref
from typing import Any, Callable, Sequence

from ray_tpu.core import ids
from ray_tpu.core.cancellation import CancelRegistry
from ray_tpu.core.object_ref import (
    ActorError,
    GetTimeoutError,
    ObjectRef,
    ObjectLostError,
    TaskCancelledError,
    TaskError,
)
from ray_tpu.core.resources import ResourcePool, default_node_resources, demand_of


class _DaemonPool:
    """Thread pool with daemon threads: in-flight tasks never block
    interpreter exit (cf. the raylet worker pool being killable)."""

    def __init__(self, max_workers: int):
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._max = max_workers
        self._count = 0
        self._idle = 0
        self._lock = threading.Lock()

    def submit(self, fn: Callable, *args) -> None:
        self._q.put((fn, args))
        with self._lock:
            if self._idle == 0 and self._count < self._max:
                self._count += 1
                threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            with self._lock:
                self._idle += 1
            try:
                fn, args = self._q.get()
            finally:
                with self._lock:
                    self._idle -= 1
            try:
                fn(*args)
            except BaseException:  # noqa: BLE001 — pool must survive anything
                from ray_tpu.util import metrics as _metrics

                _metrics.count_loop_restart("local.daemon_pool")
                traceback.print_exc()


def _approx_size(value) -> int:
    """Cheap size estimate for the state API's size ordering: exact for
    buffer-bearing values (nbytes), shallow ``getsizeof`` otherwise —
    the local backend never serializes, so this is the analog of the
    cluster store's data_size."""
    nbytes = getattr(value, "nbytes", None)
    if isinstance(nbytes, int):
        return nbytes
    import sys as _sys

    try:
        return _sys.getsizeof(value)
    except Exception:
        return 0


class _Entry:
    """Object-table slot: either a concrete value or a pending event."""

    __slots__ = ("event", "value", "error", "attr", "size")

    def __init__(self):
        self.event = threading.Event()
        self.value = None
        self.error: BaseException | None = None
        # Put-time attribution (owner/task/callsite/created_at) + size
        # estimate, for state.list_objects / memory_summary.
        self.attr: dict | None = None
        self.size = 0

    def set(self, value):
        self.value = value
        self.event.set()

    def set_error(self, err: BaseException):
        self.error = err
        self.event.set()


class _ActorState:
    def __init__(self, instance, max_concurrency: int, name: str | None):
        self.instance = instance
        self.name = name
        self.dead = False
        self.death_cause: str | None = None
        self.queue: queue.Queue = queue.Queue()
        self.max_concurrency = max_concurrency
        self.threads: list[threading.Thread] = []
        self.lock = threading.Lock()
        # Per-caller-thread submission chains: tail event of the last deferred
        # dispatch, so a caller's calls enqueue in submission order even when
        # argument resolution happens off-thread.
        self.caller_chains: dict[int, threading.Event] = {}
        # Set once the ctor acquires lifetime resources; called on kill.
        self.release_resources: Callable[[], None] | None = None
        # Declared concurrency group names (local mode shares one pool,
        # but an unknown group must still error like the cluster does).
        self.concurrency_groups: set[str] = set()


class _PlacementGroupState:
    """A gang reservation: per-bundle sub-pools carved out of the node pool.

    Single-node analog of the GCS 2-phase commit
    (``gcs_placement_group_scheduler.h:265``): prepare = blocking acquire of
    the union demand from the node pool; commit = expose per-bundle pools.
    """

    def __init__(self, pg_id, bundles, strategy, name):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        self.state = "PENDING"  # PENDING | CREATED | INFEASIBLE | REMOVED
        self.union: dict[str, float] = {}
        self.bundle_pools: list[ResourcePool] = []
        self.ready_event = threading.Event()
        self.lock = threading.Lock()
        # Signaled whenever capacity returns to any bundle, so acquirers
        # waiting for "any bundle" wake without busy-polling.
        self.release_cv = threading.Condition()

    def table_entry(self) -> dict:
        return {
            "placement_group_id": self.id,
            "name": self.name,
            "bundles": self.bundles,
            "strategy": self.strategy,
            "state": self.state,
        }


class _Lease:
    """Resources a running task/actor holds, releasable and re-acquirable.

    The release/reacquire pair is what lets a blocked ``get`` give its CPUs
    back — the analog of the raylet releasing a worker's CPUs while it is
    blocked in ``ray.get`` (reference: worker-blocked handling in
    ``node_manager.cc``). If the task's placement group was removed while it
    ran, the release is redirected to the node pool (the bundle pool is
    orphaned; its capacity was already returned).
    """

    __slots__ = ("backend", "pool", "demand", "pg", "held")

    def __init__(self, backend, pool, demand, pg=None):
        self.backend = backend
        self.pool = pool
        self.demand = demand
        self.pg = pg
        self.held = bool(demand)

    def release(self):
        if not self.held:
            return
        self.held = False
        if self.pg is not None:
            with self.pg.lock:
                if self.pg.state == "REMOVED":
                    self.backend._node_pool.release(self.demand)
                    return
                self.pool.release(self.demand)
            with self.pg.release_cv:
                self.pg.release_cv.notify_all()
        else:
            self.pool.release(self.demand)

    def reacquire(self):
        if self.held or not self.demand:
            return
        while True:
            if self.pg is not None and self.pg.state == "REMOVED":
                # Bundle pool is orphaned (its free capacity went back to the
                # node pool at removal, including what we released) — so the
                # node pool is now the right source and sink.
                self.pg = None
                self.pool = self.backend._node_pool
            if self.pool.acquire(self.demand, timeout=0.05):
                self.held = True
                return


_POISON = object()


class LocalBackend:
    """Single-process task/actor/object runtime."""

    def __init__(self, num_cpus: int | None = None, resources: dict | None = None):
        import os

        self._ncpu = num_cpus or os.cpu_count() or 8
        # Oversized pool: tasks may block waiting on upstream deps.
        self._pool = _DaemonPool(max_workers=max(64, self._ncpu * 8))
        self._objects: dict[str, _Entry] = {}
        self._refcounts: dict[str, int] = {}
        # MUST be reentrant: ObjectRef finalizers call _decref, and a GC
        # pass can fire them on whatever thread happens to allocate —
        # including one already inside this lock (e.g. _entry building a
        # threading.Event). A plain Lock self-deadlocks the whole
        # backend when that happens.
        self._objects_lock = threading.RLock()
        self._actors: dict[str, _ActorState] = {}
        self._named_actors: dict[str, str] = {}
        self._lock = threading.Lock()
        self._shutdown = False
        node_res = default_node_resources(self._ncpu)
        node_res.update(resources or {})
        self._node_pool = ResourcePool(node_res)
        self._pgs: dict[str, _PlacementGroupState] = {}
        self._current_pg = threading.local()
        # The resource lease held by the task running on this thread, so a
        # blocking get() can give the CPUs back (raylet parity: workers
        # blocked in ray.get release their CPUs).
        self._current_lease = threading.local()
        # State-API records (bounded): task lifecycle events for
        # list_tasks/summary/timeline (profiling.h + GetTasksInfo analog).
        self._task_records: "collections.OrderedDict[str, dict]" = (
            __import__("collections").OrderedDict()
        )
        self._task_records_cap = 10_000
        self._actor_records: dict[str, dict] = {}
        # Internal KV (GCS InternalKVGcsService analog, in-process flavor).
        self._kv: dict[str, Any] = {}
        # Cancellation: task ids cancelled pre-run + running-thread idents
        # for cooperative mid-run interruption (cancellation.py).
        self._cancels = CancelRegistry(threading.Lock())
        self.node_id = "local"
        # Shared asyncio loop for async actor methods, created lazily.
        self._aio_loop_obj = None
        self._aio_lock = threading.Lock()

    def _aio_loop(self):
        import asyncio

        with self._aio_lock:
            if self._aio_loop_obj is None:
                loop = asyncio.new_event_loop()
                threading.Thread(target=loop.run_forever,
                                 daemon=True).start()
                self._aio_loop_obj = loop
        return self._aio_loop_obj

    # -- internal KV -------------------------------------------------------

    def kv_put(self, key: str, value, overwrite: bool = True) -> bool:
        with self._lock:
            if not overwrite and key in self._kv:
                return False
            self._kv[key] = value
            return True

    def kv_get(self, key: str):
        with self._lock:
            return self._kv.get(key)

    def kv_del(self, key: str) -> bool:
        with self._lock:
            return self._kv.pop(key, None) is not None

    def kv_keys(self, prefix: str = "") -> list[str]:
        with self._lock:
            return [k for k in self._kv if k.startswith(prefix)]

    # -- ref counting ------------------------------------------------------

    def make_ref(self, oid: str, owner: str | None = None) -> ObjectRef:
        """Mint an ObjectRef whose lifetime pins the object-table entry.
        ``owner`` is the cluster backend's directory address — meaningless
        in local mode (single process owns everything), accepted for
        call-compatibility with ObjectRefGenerator."""
        with self._objects_lock:
            self._refcounts[oid] = self._refcounts.get(oid, 0) + 1
        ref = ObjectRef(oid)
        weakref.finalize(ref, self._decref, oid)
        return ref

    def _incref(self, oid: str):
        with self._objects_lock:
            self._refcounts[oid] = self._refcounts.get(oid, 0) + 1

    def _decref(self, oid: str):
        with self._objects_lock:
            n = self._refcounts.get(oid, 0) - 1
            if n <= 0:
                self._refcounts.pop(oid, None)
                e = self._objects.get(oid)
                # Free only resolved entries; a pending task result with no
                # handles left is freed when the task completes (see
                # _store_returns).
                if e is not None and e.event.is_set():
                    del self._objects[oid]
            else:
                self._refcounts[oid] = n

    # -- object plane -----------------------------------------------------

    def _entry(self, oid: str) -> _Entry:
        with self._objects_lock:
            e = self._objects.get(oid)
            if e is None:
                e = self._objects[oid] = _Entry()
            return e

    def put(self, value: Any) -> ObjectRef:
        from ray_tpu.core import attribution

        oid = ids.new_object_id()
        ref = self.make_ref(oid)
        e = self._entry(oid)
        e.attr = attribution.make("local")
        e.size = _approx_size(value)
        e.set(value)
        return ref

    def get(self, refs: Sequence[ObjectRef], timeout: float | None = None):
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        lease: _Lease | None = getattr(self._current_lease, "lease", None)
        released = False
        out = []
        try:
            for r in refs:
                with self._objects_lock:
                    e = self._objects.get(r.id)
                if e is None:
                    if self._refcounts.get(r.id):
                        e = self._entry(r.id)
                    else:
                        raise ObjectLostError(
                            f"object {r.id[:16]}… was freed (all references dropped)"
                        )
                if not e.event.is_set() and lease is not None and not released:
                    # About to block inside a task: give the CPUs back so
                    # nested tasks can run (deadlock avoidance).
                    lease.release()
                    released = True
                remaining = (
                    None if deadline is None else max(0.0, deadline - time.monotonic())
                )
                if not e.event.wait(remaining):
                    raise GetTimeoutError(f"ray_tpu.get timed out on {r}")
                if e.error is not None:
                    raise e.error
                out.append(e.value)
        finally:
            if released:
                lease.reacquire()
        return out

    def wait(
        self,
        refs: Sequence[ObjectRef],
        num_returns: int,
        timeout: float | None,
        fetch_local: bool = True,
    ):
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        ready: list[ObjectRef] = []
        pending = list(refs)
        while len(ready) < num_returns:
            progressed = False
            for r in list(pending):
                if self._entry(r.id).event.is_set():
                    ready.append(r)
                    pending.remove(r)
                    progressed = True
                    if len(ready) >= num_returns:
                        break
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            if not progressed:
                time.sleep(0.001)
        return ready, pending

    # -- resources + placement groups -------------------------------------

    def _plan_resources(self, options: dict, *, is_actor: bool) -> dict:
        """Resolve options into {demand, pg, bundle_index}; raise on demands
        this node can never satisfy (surfaced at submit time, unlike the
        reference which leaves the task pending forever)."""
        from ray_tpu.util.scheduling_strategies import (
            PlacementGroupSchedulingStrategy,
            validate_strategy,
        )

        demand = demand_of(options, is_actor=is_actor)
        strategy = options.get("scheduling_strategy")
        validate_strategy(strategy)
        pg_handle = options.get("placement_group")
        bundle_index = options.get("placement_group_bundle_index", -1)
        capture = False
        if isinstance(strategy, PlacementGroupSchedulingStrategy):
            pg_handle = strategy.placement_group
            bundle_index = strategy.placement_group_bundle_index
            capture = strategy.placement_group_capture_child_tasks
        if pg_handle is None and strategy in (None, "DEFAULT"):
            # Child-task capture: inherit the caller's PG if it asked for it.
            inherited = getattr(self._current_pg, "info", None)
            if inherited is not None:
                pg_handle = inherited["id"]
                bundle_index = -1
                capture = True
        pg_state = None
        if pg_handle is not None:
            pg_id = getattr(pg_handle, "id", pg_handle)
            pg_state = self._pgs.get(pg_id)
            if pg_state is None:
                raise ValueError(f"no such placement group: {pg_id}")
            if pg_state.state == "INFEASIBLE":
                raise ValueError(f"placement group {pg_id} is infeasible")
            if bundle_index >= len(pg_state.bundles) or bundle_index < -1:
                raise ValueError(
                    f"bundle index {bundle_index} out of range for placement "
                    f"group {pg_id} with {len(pg_state.bundles)} bundles"
                )
            for b in (
                pg_state.bundles
                if bundle_index < 0
                else [pg_state.bundles[bundle_index]]
            ):
                if all(b.get(k, 0.0) >= v for k, v in demand.items()):
                    break
            else:
                raise ValueError(
                    f"demand {demand} does not fit any bundle of placement "
                    f"group {pg_id} (bundles: {pg_state.bundles})"
                )
        elif demand and not self._node_pool.feasible(demand):
            raise ValueError(
                f"demand {demand} is infeasible on this node "
                f"(total: {self._node_pool.total})"
            )
        return {
            "demand": demand,
            "pg": pg_state,
            "bundle_index": bundle_index,
            "capture": capture,
        }

    def _acquire_planned(self, plan: dict) -> _Lease:
        """Blocking-acquire the planned resources; returns the held lease."""
        demand, pg = plan["demand"], plan["pg"]
        if pg is None:
            self._node_pool.acquire(demand)
            return _Lease(self, self._node_pool, demand)
        pg.ready_event.wait()
        idx = plan["bundle_index"]
        while True:
            if pg.state == "REMOVED":
                raise ValueError(f"placement group {pg.id} was removed")
            candidates = (
                list(range(len(pg.bundle_pools))) if idx < 0 else [idx]
            )
            for i in candidates:
                pool = pg.bundle_pools[i]
                if pool.try_acquire(demand):
                    return _Lease(self, pool, demand, pg)
            if not demand:
                return _Lease(self, self._node_pool, {})
            with pg.release_cv:
                pg.release_cv.wait(0.05)

    def create_placement_group(
        self, bundles: list, strategy: str, name: str = "", lifetime=None,
        spot: bool = True,
    ) -> str:
        pg_id = ids.new_placement_group_id()
        pg = _PlacementGroupState(pg_id, bundles, strategy, name)
        union: dict[str, float] = {}
        for b in bundles:
            for k, v in b.items():
                union[k] = union.get(k, 0.0) + v
        pg.union = union
        # Single-node backend: STRICT_SPREAD needs len(bundles) distinct
        # nodes, so >1 bundle is infeasible here by definition.
        if (strategy == "STRICT_SPREAD" and len(bundles) > 1) or (
            not self._node_pool.feasible(union)
        ):
            pg.state = "INFEASIBLE"
            self._pgs[pg_id] = pg
            return pg_id
        self._pgs[pg_id] = pg

        def reserve():
            # Poll-acquire so a concurrent removal cancels the reservation
            # instead of leaving this thread blocked forever.
            while not self._node_pool.acquire(union, timeout=0.05):
                if pg.state == "REMOVED":
                    return
            with pg.lock:
                if pg.state == "REMOVED":
                    self._node_pool.release(union)
                    return
                pg.bundle_pools = [ResourcePool(b) for b in bundles]
                pg.state = "CREATED"
                pg.ready_event.set()

        self._pool.submit(reserve)
        return pg_id

    def remove_placement_group(self, pg_id: str) -> None:
        pg = self._pgs.get(pg_id)
        if pg is None:
            return
        with pg.lock:
            prev = pg.state
            pg.state = "REMOVED"
            if prev == "CREATED":
                # Return only capacity not currently held by running
                # tasks/actors; their leases release straight to the node
                # pool once they finish (see _Lease.release).
                freed: dict[str, float] = {}
                for pool in pg.bundle_pools:
                    for k, v in pool.available().items():
                        freed[k] = freed.get(k, 0.0) + v
                self._node_pool.release(freed)
        # Wake anything blocked on readiness; they observe REMOVED and fail.
        pg.ready_event.set()
        with pg.release_cv:
            pg.release_cv.notify_all()

    def placement_group_ready(self, pg_id: str) -> ObjectRef:
        oid = ids.new_object_id()
        ref = self.make_ref(oid)
        pg = self._pgs.get(pg_id)
        entry = self._entry(oid)
        if pg is None or pg.state in ("INFEASIBLE", "REMOVED"):
            entry.set_error(
                ValueError(f"placement group {pg_id} cannot become ready")
            )
            return ref

        def waiter():
            pg.ready_event.wait()
            if pg.state == "REMOVED":
                entry.set_error(ValueError(f"placement group {pg_id} was removed"))
            else:
                entry.set(pg_id)

        self._pool.submit(waiter)
        return ref

    def placement_group_table(self, pg_id: str | None = None):
        if pg_id is not None:
            pg = self._pgs.get(pg_id)
            return pg.table_entry() if pg else None
        return {pid: pg.table_entry() for pid, pg in self._pgs.items()}

    def current_placement_group(self):
        return getattr(self._current_pg, "info", None)

    # -- state records ----------------------------------------------------

    def _record_task(self, task_id: str, name: str, kind: str = "NORMAL_TASK"):
        import time as _time

        with self._lock:
            if len(self._task_records) >= self._task_records_cap:
                self._task_records.popitem(last=False)
            self._task_records[task_id] = {
                "task_id": task_id,
                "name": name,
                "type": kind,
                "state": "PENDING",
                "submitted_at": _time.time(),
                "start_time": None,
                "end_time": None,
                "error": None,
                # Wall-ns per execution phase (get_args/execute/
                # put_outputs) — same shape the cluster workers report.
                "phases": {},
            }

    def _record_task_phase(self, task_id: str, name: str, ns: int) -> None:
        with self._lock:
            rec = self._task_records.get(task_id)
            if rec is not None:
                phases = rec.setdefault("phases", {})
                phases[name] = phases.get(name, 0) + int(ns)

    def _record_task_attempt(self, task_id: str) -> None:
        """A new execution attempt begins: stamp start_time (first
        attempt anchors the timeline slice) and drop the previous
        attempt's phases — a retried task must report the phases of the
        attempt that produced its outcome, not an N-attempt sum that
        overflows the slice (cluster workers get this for free: each
        attempt ships a fresh record)."""
        import time as _time

        with self._lock:
            rec = self._task_records.get(task_id)
            if rec is not None:
                if rec["start_time"] is None:
                    rec["start_time"] = _time.time()
                rec["phases"] = {}

    def _record_task_state(self, task_id: str, state: str, error: str | None = None):
        import time as _time

        rec = self._task_records.get(task_id)
        if rec is None:
            return
        rec["state"] = state
        if state == "RUNNING":
            # Keep the earliest stamp: _record_task_attempt anchors the
            # timeline slice before arg resolution; RUNNING here only
            # flips the reported state once resources are actually held.
            if rec["start_time"] is None:
                rec["start_time"] = _time.time()
        elif state in ("FINISHED", "FAILED"):
            rec["end_time"] = _time.time()
            rec["error"] = error

    def list_tasks(self, limit: int = 1000) -> list[dict]:
        with self._lock:
            return [dict(r) for r in list(self._task_records.values())[-limit:]]

    def list_actors(self) -> list[dict]:
        out = []
        for actor_id, state in self._actors.items():
            rec = self._actor_records.get(actor_id, {})
            out.append({
                "actor_id": actor_id,
                "class_name": rec.get("class_name", "?"),
                "name": state.name,
                "state": "DEAD" if state.dead else "ALIVE",
                "death_cause": state.death_cause,
            })
        return out

    def list_objects(self, limit: int = 1000) -> dict:
        """{"objects": [...], "truncated": bool, "total": int} sorted by
        size descending — the limit clips AFTER the sort, so `limit=N`
        means the N largest objects, never N arbitrary insertion-order
        ones, and clipping is reported instead of silent."""
        import time as _time

        now = _time.time()
        with self._objects_lock:
            # Snapshot first: building the per-object dicts below
            # allocates, which can trigger GC -> an ObjectRef finalizer
            # -> a reentrant _decref (the lock is an RLock for exactly
            # that reason) deleting from the live table mid-iteration.
            items = list(self._objects.items())
            out = []
            for oid, entry in items:
                attr = entry.attr or {}
                created = attr.get("created_at")
                out.append({
                    "object_id": oid,
                    "status": "READY" if entry.event.is_set() else "PENDING",
                    "refcount": self._refcounts.get(oid, 0),
                    "size": entry.size,
                    "owner": attr.get("owner", ""),
                    "task": attr.get("task", ""),
                    "callsite": attr.get("callsite", ""),
                    "nodes": ["local"],
                    "age_s": round(now - created, 3) if created else None,
                })
        out.sort(key=lambda r: r["size"], reverse=True)
        total = len(out)
        return {"objects": out[:limit], "truncated": total > limit,
                "total": total}

    def memory_summary(self, top_k: int = 20,
                       group_by: str = "callsite") -> dict:
        """Single-process analog of the cluster memory rollup: this
        backend's object table grouped by callsite/task (sizes are the
        local estimates — there is no shm segment to meter)."""
        if group_by not in ("callsite", "task", "node", "owner"):
            # Same contract as the head: a typo'd group_by must fail
            # loud, not return everything under "(unknown)".
            raise ValueError(
                f"group_by must be callsite|task|node|owner, "
                f"got {group_by!r}")
        listing = self.list_objects(limit=1 << 20)["objects"]
        bytes_used = sum(r["size"] for r in listing)
        groups: dict[str, dict] = {}
        for r in listing:
            key = (self.node_id if group_by == "node"
                   else r.get(group_by)) or "(unknown)"
            g = groups.setdefault(key, {"key": key, "bytes": 0,
                                        "objects": 0})
            g["bytes"] += r["size"]
            g["objects"] += 1
        node = {"bytes_used": bytes_used, "bytes_capacity": 0,
                "occupancy": 0.0, "objects": len(listing), "evictions": 0,
                "spilled_bytes": 0, "oom_reports": []}
        return {
            "totals": {"bytes_used": bytes_used, "bytes_capacity": 0,
                       "objects": len(listing), "evictions": 0,
                       "spilled_bytes": 0, "spilled_objects": 0,
                       "nodes": 1},
            "nodes": {self.node_id: node},
            "top_objects": listing[:top_k],
            "group_by": group_by,
            "groups": sorted(groups.values(),
                             key=lambda g: g["bytes"], reverse=True),
            "leaks": 0,
        }

    def memory_leaks(self) -> list[dict]:
        """Local mode frees on the last decref — there is no unreachable-
        but-pinned state to leak-sweep."""
        return []

    def object_store_stats(self, node_id=None,
                           include_objects: bool = True) -> list[dict]:
        listing = self.list_objects(limit=1 << 20)["objects"]
        report = {
            "node_id": self.node_id,
            "stats": {"capacity": 0,
                      "used": sum(r["size"] for r in listing),
                      "num_objects": len(listing), "num_evictions": 0,
                      "spilled_objects": 0, "spilled_bytes": 0},
            "oom_reports": [],
        }
        if include_objects:
            report["objects"] = listing
        return [report]

    # -- node reporter surface (logs / stacks / telemetry) -----------------
    # Local mode runs everything in THIS process: profiling/stack dumps
    # sample our own threads (tasks run on pool threads here, so the
    # busy task IS visible); there are no per-worker log files or child
    # processes, so those surfaces return empty/raise.

    def list_logs(self) -> list[dict]:
        return []

    def get_log(self, worker_id: str, *a, **kw):
        raise ValueError(
            "the local backend runs tasks in-process and captures no "
            "per-worker log files (use a cluster for state.get_log)")

    def dump_worker_stack(self, worker_id: str | None = None,
                          node_id=None) -> str:
        from ray_tpu.util import stack_sampler

        import os as _os

        return stack_sampler.dump_stacks(
            header=f"local backend (pid {_os.getpid()})")

    def profile_worker(self, worker_id: str | None = None,
                       duration_s: float = 1.0, interval_s: float = 0.01,
                       node_id=None) -> dict:
        from ray_tpu.util import stack_sampler

        prof = stack_sampler.sample(duration_s, interval_s)
        prof["worker_id"] = worker_id or "local"
        prof["node_id"] = self.node_id
        return prof

    def worker_stats(self, fresh: bool = False) -> list[dict]:
        return []

    def device_stats(self, fresh: bool = False) -> list[dict]:
        """This process's JAX/XLA device view (a stub until something
        imports jax — the snapshot never triggers the import itself)."""
        from ray_tpu.util import device_telemetry

        snap = device_telemetry.snapshot()
        snap["worker_id"] = "local"
        snap["node_id"] = self.node_id
        return [snap]

    def capture_profile(self, worker_id=None, duration_s: float = 1.0,
                        interval_s: float = 0.01, out_dir=None,
                        node_id=None) -> dict:
        """Timed profiler window over this process: jax.profiler.trace
        when jax is loaded, the stack sampler otherwise; trace files
        land in ``out_dir`` (a fresh temp dir by default)."""
        import os as _os
        import tempfile

        from ray_tpu.util import device_telemetry

        out_dir = out_dir or tempfile.mkdtemp(prefix="ray_tpu_tprof_")
        res = device_telemetry.capture_to_dir(
            out_dir, duration_s, interval_s,
            worker_id=worker_id or "local")
        return {
            "kind": res["kind"],
            "worker_id": worker_id or "local",
            "node_id": self.node_id,
            "duration_s": res["duration_s"],
            "dir": out_dir,
            "files": [_os.path.join(out_dir, rel)
                      for rel in sorted(res["files"])],
        }

    def list_spans(self, trace_id=None, limit: int = 10_000) -> list[dict]:
        """This process's finished tracing spans (the cluster backend
        reads the head's span store instead)."""
        from ray_tpu.util import tracing

        spans = tracing.collect()
        if trace_id is not None:
            spans = [s for s in spans if s["trace_id"] == trace_id]
        return spans[-limit:]

    def cluster_metrics_text(self) -> str:
        """Single-process 'cluster': the federated view IS the local
        registry."""
        from ray_tpu.util import metrics as _metrics

        return _metrics.prometheus_text()

    # -- trace flight recorder (cluster/traces.py over local spans) --------

    def _trace_store(self):
        """A fresh TraceStore over this process's finished spans:
        single-process, so assembly is trivial (no clock alignment, no
        quiet-window wait) and nothing is tail-sampled — the local
        backend is the debugging backend, keep everything. Rebuilt per
        query; the span buffer itself is the bounded state."""
        from ray_tpu.cluster.traces import TraceStore
        from ray_tpu.core.config import config
        from ray_tpu.util import tracing

        store = TraceStore(
            max_traces=config.head_trace_retention,
            sample_rate=1.0,
            slow_threshold_s=config.trace_slow_threshold_s,
            max_spans_per_trace=config.trace_max_spans,
            quiet_s=0.0)
        store.add_spans(tracing.collect())
        store.finalize_quiet(force=True)
        return store

    def get_trace(self, trace_id: str):
        return self._trace_store().get(trace_id)

    def list_traces(self, limit: int = 50) -> list:
        return self._trace_store().list(limit)

    def trace_stats(self) -> dict:
        return self._trace_store().stats()

    def ttft_decomposition(self, window_s: float | None = None,
                           deployment: str | None = None) -> dict:
        return self._trace_store().ttft_decomposition(
            window_s=window_s, deployment=deployment)

    # -- task plane -------------------------------------------------------

    def _pin_ref_args(self, args, kwargs) -> list[str]:
        """Pin ObjectRef arguments for the duration of a task (the lineage-
        pinning analog of TaskManager, ``task_manager.h:87``)."""
        pins = [a.id for a in args if isinstance(a, ObjectRef)]
        pins += [v.id for v in kwargs.values() if isinstance(v, ObjectRef)]
        for oid in pins:
            self._incref(oid)
        return pins

    def _unpin(self, pins: list[str]):
        for oid in pins:
            self._decref(oid)

    def _resolve_args(self, args, kwargs):
        args = [self.get([a])[0] if isinstance(a, ObjectRef) else a for a in args]
        kwargs = {
            k: self.get([v])[0] if isinstance(v, ObjectRef) else v
            for k, v in kwargs.items()
        }
        return args, kwargs

    def _set_result(self, oid: str, value) -> None:
        """Store one task-return value with put-time attribution (the
        creating task's name comes from the ambient task_context)."""
        from ray_tpu.core import attribution

        e = self._entry(oid)
        e.attr = attribution.make("local", default_task="task")
        e.size = _approx_size(value)
        e.set(value)

    def _store_returns(self, oids: list[str], result, num_returns):
        if num_returns == "streaming":
            # Generator protocol (see workerproc._store_result): items at
            # successive return indices, then a _StreamEnd terminator;
            # a mid-stream error lands AT the failing index. Returns
            # False on failure (contained here — a partially consumed
            # stream must not retry, and index 0 may already hold a
            # yielded item the generic error path would clobber).
            from ray_tpu.core.object_ref import _StreamEnd

            task_id = ids.task_of_object(oids[0])[0]
            i = 0
            try:
                for item in result:
                    self._set_result(ids.object_id_for(task_id, i), item)
                    i += 1
                self._entry(
                    ids.object_id_for(task_id, i)).set(_StreamEnd())
            except BaseException as e:  # noqa: BLE001
                self._entry(ids.object_id_for(task_id, i)).set_error(
                    TaskError("streaming_task", traceback.format_exc(),
                              repr(e)))
                self._record_task_state(task_id, "FAILED", repr(e))
                self._gc_unreferenced(oids)
                return False
            self._gc_unreferenced(oids)
            return True
        if num_returns == 1:
            self._set_result(oids[0], result)
        else:
            vals = list(result)
            if len(vals) != num_returns:
                raise ValueError(
                    f"task declared num_returns={num_returns} but returned "
                    f"{len(vals)} values"
                )
            for oid, v in zip(oids, vals):
                self._set_result(oid, v)
        self._gc_unreferenced(oids)

    def release_stream(self, task_id: str, from_index: int) -> None:
        """Drop an abandoned stream's unconsumed items (ObjectRefGenerator
        finalizer). Cooperatively cancels a still-running producer, then
        deletes produced-but-unread entries from ``from_index`` on."""
        self._cancels.cancel(task_id, TaskCancelledError)
        i = from_index
        while True:
            oid = ids.object_id_for(task_id, i)
            with self._objects_lock:
                e = self._objects.get(oid)
                if e is None or not e.event.is_set():
                    break
                del self._objects[oid]
            i += 1

    def _store_error(self, oids: list[str], err: BaseException):
        for oid in oids:
            self._entry(oid).set_error(err)
        self._gc_unreferenced(oids)

    def _gc_unreferenced(self, oids: list[str]):
        with self._objects_lock:
            for oid in oids:
                if not self._refcounts.get(oid):
                    self._objects.pop(oid, None)

    def submit_task(
        self,
        func: Callable,
        args: tuple,
        kwargs: dict,
        *,
        num_returns: int = 1,
        max_retries: int = 0,
        retry_exceptions: bool | tuple = False,
        name: str = "",
        **_options,
    ) -> list[ObjectRef]:
        task_id = ids.new_task_id()
        n_oids = 1 if num_returns == "streaming" else num_returns
        oids = [ids.object_id_for(task_id, i) for i in range(n_oids)]
        refs = [self.make_ref(o) for o in oids]
        fname = name or getattr(func, "__name__", "task")
        self._record_task(task_id, fname)
        try:
            plan = self._plan_resources(_options, is_actor=False)
        except (ValueError, TypeError) as e:
            self._store_error(oids, e)
            return refs
        pins = self._pin_ref_args(args, kwargs)
        from contextlib import nullcontext

        from ray_tpu.core import attribution
        from ray_tpu.util import tracing

        # Submit-time callsite: by store time the user frames are gone,
        # so the .remote() line is the return objects' creation site.
        submit_site = attribution.submit_site()

        def run():
            from ray_tpu.core import attribution

            try:
                if not self._cancels.begin(task_id, threading.get_ident()):
                    self._record_task_state(task_id, "CANCELLED")
                    self._store_error(oids, TaskCancelledError(fname))
                    return
                # Execution span parents under the submit span's spec
                # context — same parent/child shape as a cluster worker
                # (tracing_helper parity), so the conformance tests see
                # one trace tree regardless of backend.
                run_cm = (tracing.span(f"run:{fname}",
                                       {"task_id": task_id},
                                       parent=trace_ctx)
                          if trace_ctx and tracing.is_enabled()
                          else nullcontext())
                # Attribution context: the task's returns and any nested
                # puts its user code makes attribute to this task name.
                with attribution.task_context(fname, submit_site), run_cm:
                    run_attempts()
            finally:
                try:
                    self._cancels.end(task_id, threading.get_ident())
                finally:
                    self._unpin(pins)

        def run_attempts():
            attempts = 0
            while True:
                    try:
                        # Stamp start BEFORE arg resolution (cluster
                        # workers stamp at executor pickup, also
                        # pre-resolve — timeline children must nest);
                        # the state stays PENDING until resources are
                        # held so a resource-queued task never reads as
                        # RUNNING.
                        self._record_task_attempt(task_id)
                        t_phase = time.monotonic_ns()
                        a, kw = self._resolve_args(args, kwargs)
                        self._record_task_phase(
                            task_id, "get_args",
                            time.monotonic_ns() - t_phase)
                        lease = self._acquire_planned(plan)
                        self._current_lease.lease = lease
                        if plan["capture"]:
                            self._current_pg.info = {
                                "id": plan["pg"].id,
                                "bundles": plan["pg"].bundles,
                                "strategy": plan["pg"].strategy,
                                "name": plan["pg"].name,
                            }
                        self._record_task_state(task_id, "RUNNING")
                        t_phase = time.monotonic_ns()
                        try:
                            result = func(*a, **kw)
                            self._record_task_phase(
                                task_id, "execute",
                                time.monotonic_ns() - t_phase)
                            t_phase = time.monotonic_ns()
                            if num_returns == "streaming":
                                # The generator BODY runs during
                                # iteration — keep the lease held for it
                                # (parity with the cluster worker, which
                                # holds resources until task_done).
                                ok = self._store_returns(
                                    oids, result, num_returns)
                                self._record_task_phase(
                                    task_id, "put_outputs",
                                    time.monotonic_ns() - t_phase)
                        finally:
                            self._current_lease.lease = None
                            lease.release()
                            if plan["capture"]:
                                self._current_pg.info = None
                        if num_returns == "streaming":
                            if ok:
                                self._record_task_state(task_id, "FINISHED")
                            return  # FAILED already recorded inside
                        self._store_returns(oids, result, num_returns)
                        self._record_task_phase(
                            task_id, "put_outputs",
                            time.monotonic_ns() - t_phase)
                        self._record_task_state(task_id, "FINISHED")
                        return
                    except BaseException as e:  # noqa: BLE001 — stored, not dropped
                        if isinstance(e, TaskCancelledError):
                            self._record_task_state(task_id, "CANCELLED")
                            self._store_error(oids, e)
                            return
                        self._record_task_state(task_id, "FAILED", repr(e))
                        retriable = retry_exceptions is True or (
                            isinstance(retry_exceptions, tuple)
                            and isinstance(e, retry_exceptions)
                        )
                        if retriable and attempts < max_retries:
                            attempts += 1
                            continue
                        if isinstance(e, (TaskError, ActorError)):
                            self._store_error(oids, e)
                        else:
                            self._store_error(
                                oids,
                                TaskError(fname, traceback.format_exc(), repr(e)),
                            )
                        return

        # Submission span: covers the enqueue only (dispatch is async);
        # its context is the spec-carried trace_ctx the run span (and
        # anything the task itself traces) parents under.
        span_cm = (tracing.span(f"submit:{fname}", {"task_id": task_id})
                   if tracing.is_enabled() else nullcontext())
        with span_cm as s:
            trace_ctx = ({"trace_id": s["trace_id"],
                          "span_id": s["span_id"]}
                         if s is not None else None)
            self._pool.submit(run)
        return refs

    # -- actor plane ------------------------------------------------------

    def create_actor(
        self,
        cls: type,
        args: tuple,
        kwargs: dict,
        *,
        name: str | None = None,
        max_concurrency: int = 1,
        **_options,
    ) -> str:
        actor_id = ids.new_actor_id()
        # Local-mode approximation of concurrency groups: the group
        # threads join one shared pool (total parallelism matches; the
        # per-group queue ISOLATION is a cluster-backend property).
        groups = _options.get("concurrency_groups") or {}
        max_concurrency += sum(int(n) for n in groups.values())
        plan = self._plan_resources(_options, is_actor=True)  # raises if infeasible
        with self._lock:
            if name is not None:
                if name in self._named_actors:
                    raise ValueError(f"actor name {name!r} already taken")
                self._named_actors[name] = actor_id
        state = _ActorState(None, max_concurrency, name)
        state.concurrency_groups = set(groups)
        self._actors[actor_id] = state
        self._actor_records[actor_id] = {"class_name": cls.__name__}
        pins = self._pin_ref_args(args, kwargs)

        ctor_done = threading.Event()

        def ctor():
            lease = None
            try:
                a, kw = self._resolve_args(args, kwargs)
                # Resources are held for the actor's whole lifetime.
                lease = self._acquire_planned(plan)
                state.instance = cls(*a, **kw)
                state.release_resources = lease.release
            except BaseException:  # noqa: BLE001
                state.dead = True
                state.death_cause = traceback.format_exc()
                if lease is not None:
                    lease.release()
            finally:
                self._unpin(pins)
                ctor_done.set()

        def worker_loop(run_ctor: bool = False):
            # The ctor runs on worker thread 0 so that thread-local state a
            # constructor sets (e.g. a collective-group context) is visible
            # to subsequent method calls on a max_concurrency=1 actor —
            # matching the reference, where ctor and methods share a process.
            if run_ctor:
                ctor()
            else:
                ctor_done.wait()
            while True:
                item = state.queue.get()
                if item is _POISON:
                    return
                (oids, method_name, m_args, m_kwargs, num_returns, site,
                 pins) = item
                call_tid = ids.task_of_object(oids[0])[0]
                try:
                    self._run_actor_item(
                        state, cls, actor_id, oids, method_name, m_args,
                        m_kwargs, num_returns, pins, call_tid, site)
                except BaseException:  # noqa: BLE001
                    # A cancel injection delivered after the item's own
                    # handlers (e.g. inside a finally) must not kill this
                    # actor's executor thread.
                    from ray_tpu.util import metrics as _metrics

                    _metrics.count_loop_restart("local.actor_exec")
                    traceback.print_exc()

        for i in range(max_concurrency):
            t = threading.Thread(target=worker_loop, args=(i == 0,), daemon=True)
            t.start()
            state.threads.append(t)
        return actor_id

    def _run_actor_item(self, state, cls, actor_id, oids, method_name,
                        m_args, m_kwargs, num_returns, pins, call_tid,
                        site=None):
        """Execute one dequeued actor call (body of the actor's executor
        loop, factored out so worker_loop can shield its thread from a
        late-delivered cancel injection)."""
        try:
            if state.dead:
                self._store_error(
                    oids,
                    ActorError(
                        f"actor {actor_id} is dead: {state.death_cause}"
                    ),
                )
                return
            if not self._cancels.begin(call_tid, threading.get_ident()):
                self._record_task_state(call_tid, "CANCELLED")
                self._store_error(oids, TaskCancelledError(method_name))
                return
            try:
                # Pre-resolve stamp, same reason as submit_task: the
                # get_args slice must fall inside the call's timeline.
                self._record_task_attempt(call_tid)
                t_phase = time.monotonic_ns()
                a, kw = self._resolve_args(m_args, m_kwargs)
                self._record_task_phase(
                    call_tid, "get_args", time.monotonic_ns() - t_phase)
                method = getattr(state.instance, method_name)
                self._record_task_state(call_tid, "RUNNING")
                t_phase = time.monotonic_ns()
                from ray_tpu.core import attribution

                with attribution.task_context(method_name, site):
                    result = method(*a, **kw)
                    import asyncio

                    if asyncio.iscoroutine(result):
                        # Async actor method: run on the backend's shared
                        # event loop so concurrent async calls interleave
                        # at await points (reference async actors; the
                        # executor thread blocks, so per-actor parallelism
                        # is still bounded by max_concurrency — set it >1
                        # for interleaving). Attribution rides the
                        # asyncio Task's own context: the executor
                        # thread's contextvar doesn't reach the loop.
                        async def attributed(inner=result):
                            with attribution.task_context(
                                    method_name, site):
                                return await inner

                        result = asyncio.run_coroutine_threadsafe(
                            attributed(), self._aio_loop()).result()
                    self._record_task_phase(
                        call_tid, "execute", time.monotonic_ns() - t_phase)
                    t_phase = time.monotonic_ns()
                    self._store_returns(oids, result, num_returns)
                self._record_task_phase(
                    call_tid, "put_outputs", time.monotonic_ns() - t_phase)
                self._record_task_state(call_tid, "FINISHED")
            except BaseException as e:  # noqa: BLE001
                if isinstance(e, TaskCancelledError):
                    self._record_task_state(call_tid, "CANCELLED")
                    self._store_error(oids, e)
                else:
                    self._store_error(
                        oids,
                        TaskError(
                            f"{cls.__name__}.{method_name}",
                            traceback.format_exc(),
                            repr(e),
                        ),
                    )
            finally:
                self._cancels.end(call_tid, threading.get_ident())
        finally:
            self._unpin(pins)

    def submit_actor_task(
        self,
        actor_id: str,
        method_name: str,
        args: tuple,
        kwargs: dict,
        *,
        num_returns: int = 1,
        **_options,
    ) -> list[ObjectRef]:
        state = self._actors.get(actor_id)
        task_id = ids.new_task_id()
        oids = [ids.object_id_for(task_id, i) for i in range(num_returns)]
        refs = [self.make_ref(o) for o in oids]
        self._record_task(task_id, method_name, kind="ACTOR_TASK")
        if state is None:
            self._store_error(oids, ActorError(f"no such actor: {actor_id}"))
            return refs
        group = _options.get("concurrency_group")
        if group and group not in state.concurrency_groups:
            # Same contract as the cluster worker: unknown group = error
            # (local mode shares one pool but must not mask the typo).
            self._store_error(
                oids,
                TaskError(method_name,
                          f"actor has no concurrency group {group!r}",
                          "no-such-group"),
            )
            self._record_task_state(task_id, "FAILED", "no-such-group")
            return refs

        from ray_tpu.core import attribution

        pins = self._pin_ref_args(args, kwargs)
        item = (oids, method_name, args, kwargs, num_returns,
                attribution.submit_site(), pins)
        caller = threading.get_ident()

        # Unresolved ObjectRef args are resolved OFF the actor's execution
        # thread (caller-side dependency resolution), then the call is
        # enqueued — chained per caller thread to preserve submission order.
        has_deps = any(
            isinstance(a, ObjectRef) and not self._entry(a.id).event.is_set()
            for a in list(args) + list(kwargs.values())
        )
        with state.lock:
            if state.dead:
                self._unpin(pins)
                self._store_error(
                    oids, ActorError(f"actor {actor_id} is dead: {state.death_cause}")
                )
                return refs
            prev = state.caller_chains.get(caller)
            if not has_deps and (prev is None or prev.is_set()):
                state.queue.put(item)
                return refs
            done = threading.Event()
            state.caller_chains[caller] = done

        def resolve_then_enqueue():
            try:
                if prev is not None:
                    prev.wait()
                for a in list(args) + list(kwargs.values()):
                    if isinstance(a, ObjectRef):
                        self._entry(a.id).event.wait()
                with state.lock:
                    if state.dead:
                        self._unpin(pins)
                        self._store_error(
                            oids,
                            ActorError(
                                f"actor {actor_id} is dead: {state.death_cause}"
                            ),
                        )
                    else:
                        state.queue.put(item)
            finally:
                done.set()

        self._pool.submit(resolve_then_enqueue)
        return refs

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        state = self._actors.get(actor_id)
        if state is None:
            return
        with state.lock:
            state.dead = True
            state.death_cause = "killed via ray_tpu.kill"
            # Fail everything still queued, then poison the worker threads.
            drained = []
            try:
                while True:
                    drained.append(state.queue.get_nowait())
            except queue.Empty:
                pass
            for item in drained:
                if item is _POISON:
                    continue
                oids, *_rest, pins = item
                self._unpin(pins)
                self._store_error(
                    oids, ActorError(f"actor {actor_id} is dead: killed")
                )
            for _ in state.threads:
                state.queue.put(_POISON)
            if state.release_resources is not None:
                state.release_resources()
                state.release_resources = None
        with self._lock:
            if state.name and self._named_actors.get(state.name) == actor_id:
                del self._named_actors[state.name]

    def get_named_actor(self, name: str) -> str:
        with self._lock:
            aid = self._named_actors.get(name)
        if aid is None:
            raise ValueError(f"no actor named {name!r}")
        return aid

    def cancel(self, ref: ObjectRef, force: bool = False) -> None:
        """Best-effort cancel. Not-yet-started work (pool backlog, actor
        queue) is skipped at pickup; a running task gets TaskCancelledError
        injected into its executor thread (cooperative — C-blocked code
        finishes its call first; there is no separate process to kill in
        local mode, so ``force`` adds nothing here)."""
        task_id = ids.task_of_object(ref.id)[0]
        if self._entry(ref.id).event.is_set():
            return  # already finished: no-op
        self._cancels.cancel(task_id, TaskCancelledError)

    # -- lifecycle --------------------------------------------------------

    def shutdown(self) -> None:
        self._shutdown = True
        for aid in list(self._actors):
            self.kill_actor(aid)

    # -- introspection ----------------------------------------------------

    def cluster_resources(self) -> dict:
        return self._node_pool.total

    def available_resources(self) -> dict:
        return self._node_pool.available()

    def nodes(self) -> list[dict]:
        return [{"NodeID": "local", "Alive": True, "Resources": self.cluster_resources()}]
