"""In-process backend: tasks on a thread pool, actors on dedicated threads.

This is the ``ray.init(local_mode=...)`` analog but with real asynchrony —
tasks run concurrently and ObjectRefs are genuine futures. It implements the
same ``Backend`` surface the cluster backend (multi-process, M3) implements,
so the public API code is backend-agnostic — preserving the reference's
invariant that libraries sit only on tasks/actors/objects (SURVEY.md §1).
"""

from __future__ import annotations

import concurrent.futures as cf
import queue
import threading
import traceback
from typing import Any, Callable, Sequence

from ray_tpu.core import ids
from ray_tpu.core.object_ref import (
    ActorError,
    GetTimeoutError,
    ObjectRef,
    TaskError,
)


class _Entry:
    """Object-table slot: either a concrete value or a pending event."""

    __slots__ = ("event", "value", "error")

    def __init__(self):
        self.event = threading.Event()
        self.value = None
        self.error: BaseException | None = None

    def set(self, value):
        self.value = value
        self.event.set()

    def set_error(self, err: BaseException):
        self.error = err
        self.event.set()


class _ActorState:
    def __init__(self, instance, max_concurrency: int, name: str | None):
        self.instance = instance
        self.name = name
        self.dead = False
        self.death_cause: str | None = None
        self.queue: queue.Queue = queue.Queue()
        self.max_concurrency = max_concurrency
        self.threads: list[threading.Thread] = []
        self.lock = threading.Lock()


_POISON = object()


class LocalBackend:
    """Single-process task/actor/object runtime."""

    def __init__(self, num_cpus: int | None = None):
        import os

        self._ncpu = num_cpus or os.cpu_count() or 8
        # Oversized pool: tasks may block waiting on upstream deps.
        self._pool = cf.ThreadPoolExecutor(max_workers=max(64, self._ncpu * 8))
        self._objects: dict[str, _Entry] = {}
        self._objects_lock = threading.Lock()
        self._actors: dict[str, _ActorState] = {}
        self._named_actors: dict[str, str] = {}
        self._lock = threading.Lock()
        self._shutdown = False

    # -- object plane -----------------------------------------------------

    def _entry(self, oid: str) -> _Entry:
        with self._objects_lock:
            e = self._objects.get(oid)
            if e is None:
                e = self._objects[oid] = _Entry()
            return e

    def put(self, value: Any) -> ObjectRef:
        oid = ids.new_object_id()
        self._entry(oid).set(value)
        return ObjectRef(oid)

    def get(self, refs: Sequence[ObjectRef], timeout: float | None = None):
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for r in refs:
            e = self._entry(r.id)
            remaining = (
                None if deadline is None else max(0.0, deadline - time.monotonic())
            )
            if not e.event.wait(remaining):
                raise GetTimeoutError(f"ray_tpu.get timed out on {r}")
            if e.error is not None:
                raise e.error
            out.append(e.value)
        return out

    def wait(
        self,
        refs: Sequence[ObjectRef],
        num_returns: int,
        timeout: float | None,
        fetch_local: bool = True,
    ):
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        ready: list[ObjectRef] = []
        pending = list(refs)
        while len(ready) < num_returns:
            progressed = False
            for r in list(pending):
                if self._entry(r.id).event.is_set():
                    ready.append(r)
                    pending.remove(r)
                    progressed = True
                    if len(ready) >= num_returns:
                        break
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            if not progressed:
                time.sleep(0.001)
        return ready, pending

    # -- task plane -------------------------------------------------------

    def _resolve_args(self, args, kwargs):
        args = [self.get([a])[0] if isinstance(a, ObjectRef) else a for a in args]
        kwargs = {
            k: self.get([v])[0] if isinstance(v, ObjectRef) else v
            for k, v in kwargs.items()
        }
        return args, kwargs

    def _store_returns(self, oids: list[str], result, num_returns: int):
        if num_returns == 1:
            self._entry(oids[0]).set(result)
        else:
            vals = list(result)
            if len(vals) != num_returns:
                raise ValueError(
                    f"task declared num_returns={num_returns} but returned "
                    f"{len(vals)} values"
                )
            for oid, v in zip(oids, vals):
                self._entry(oid).set(v)

    def _store_error(self, oids: list[str], err: BaseException):
        for oid in oids:
            self._entry(oid).set_error(err)

    def submit_task(
        self,
        func: Callable,
        args: tuple,
        kwargs: dict,
        *,
        num_returns: int = 1,
        max_retries: int = 0,
        retry_exceptions: bool | tuple = False,
        name: str = "",
        **_options,
    ) -> list[ObjectRef]:
        task_id = ids.new_task_id()
        oids = [ids.object_id_for(task_id, i) for i in range(num_returns)]
        fname = name or getattr(func, "__name__", "task")

        def run():
            attempts = 0
            while True:
                try:
                    a, kw = self._resolve_args(args, kwargs)
                    result = func(*a, **kw)
                    self._store_returns(oids, result, num_returns)
                    return
                except BaseException as e:  # noqa: BLE001 — stored, not dropped
                    retriable = retry_exceptions is True or (
                        isinstance(retry_exceptions, tuple)
                        and isinstance(e, retry_exceptions)
                    )
                    if retriable and attempts < max_retries:
                        attempts += 1
                        continue
                    if isinstance(e, (TaskError, ActorError)):
                        self._store_error(oids, e)
                    else:
                        self._store_error(
                            oids,
                            TaskError(fname, traceback.format_exc(), repr(e)),
                        )
                    return

        self._pool.submit(run)
        return [ObjectRef(o) for o in oids]

    # -- actor plane ------------------------------------------------------

    def create_actor(
        self,
        cls: type,
        args: tuple,
        kwargs: dict,
        *,
        name: str | None = None,
        max_concurrency: int = 1,
        **_options,
    ) -> str:
        actor_id = ids.new_actor_id()
        with self._lock:
            if name is not None:
                if name in self._named_actors:
                    raise ValueError(f"actor name {name!r} already taken")
                self._named_actors[name] = actor_id
        state = _ActorState(None, max_concurrency, name)
        self._actors[actor_id] = state

        def ctor():
            try:
                a, kw = self._resolve_args(args, kwargs)
                state.instance = cls(*a, **kw)
            except BaseException:  # noqa: BLE001
                state.dead = True
                state.death_cause = traceback.format_exc()
                return

        def worker_loop():
            ctor_done.wait()
            while True:
                item = state.queue.get()
                if item is _POISON:
                    return
                oids, method_name, m_args, m_kwargs, num_returns = item
                if state.dead:
                    self._store_error(
                        oids,
                        ActorError(
                            f"actor {actor_id} is dead: {state.death_cause}"
                        ),
                    )
                    continue
                try:
                    a, kw = self._resolve_args(m_args, m_kwargs)
                    method = getattr(state.instance, method_name)
                    result = method(*a, **kw)
                    self._store_returns(oids, result, num_returns)
                except BaseException as e:  # noqa: BLE001
                    self._store_error(
                        oids,
                        TaskError(
                            f"{cls.__name__}.{method_name}",
                            traceback.format_exc(),
                            repr(e),
                        ),
                    )

        ctor_done = threading.Event()

        def ctor_then_signal():
            ctor()
            ctor_done.set()

        threading.Thread(target=ctor_then_signal, daemon=True).start()
        for _ in range(max_concurrency):
            t = threading.Thread(target=worker_loop, daemon=True)
            t.start()
            state.threads.append(t)
        return actor_id

    def submit_actor_task(
        self,
        actor_id: str,
        method_name: str,
        args: tuple,
        kwargs: dict,
        *,
        num_returns: int = 1,
        **_options,
    ) -> list[ObjectRef]:
        state = self._actors.get(actor_id)
        task_id = ids.new_task_id()
        oids = [ids.object_id_for(task_id, i) for i in range(num_returns)]
        if state is None or state.dead:
            cause = state.death_cause if state else "no such actor"
            err = ActorError(f"actor {actor_id} is dead: {cause}")
            self._store_error(oids, err)
        else:
            state.queue.put((oids, method_name, args, kwargs, num_returns))
        return [ObjectRef(o) for o in oids]

    def kill_actor(self, actor_id: str, no_restart: bool = True) -> None:
        state = self._actors.get(actor_id)
        if state is None:
            return
        state.dead = True
        state.death_cause = "killed via ray_tpu.kill"
        for _ in state.threads:
            state.queue.put(_POISON)
        with self._lock:
            if state.name and self._named_actors.get(state.name) == actor_id:
                del self._named_actors[state.name]

    def get_named_actor(self, name: str) -> str:
        with self._lock:
            aid = self._named_actors.get(name)
        if aid is None:
            raise ValueError(f"no actor named {name!r}")
        return aid

    def cancel(self, ref: ObjectRef, force: bool = False) -> None:
        # Local mode: best-effort no-op (threads are not interruptible).
        pass

    # -- lifecycle --------------------------------------------------------

    def shutdown(self) -> None:
        self._shutdown = True
        for aid in list(self._actors):
            self.kill_actor(aid)
        self._pool.shutdown(wait=False, cancel_futures=True)

    # -- introspection ----------------------------------------------------

    def cluster_resources(self) -> dict:
        return {"CPU": float(self._ncpu)}

    def nodes(self) -> list[dict]:
        return [{"NodeID": "local", "Alive": True, "Resources": self.cluster_resources()}]
