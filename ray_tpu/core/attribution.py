"""Object attribution: who created an object, from which task, where.

The put-time half of the memory observability plane (reference: Ray's
``ray memory`` owner/callsite columns, fed by the CoreWorker stamping
each object's owner and call site when ``RAY_record_ref_creation_sites``
is set). Every ``put``/task-return records cheap always-on fields — the
owning process id, the creating task's name, and the creation wall time
— plus, when ``RAY_TPU_RECORD_CALLSITE`` is on, a trimmed user-code
callsite. The callsite stack walk costs tens of microseconds, so hot
put paths keep it opt-in; everything else is dict assembly.

The attribution dict rides the object's store-entry metadata (an extra
key in the serialization meta — msgpack consumers ignore unknown keys),
the owner's location table, and the head's object directory, so
``ray-tpu memory`` can group live store bytes by task/callsite and the
leak sweeper can say *what* leaked, not just that bytes are stuck.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import time
import traceback

# Frames under the package root are framework plumbing, and stdlib
# frames (threading bootstrap, executor loops) are scaffolding — neither
# is the creation site the user wants to see.
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_STDLIB_DIR = os.path.dirname(os.path.abspath(threading.__file__))

# ContextVar, not a thread-local: per-thread for the sync executor paths
# AND per-asyncio-task for async actor methods (each Task steps in its
# own context copy, so interleaved coroutines can't see each other's
# task name the way a loop-thread-local would leak at await points).
_ctx: "contextvars.ContextVar[tuple | None]" = contextvars.ContextVar(
    "ray_tpu_attribution", default=None)


@contextlib.contextmanager
def task_context(name: str, site: str | None = None):
    """Mark this thread as executing task ``name``: puts (explicit or
    task-return) attribute to it. ``site`` is the task's SUBMIT-time
    callsite (captured where ``.remote()`` ran): by the time a return
    value is stored the user frames are off the stack, so the submit
    site is the fallback creation site. Nests; restores the previous."""
    token = _ctx.set((name, site))
    try:
        yield
    finally:
        _ctx.reset(token)


def current_task() -> str | None:
    """Name of the task executing in this context, if any."""
    cur = _ctx.get()
    return cur[0] if cur else None


def current_site() -> str | None:
    """The running task's submit-time callsite, if one was recorded."""
    cur = _ctx.get()
    return cur[1] if cur else None


def callsite(limit: int = 3) -> str:
    """Trimmed creation callsite: the innermost ``limit`` user-code
    frames (framework/importlib frames skipped), innermost first, as
    ``file.py:LINE in func`` joined by " < "."""
    out = []
    for fr in reversed(traceback.extract_stack()):
        fname = fr.filename or ""
        if fname.startswith(_PKG_DIR) or fname.startswith(_STDLIB_DIR) \
                or "importlib" in fname or fname.startswith("<"):
            continue
        out.append(f"{os.path.basename(fname)}:{fr.lineno} in {fr.name}")
        if len(out) >= limit:
            break
    return " < ".join(out)


def make(owner: str, default_task: str = "driver") -> dict:
    """Attribution record for an object created right now by ``owner``
    (a client/worker process id). ``task`` is the task running on this
    thread, or ``default_task`` outside any task."""
    from ray_tpu.core.config import config

    attr = {
        "owner": owner,
        "task": current_task() or default_task,
        "created_at": round(time.time(), 3),
    }
    if config.record_callsite:
        # Prefer the live stack (a ray_tpu.put in user code points at
        # that line); fall back to the running task's submit site for
        # task returns, whose user frames already unwound.
        site = callsite() or current_site()
        if site:
            attr["callsite"] = site
    return attr


def submit_site() -> str | None:
    """Callsite of a task submission, recorded onto the spec so the
    executing worker can attribute the task's return objects to the
    ``.remote()`` line (None when callsite recording is off)."""
    from ray_tpu.core.config import config

    if not config.record_callsite:
        return None
    return callsite() or None
