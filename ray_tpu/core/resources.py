"""Resource accounting: pools, demands, and blocking acquisition.

Reference parity: the fixed-point resource arithmetic and per-node resource
views of ``src/ray/raylet/scheduling/cluster_resource_scheduler.cc`` and
``local_resource_manager``. We use plain floats (demands are small and
human-entered); atomicity comes from a condition variable rather than an
event loop.

TPU is a first-class resource alongside CPU (SURVEY.md §7 "topology-aware
resource model"). Chip counts come from ``RAY_TPU_CHIPS`` or an explicit
``resources={"TPU": n}`` at init; the train layer passes real
``jax.device_count()`` values when it owns the devices.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Mapping

_EPS = 1e-9


def default_node_resources(num_cpus: float | None = None) -> dict[str, float]:
    cpus = float(num_cpus if num_cpus is not None else (os.cpu_count() or 8))
    res = {"CPU": cpus}
    tpus = float(os.environ.get("RAY_TPU_CHIPS", 0) or 0)
    if tpus:
        res["TPU"] = tpus
    return res


def demand_of(options: Mapping, *, is_actor: bool = False) -> dict[str, float]:
    """Resolve @remote options into a resource demand dict.

    Defaults mirror the reference (``ray_option_utils.py``): tasks take 1 CPU,
    actors take 0 (their creation cost is transient and we don't model it
    separately in-process).
    """
    demand: dict[str, float] = {}
    ncpu = options.get("num_cpus")
    if ncpu is None:
        ncpu = 0 if is_actor else 1
    if ncpu:
        demand["CPU"] = float(ncpu)
    if options.get("num_tpus"):
        demand["TPU"] = float(options["num_tpus"])
    if options.get("num_gpus"):
        demand["GPU"] = float(options["num_gpus"])
    for k, v in (options.get("resources") or {}).items():
        if v:
            demand[k] = float(v)
    return demand


class ResourcePool:
    """A named pool of fractional resources with blocking acquire.

    Used for the node's own capacity and for each placement-group bundle
    (which is capacity carved out of a node pool).
    """

    def __init__(self, total: Mapping[str, float]):
        self._total = {k: float(v) for k, v in total.items() if v > 0}
        self._avail = dict(self._total)
        self._cv = threading.Condition()

    @property
    def total(self) -> dict[str, float]:
        return dict(self._total)

    def available(self) -> dict[str, float]:
        with self._cv:
            return dict(self._avail)

    def feasible(self, demand: Mapping[str, float]) -> bool:
        return all(self._total.get(k, 0.0) + _EPS >= v for k, v in demand.items())

    def _fits(self, demand: Mapping[str, float]) -> bool:
        return all(self._avail.get(k, 0.0) + _EPS >= v for k, v in demand.items())

    def try_acquire(self, demand: Mapping[str, float]) -> bool:
        with self._cv:
            if not self._fits(demand):
                return False
            for k, v in demand.items():
                self._avail[k] = self._avail.get(k, 0.0) - v
            return True

    def acquire(self, demand: Mapping[str, float], timeout: float | None = None) -> bool:
        """Block until the demand fits, then take it. False on timeout or if
        the demand can never fit this pool (infeasible)."""
        if not demand:
            return True
        if not self.feasible(demand):
            return False
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while not self._fits(demand):
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(remaining)
            for k, v in demand.items():
                self._avail[k] = self._avail.get(k, 0.0) - v
            return True

    def release(self, demand: Mapping[str, float]) -> None:
        if not demand:
            return
        with self._cv:
            for k, v in demand.items():
                self._avail[k] = min(self._total.get(k, 0.0), self._avail.get(k, 0.0) + v)
            self._cv.notify_all()
