"""Binary IDs for objects/tasks/actors/nodes/jobs.

Reference parity: ``src/ray/common/id.h`` — Ray embeds lineage (task id +
return index) in object IDs; we keep that property so ownership and lineage
reconstruction (M-later) can recover an object's creating task from its ID
alone.

Layout (hex strings over random bytes):
  TaskID   = 16 random bytes
  ObjectID = task_id (16B) + 4B big-endian return index
  ActorID / NodeID / JobID / PlacementGroupID = 12 random bytes, prefixed.
"""

from __future__ import annotations

import os

_TASK_LEN = 16
_INDEX_LEN = 4


def new_task_id() -> str:
    return os.urandom(_TASK_LEN).hex()


def object_id_for(task_id: str, index: int) -> str:
    return task_id + index.to_bytes(_INDEX_LEN, "big").hex()


def new_object_id() -> str:
    """For ray.put — synthesizes a fresh 'put task' id with index 0."""
    return object_id_for(new_task_id(), 0)


def task_of_object(object_id: str) -> tuple[str, int]:
    tid = object_id[: _TASK_LEN * 2]
    idx = int(object_id[_TASK_LEN * 2 :], 16)
    return tid, idx


def new_actor_id() -> str:
    return "act-" + os.urandom(12).hex()


def new_node_id() -> str:
    return "node-" + os.urandom(12).hex()


def new_job_id() -> str:
    return "job-" + os.urandom(12).hex()


def new_placement_group_id() -> str:
    return "pg-" + os.urandom(12).hex()
