"""System configuration registry (``RAY_CONFIG`` analog).

Reference: ``src/ray/common/ray_config_def.h`` + ``ray_config.h`` — a
single typed registry of tunables, each overridable from the environment
without code changes. Here every knob ``foo_bar`` reads its override from
``RAY_TPU_FOO_BAR`` (parsed to the declared type) at first access;
``config.foo_bar`` afterwards is cached process-wide.

Usage:
    from ray_tpu.core.config import config
    interval = config.heartbeat_interval_s

Tests / embedders can force values with ``config.override(name, value)``
(and ``config.reset()`` to drop all overrides and re-read the env).
"""

from __future__ import annotations

import os
import threading
from typing import Any

_ENV_PREFIX = "RAY_TPU_"


def _parse_bool(raw: str) -> bool:
    return raw.strip().lower() in ("1", "true", "yes", "on")


# name -> (type, default). The single source of truth for system knobs.
_DEFS: dict[str, tuple[type, Any]] = {
    # -- control plane -----------------------------------------------------
    "heartbeat_interval_s": (float, 0.25),
    "node_death_timeout_s": (float, 5.0),
    "head_reconnect_window_s": (float, 15.0),
    "head_snapshot_interval_s": (float, 0.2),
    # Write-behind persistence (head _PersistentStore): the flusher
    # thread commits the coalesced dirty queue as ONE sqlite transaction
    # every interval (whole batches land or don't — never a torn row);
    # max_batch bounds one transaction's statement count. Crash loss
    # window <= one interval; snapshot/shutdown flush synchronously.
    "head_persist_flush_interval_s": (float, 0.05),
    "head_persist_max_batch": (int, 2_000),
    # Tracing spans the head retains (ring buffer; older spans drop and
    # the drop counter surfaces in rpc_pubsub_stats / metrics). Bounds
    # head RSS under a 100k-task burst's span upload.
    "head_span_retention": (int, 100_000),
    # -- trace assembly (cluster/traces.py flight recorder) ----------------
    # Assembled traces the head keeps after tail sampling (bounded ring;
    # evictions counted in ray_tpu_head_traces_dropped_total).
    "head_trace_retention": (int, 512),
    # Tail-sampling keep probability for unremarkable traces. Errored
    # traces and traces slower than trace_slow_threshold_s are ALWAYS
    # kept — sampling only thins the healthy fast ones.
    "trace_sample_rate": (float, 0.05),
    "trace_slow_threshold_s": (float, 1.0),
    # A pending trace finalizes (tail-sampling decision) once its span
    # stream has been quiet this long; spans per trace are capped (the
    # clip is counted, never silent).
    "trace_quiet_s": (float, 1.5),
    "trace_max_spans": (int, 4096),
    # Agents probe the head's clock every Nth heartbeat (NTP-style
    # request/response timestamps -> per-node offset for cross-node
    # span alignment); 0 disables probing.
    "clock_probe_every_beats": (int, 10),
    # -- worker pool -------------------------------------------------------
    "workers_per_cpu": (int, 4),
    "worker_start_timeout_s": (float, 60.0),
    "worker_min_pool": (int, 4),
    # Plain-env workers forked at agent boot (worker_pool.cc prestart);
    # 0 disables. The delay keeps mass cluster boots from fork-storming.
    "worker_prestart_per_cpu": (float, 1.0),
    "worker_prestart_delay_s": (float, 2.0),
    # Pause between consecutive prestart forks (per agent): keeps a mass
    # cluster boot's fork storm off the CPU exactly when node
    # registration needs it.
    "worker_prestart_spacing_s": (float, 1.0),
    # Comma-separated substrings: PYTHONPATH entries matching any are
    # stripped from WORKER processes so site hooks that pre-import heavy
    # frameworks at interpreter startup (a TPU plugin's sitecustomize
    # importing jax) don't serialize every fork. "" disables.
    "worker_pythonpath_exclude": (str, ".axon_site"),
    # -- node reporter (per-worker observability) --------------------------
    # Agent sampling cadence for per-worker CPU/RSS/uptime gauges
    # (reporter_agent.py analog); 0 disables the telemetry loop.
    "worker_telemetry_interval_s": (float, 1.0),
    # Dead workers whose log files stay indexed (and on disk) per agent.
    "worker_log_retention": (int, 1000),
    # -- resource-view gossip (ray_syncer.h analog) ------------------------
    # Node agents exchange per-node load views peer-to-peer so spillback
    # can place directly on a peer without the head. 0 disables gossip.
    "gossip_interval_s": (float, 0.5),
    "gossip_fanout": (int, 2),
    # Refresh membership (join/dead) from the head every N gossip ticks.
    "gossip_membership_every": (int, 10),
    # -- object plane ------------------------------------------------------
    "object_store_capacity_bytes": (int, 512 << 20),
    "transfer_chunk_bytes": (int, 4 << 20),
    "transfer_whole_fetch_max_bytes": (int, 8 << 20),
    "transfer_pull_concurrency": (int, 8),
    # Objects up to this many chunks pull via ONE streaming RPC (server
    # pipelines chunk frames); bigger objects fan out over parallel
    # per-chunk pulls on multiple connections.
    "transfer_stream_max_chunks": (int, 8),
    # Cap on total in-flight chunked-pull bytes per process; blocked
    # pulls admit by priority get > wait > args (pull_manager.h analog).
    "pull_max_inflight_bytes": (int, 256 << 20),
    "spill_headroom_bytes": (int, 64 << 10),
    # Remote spill target (external_storage.py analog): a URI whose
    # scheme picks a registered spill backend (cluster/spill_storage.py;
    # "file:///shared/dir" ships). "" keeps the per-node session spill
    # dir — node-local, so a dead node takes its spilled objects with
    # it. With a remote URI the head records every spilled object and
    # lineage recovery RESTORES it from the target onto a live node
    # instead of recomputing (or losing) it.
    "spill_uri": (str, ""),
    # -- data plane --------------------------------------------------------
    # Dynamic block splitting: read/map tasks split output blocks bigger
    # than this into store-friendly pieces (each its own object) so one
    # skewed multi-GiB block cannot OOM the store. 0 disables splitting
    # (legacy single-object stage outputs).
    "target_block_size_bytes": (int, 128 << 20),
    # -- memory protection -------------------------------------------------
    "memory_usage_threshold": (float, 0.95),
    "memory_limit_bytes": (int, 0),  # 0 = no aggregate-RSS limit
    "memory_monitor_interval_s": (float, 0.25),
    # -- memory observability ----------------------------------------------
    # Record a trimmed user-code callsite on every put/task-return object
    # (``ray memory`` callsite column analog). Off by default: the stack
    # walk is measurable on hot put paths; the cheap fields — owner
    # worker id, creating task name, creation time — are always on.
    "record_callsite": (bool, False),
    # Head-side leak sweeper: an object alive longer than the threshold
    # with zero registered holders (or held refs whose every replica is
    # gone) is flagged in ``state.memory_leaks()`` / ``ray-tpu memory
    # --leaks``. 0 disables the sweeper.
    "leak_age_threshold_s": (float, 300.0),
    "leak_sweep_interval_s": (float, 5.0),
    # -- tasks -------------------------------------------------------------
    "task_default_max_retries": (int, 3),
    "pending_task_timeout_s": (float, 120.0),
    # How long a caller blocks for an actor's registration to appear on
    # the head (mass actor creation forks one process per actor; deep
    # bursts need room).
    "actor_register_timeout_s": (float, 60.0),
    # Lease pipelining (direct_task_transport.h analog): how many specs a
    # client batches into one schedule/submit RPC. (Leased-push admission
    # itself is capacity-based, not depth-based — see
    # node_agent.rpc_submit_tasks_leased.)
    "submit_batch_max": (int, 256),
    # Unplaceable-spec retry backoff (client _retry_heap): the first
    # re-schedule attempt comes after base_s, doubling per miss up to
    # max_s. A flat timer at 100k parked specs re-batched EVERY tick
    # through schedule_batch — ~400 head RPCs per 250ms of pure misses;
    # backoff decays that to a trickle while staying responsive when
    # capacity appears within the first few attempts.
    "submit_retry_base_s": (float, 0.25),
    "submit_retry_max_s": (float, 2.0),
    # Finished-task records each node agent retains (ring; evictions
    # count into ray_tpu_task_records_evicted_total).
    "task_record_retention": (int, 10_000),
    # Nested-timeout budgets (the analyzer's timeout-budget annotations
    # relate inner RPC timeouts to these — edit one side and `ray-tpu
    # analyze` fails instead of a healthy task dying):
    # how long an agent's task_unblocked handler may block re-acquiring
    # the CPU slot on a saturated node...
    "cpu_reacquire_budget_s": (float, 300.0),
    # ...and how long a 2PC prepare may block carving out a PG bundle's
    # reservation on a busy node.
    "bundle_reserve_timeout_s": (float, 60.0),
    # -- node drain / preemption -------------------------------------------
    # Default deadline a graceful drain gives in-flight tasks before the
    # node is force-removed (DrainRaylet deadline analog).
    "drain_deadline_s": (float, 30.0),
    # Agent-side preemption watcher cadence; the watcher thread only
    # starts when a signal source below is configured.
    "preemption_poll_interval_s": (float, 1.0),
    # Test/ops hook: a node self-drains with reason="preemption" when
    # this file exists and is empty or contains its node id.
    "preemption_signal_file": (str, ""),
    # Cloud hook: metadata endpoint polled for a termination notice
    # (GCE: .../computeMetadata/v1/instance/preempted returns "TRUE").
    "preemption_metadata_url": (str, ""),
    # -- autoscaler execution half (boot-loop robustness) -------------------
    # Wall-clock budget for one provider create_node call; past it the
    # launch counts as failed (the provider call may still land — the
    # reconcile loop adopts it via non_terminated_nodes on a later pass).
    "autoscaler_launch_timeout_s": (float, 120.0),
    # Jittered exponential backoff between launch attempts for a node
    # type whose last create failed: base * 2^(failures-1), capped.
    "autoscaler_launch_backoff_base_s": (float, 1.0),
    "autoscaler_launch_backoff_max_s": (float, 30.0),
    # Consecutive boot failures before a node type is quarantined
    # (benched for the cooldown; demand falls through to the next
    # feasible type) — a flapping provider can never hot-loop create.
    "autoscaler_quarantine_failures": (int, 3),
    "autoscaler_quarantine_cooldown_s": (float, 60.0),
    # -- chaos / fault injection -------------------------------------------
    # One seed for ALL chaos randomness (failpoint probability RNGs,
    # network-chaos delay/jitter draws, soak schedules, the chaos test's
    # victim choice) so any chaos run replays from one env var. 0 =
    # unseeded (OS entropy).
    "chaos_seed": (int, 0),
    # -- signal plane (head metrics history + SLO evaluation) --------------
    # The head self-scrapes its own federated /metrics/cluster body into
    # a bounded in-memory time-series ring every interval; 0 disables
    # the scrape loop (and every history-backed surface falls back to
    # its single-scrape behaviour).
    "signal_scrape_interval_s": (float, 2.0),
    # Per-series retention window: samples older than this age out of
    # the ring (bounded-retention discipline — head RSS must not grow
    # with uptime).
    "signal_history_s": (float, 600.0),
    # Hard cap on distinct series the ring retains; past it the
    # least-recently-updated series is evicted (and counted into
    # ray_tpu_head_signal_evictions_total).
    "signal_max_series": (int, 50_000),
    # SLO evaluator cadence (burn-rate state machine over the ring);
    # 0 disables the loop. Defaults to the scrape cadence.
    "slo_eval_interval_s": (float, 2.0),
    # Consecutive breaching evaluations before an SLO transitions to
    # burning (hysteresis: one scrape gap or blip must not flap it).
    "slo_burn_evals": (int, 3),
    # -- pubsub ------------------------------------------------------------
    "pubsub_max_buffer": (int, 10_000),
    "pubsub_subscriber_ttl_s": (float, 120.0),
    # -- security ----------------------------------------------------------
    "cluster_token": (str, ""),
    # -- cross-language ----------------------------------------------------
    # Default C++ worker binary agents spawn for lang="cpp" tasks (the
    # reference's equivalent is the per-language worker command the raylet
    # worker pool is configured with, worker_pool.h:80). Empty = cpp tasks
    # must carry an explicit binary path.
    "cpp_worker_bin": (str, ""),
}


class _Config:
    def __init__(self):
        self._lock = threading.Lock()
        self._cache: dict[str, Any] = {}

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.get(name)

    def get(self, name: str):
        if name not in _DEFS:
            raise AttributeError(f"unknown config {name!r}; known: "
                                 f"{sorted(_DEFS)}")
        with self._lock:
            if name in self._cache:
                return self._cache[name]
            typ, default = _DEFS[name]
            raw = os.environ.get(_ENV_PREFIX + name.upper())
            if raw is None:
                value = default
            elif typ is bool:
                value = _parse_bool(raw)
            else:
                value = typ(raw)
            self._cache[name] = value
            return value

    def override(self, name: str, value) -> None:
        if name not in _DEFS:
            raise AttributeError(f"unknown config {name!r}")
        with self._lock:
            self._cache[name] = value

    def reset(self, name: str | None = None) -> None:
        with self._lock:
            if name is None:
                self._cache.clear()
            else:
                self._cache.pop(name, None)

    def snapshot(self) -> dict:
        return {name: self.get(name) for name in _DEFS}


config = _Config()
