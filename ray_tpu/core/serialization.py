"""Object serialization: cloudpickle + pickle-5 out-of-band buffers.

Mirrors the reference's split (``python/ray/_private/serialization.py``):
the pickle stream carries structure, while large contiguous buffers (numpy
arrays, jax host arrays, arrow buffers) travel out-of-band so that reads
from the shm store are zero-copy — the deserialized numpy array's memory IS
the store segment, exactly like plasma's numpy/Arrow views (SURVEY.md §3.3).

Wire format of one serialized object:
    meta  = msgpack: {"n": num_buffers, "sizes": [..], "inline": bool}
    data  = pickled bytes || buffer0 || buffer1 || ...  (8-byte aligned)
"""

from __future__ import annotations

import pickle
from typing import Any

import cloudpickle
import msgpack

ALIGN = 64


def _aligned(n: int) -> int:
    return (n + ALIGN - 1) & ~(ALIGN - 1)


def serialize(
    value: Any, found_refs: list | None = None,
    extra_meta: dict | None = None,
) -> tuple[bytes, list[bytes | memoryview]]:
    """Returns (meta, chunks). Concatenating chunks gives the data payload.
    ``found_refs``: optional list that receives the ids of any ObjectRefs
    nested in ``value`` (feeds distributed ref-counting).
    ``extra_meta``: extra keys packed into the meta document (e.g. the
    put-time attribution record) — ``deserialize`` only reads "sizes",
    so consumers that don't know a key ignore it."""
    from ray_tpu.core.object_ref import capture_refs

    buffers: list[pickle.PickleBuffer] = []
    with capture_refs(found_refs if found_refs is not None else []):
        payload = cloudpickle.dumps(
            value, protocol=5, buffer_callback=buffers.append
        )
    raw = [b.raw() for b in buffers]
    sizes = [len(payload)] + [len(r) for r in raw]
    chunks: list[bytes | memoryview] = []
    offset = 0
    for part in [payload, *raw]:
        pad = _aligned(offset) - offset
        if pad:
            chunks.append(b"\x00" * pad)
            offset += pad
        chunks.append(part)
        offset += len(part)
    doc = {"sizes": sizes}
    if extra_meta:
        doc.update(extra_meta)
    meta = msgpack.packb(doc)
    return meta, chunks


def total_size(chunks: list[bytes | memoryview]) -> int:
    return sum(len(c) for c in chunks)


def deserialize(meta: bytes, data) -> Any:
    """``data``: bytes-like covering the full payload (zero-copy memoryview
    straight from the shm segment, or bytes off the wire)."""
    info = msgpack.unpackb(meta)
    sizes = info["sizes"]
    view = memoryview(data)
    parts = []
    offset = 0
    for size in sizes:
        offset = _aligned(offset) if offset else 0
        # first part starts at 0; subsequent start aligned
        parts.append(view[offset : offset + size])
        offset += size
    payload, bufs = parts[0], parts[1:]
    return pickle.loads(payload, buffers=bufs)


def num_buffers(meta: bytes) -> int:
    """Out-of-band buffer count recorded in a serialized object's meta."""
    return len(msgpack.unpackb(meta)["sizes"]) - 1


def meta_field(meta: bytes, key: str, default=None):
    """One extra key out of a serialized object's meta document (e.g.
    ``attr`` — the put-time attribution record); ``default`` on absent
    keys or undecodable meta (error markers from pre-attribution code)."""
    try:
        return msgpack.unpackb(meta).get(key, default)
    except Exception:
        return default


def dumps(value: Any, found_refs: list | None = None) -> bytes:
    """One-shot in-band serialization (control-plane messages).
    ``found_refs``: see :func:`serialize`."""
    from ray_tpu.core.object_ref import capture_refs

    with capture_refs(found_refs if found_refs is not None else []):
        return cloudpickle.dumps(value)


def loads(blob: bytes) -> Any:
    return pickle.loads(blob)
