"""ObjectRef: a future handle to an immutable object in the object plane.

Reference parity: ``python/ray/_raylet.pyx`` ObjectRef + the ownership model
of ``src/ray/core_worker/reference_count.h:61`` (every object has an owning
worker). Here the owner is recorded as metadata; local mode has a single
owner (the driver process).
"""

from __future__ import annotations

import threading
from typing import Any

# Serialization-time ref capture (reference_count.h borrower registration
# analog): while a collector list is installed, every ObjectRef pickled on
# this thread records its id — so put() knows which refs a value contains
# and submit() knows which refs travel as task args.
_capture = threading.local()


def capture_refs(collector: list):
    """Context manager: collect ids of ObjectRefs serialized on this thread."""

    class _Ctx:
        def __enter__(self):
            self.prev = getattr(_capture, "collector", None)
            _capture.collector = collector
            return collector

        def __exit__(self, *exc):
            _capture.collector = self.prev

    return _Ctx()


def _rehydrate_ref(object_id: str, owner: str):
    """Unpickle hook: hand the ref to the process-wide backend so it can
    register this process as a holder (distributed ref-counting)."""
    from ray_tpu._private import worker as worker_mod

    b = worker_mod._backend
    if b is not None and hasattr(b, "on_ref_deserialized"):
        return b.on_ref_deserialized(object_id, owner)
    return ObjectRef(object_id, owner)


class ObjectRef:
    __slots__ = ("id", "_owner", "__weakref__")

    def __init__(self, object_id: str, owner: str = ""):
        self.id = object_id
        self._owner = owner

    def hex(self) -> str:
        return self.id

    def __repr__(self) -> str:
        return f"ObjectRef({self.id[:16]}…)"

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, ObjectRef) and other.id == self.id

    def __hash__(self) -> int:
        return hash(self.id)

    def __reduce__(self):
        collector = getattr(_capture, "collector", None)
        if collector is not None:
            collector.append(self.id)
        return (_rehydrate_ref, (self.id, self._owner))


class TaskError(Exception):
    """A task raised; re-raised at every ray.get of its outputs.

    Mirrors ``ray.exceptions.RayTaskError`` — carries the remote traceback.
    """

    def __init__(self, function_name: str, remote_traceback: str, cause_repr: str):
        self.function_name = function_name
        self.remote_traceback = remote_traceback
        self.cause_repr = cause_repr
        super().__init__(
            f"task {function_name} failed:\n{remote_traceback}"
        )

    def __reduce__(self):
        return (
            TaskError,
            (self.function_name, self.remote_traceback, self.cause_repr),
        )


class TaskCancelledError(TaskError):
    """The task was cancelled via ray_tpu.cancel
    (cf. ``ray.exceptions.TaskCancelledError``). Raised at every get() of
    the cancelled task's outputs. Subclasses TaskError so every store/raise
    path that forwards task failures forwards cancellations unchanged.
    Zero-arg constructible: cooperative cancellation injects the CLASS into
    the executing thread (PyThreadState_SetAsyncExc instantiates it bare).
    """

    def __init__(self, function_name: str = "task",
                 remote_traceback: str = "",
                 cause_repr: str = "cancelled"):
        self.function_name = function_name
        self.remote_traceback = remote_traceback
        self.cause_repr = cause_repr
        Exception.__init__(self, f"task {function_name} was cancelled")

    def __reduce__(self):
        return (
            TaskCancelledError,
            (self.function_name, self.remote_traceback, self.cause_repr),
        )


class OutOfMemoryError(TaskError):
    """The node's memory monitor killed this task's worker to protect the
    node (cf. ``ray.exceptions.OutOfMemoryError``; policy in
    ``worker_killing_policy.h``)."""

    def __init__(self, function_name: str = "task",
                 remote_traceback: str = "",
                 cause_repr: str = "oom-killed"):
        self.function_name = function_name
        self.remote_traceback = remote_traceback
        self.cause_repr = cause_repr
        Exception.__init__(
            self,
            f"task {function_name} was killed by the memory monitor: "
            f"{remote_traceback}"
        )

    def __reduce__(self):
        return (
            OutOfMemoryError,
            (self.function_name, self.remote_traceback, self.cause_repr),
        )


class ActorError(Exception):
    """The actor died before/while executing this call (cf. RayActorError)."""


class GetTimeoutError(TimeoutError):
    """ray.get(timeout=...) expired (cf. ray.exceptions.GetTimeoutError)."""


class ObjectLostError(Exception):
    """Object is gone and cannot be recovered (cf. ray.exceptions.ObjectLostError)."""
