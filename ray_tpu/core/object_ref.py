"""ObjectRef: a future handle to an immutable object in the object plane.

Reference parity: ``python/ray/_raylet.pyx`` ObjectRef + the ownership model
of ``src/ray/core_worker/reference_count.h:61`` (every object has an owning
worker). Here the owner is recorded as metadata; local mode has a single
owner (the driver process).
"""

from __future__ import annotations

import threading
from typing import Any

# Serialization-time ref capture (reference_count.h borrower registration
# analog): while a collector list is installed, every ObjectRef pickled on
# this thread records its id — so put() knows which refs a value contains
# and submit() knows which refs travel as task args.
_capture = threading.local()


def capture_refs(collector: list):
    """Context manager: collect ids of ObjectRefs serialized on this thread."""

    class _Ctx:
        def __enter__(self):
            self.prev = getattr(_capture, "collector", None)
            _capture.collector = collector
            return collector

        def __exit__(self, *exc):
            _capture.collector = self.prev

    return _Ctx()


def _rehydrate_ref(object_id: str, owner: str):
    """Unpickle hook: hand the ref to the process-wide backend so it can
    register this process as a holder (distributed ref-counting)."""
    from ray_tpu._private import worker as worker_mod

    b = worker_mod._backend
    if b is not None and hasattr(b, "on_ref_deserialized"):
        return b.on_ref_deserialized(object_id, owner)
    return ObjectRef(object_id, owner)


class ObjectRef:
    __slots__ = ("id", "_owner", "__weakref__")

    def __init__(self, object_id: str, owner: str = ""):
        self.id = object_id
        self._owner = owner

    def hex(self) -> str:
        return self.id

    def __repr__(self) -> str:
        return f"ObjectRef({self.id[:16]}…)"

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, ObjectRef) and other.id == self.id

    def __hash__(self) -> int:
        return hash(self.id)

    def __reduce__(self):
        collector = getattr(_capture, "collector", None)
        if collector is not None:
            collector.append(self.id)
        return (_rehydrate_ref, (self.id, self._owner))


class TaskError(Exception):
    """A task raised; re-raised at every ray.get of its outputs.

    Mirrors ``ray.exceptions.RayTaskError`` — carries the remote traceback.
    """

    def __init__(self, function_name: str, remote_traceback: str, cause_repr: str):
        self.function_name = function_name
        self.remote_traceback = remote_traceback
        self.cause_repr = cause_repr
        super().__init__(
            f"task {function_name} failed:\n{remote_traceback}"
        )

    def __reduce__(self):
        return (
            TaskError,
            (self.function_name, self.remote_traceback, self.cause_repr),
        )


class TaskCancelledError(TaskError):
    """The task was cancelled via ray_tpu.cancel
    (cf. ``ray.exceptions.TaskCancelledError``). Raised at every get() of
    the cancelled task's outputs. Subclasses TaskError so every store/raise
    path that forwards task failures forwards cancellations unchanged.
    Zero-arg constructible: cooperative cancellation injects the CLASS into
    the executing thread (PyThreadState_SetAsyncExc instantiates it bare).
    """

    def __init__(self, function_name: str = "task",
                 remote_traceback: str = "",
                 cause_repr: str = "cancelled"):
        self.function_name = function_name
        self.remote_traceback = remote_traceback
        self.cause_repr = cause_repr
        Exception.__init__(self, f"task {function_name} was cancelled")

    def __reduce__(self):
        return (
            TaskCancelledError,
            (self.function_name, self.remote_traceback, self.cause_repr),
        )


class OutOfMemoryError(TaskError):
    """The node's memory monitor killed this task's worker to protect the
    node (cf. ``ray.exceptions.OutOfMemoryError``; policy in
    ``worker_killing_policy.h``)."""

    def __init__(self, function_name: str = "task",
                 remote_traceback: str = "",
                 cause_repr: str = "oom-killed"):
        self.function_name = function_name
        self.remote_traceback = remote_traceback
        self.cause_repr = cause_repr
        Exception.__init__(
            self,
            f"task {function_name} was killed by the memory monitor: "
            f"{remote_traceback}"
        )

    def __reduce__(self):
        return (
            OutOfMemoryError,
            (self.function_name, self.remote_traceback, self.cause_repr),
        )


class ActorError(Exception):
    """The actor died before/while executing this call (cf. RayActorError)."""


class GetTimeoutError(TimeoutError):
    """ray.get(timeout=...) expired (cf. ray.exceptions.GetTimeoutError)."""


class ObjectLostError(Exception):
    """Object is gone and cannot be recovered (cf. ray.exceptions.ObjectLostError)."""


class _StreamEnd:
    """Terminator a streaming task stores after its last yielded item
    (``num_returns="streaming"`` protocol: item i lives at return-index
    i of the task; the first index holding a ``_StreamEnd`` marks the
    stream's length)."""

    def __reduce__(self):
        return (_StreamEnd, ())


class ObjectRefGenerator:
    """Iterator over a streaming task's output refs (reference
    ``ObjectRefGenerator`` / ``num_returns="streaming"``): each
    ``__next__`` blocks until the task yields its next item, then
    returns that item's ObjectRef (the value is already local, so the
    caller's ``get`` is cheap). Iteration ends at the task's return; a
    mid-stream task error raises at the failing index's ``get``.

    Lineage note: only the stream's index-0 object is tracked for
    re-execution; losing a later chunk after the driver dropped its ref
    is not recoverable (v1 limitation)."""

    def __init__(self, task_id: str, first_ref: "ObjectRef | None" = None):
        self._task_id = task_id
        self._i = 0
        self._first = first_ref

    def __iter__(self) -> "ObjectRefGenerator":
        return self

    def _ref_at(self, i: int) -> "ObjectRef":
        from ray_tpu._private import worker as _worker
        from ray_tpu.core import ids

        if i == 0 and self._first is not None:
            return self._first
        # Later indices inherit the stream's owner from the index-0 ref
        # (the submitting client), so a consumer that is NOT the owner
        # still resolves locations against the right directory.
        owner = getattr(self._first, "_owner", None) if self._first else None
        return _worker.backend().make_ref(
            ids.object_id_for(self._task_id, i), owner)

    def __next__(self) -> "ObjectRef":
        from ray_tpu._private import worker as _worker

        ref = self._ref_at(self._i)
        value = _worker.backend().get([ref])[0]  # raises task errors
        if isinstance(value, _StreamEnd):
            raise StopIteration
        self._i += 1
        return ref

    def __del__(self):
        # Abandoned stream: release unconsumed tail items (and ask the
        # producer to stop) — otherwise they sit in the store with no
        # holder until process exit. Best-effort: at interpreter
        # shutdown the backend may already be gone.
        try:
            from ray_tpu._private import worker as _worker

            if _worker.is_initialized():
                _worker.backend().release_stream(self._task_id, self._i)
        except Exception:
            pass

    def __repr__(self) -> str:
        return f"ObjectRefGenerator(task={self._task_id[:12]}…, next={self._i})"
