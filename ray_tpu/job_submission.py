"""Job submission: run driver entrypoints under a supervisor actor.

Reference parity: ``python/ray/job_submission`` + ``dashboard/modules/job``
— submit a shell entrypoint, poll status, fetch logs; the driver runs as a
subprocess supervised by a ``JobSupervisor`` actor (``job_manager.py``).
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
import uuid
from typing import Dict, List, Optional

import ray_tpu

_MANAGER_NAME = "ray_tpu.job_manager"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


class _JobManager:
    """Named actor: registry + supervisor threads for submitted jobs."""

    def __init__(self):
        self.jobs: Dict[str, dict] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    def submit(self, entrypoint: str, job_id: Optional[str],
               runtime_env: Optional[dict], metadata: Optional[dict]) -> str:
        job_id = job_id or f"raytpu_job_{uuid.uuid4().hex[:10]}"
        with self._lock:
            if job_id in self.jobs:
                raise ValueError(f"job {job_id} already exists")
            self.jobs[job_id] = {
                "job_id": job_id,
                "entrypoint": entrypoint,
                "status": JobStatus.PENDING,
                "logs": "",
                "metadata": metadata or {},
                "start_time": time.time(),
                "end_time": None,
            }
        threading.Thread(
            target=self._supervise, args=(job_id, entrypoint, runtime_env),
            daemon=True,
        ).start()
        return job_id

    def _supervise(self, job_id: str, entrypoint: str,
                   runtime_env: Optional[dict]):
        env = dict(os.environ)
        for k, v in ((runtime_env or {}).get("env_vars") or {}).items():
            env[k] = str(v)
        try:
            proc = subprocess.Popen(
                entrypoint, shell=True, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                cwd=(runtime_env or {}).get("working_dir") or None,
            )
        except OSError as e:
            with self._lock:
                self.jobs[job_id]["status"] = JobStatus.FAILED
                self.jobs[job_id]["logs"] = f"failed to start: {e}"
                self.jobs[job_id]["end_time"] = time.time()
            return
        with self._lock:
            self.jobs[job_id]["status"] = JobStatus.RUNNING
            self._procs[job_id] = proc
        out, _ = proc.communicate()
        with self._lock:
            job = self.jobs[job_id]
            job["logs"] = out or ""
            job["end_time"] = time.time()
            if job["status"] != JobStatus.STOPPED:
                job["status"] = (
                    JobStatus.SUCCEEDED if proc.returncode == 0
                    else JobStatus.FAILED
                )
            self._procs.pop(job_id, None)

    def status(self, job_id: str) -> str:
        return self.jobs[job_id]["status"]

    def logs(self, job_id: str) -> str:
        return self.jobs[job_id]["logs"]

    def info(self, job_id: str) -> dict:
        return dict(self.jobs[job_id])

    def list_jobs(self) -> List[dict]:
        return [dict(j) for j in self.jobs.values()]

    def stop(self, job_id: str) -> bool:
        with self._lock:
            proc = self._procs.get(job_id)
            if proc is None:
                return False
            self.jobs[job_id]["status"] = JobStatus.STOPPED
        proc.terminate()
        return True


def _manager():
    try:
        return ray_tpu.get_actor(_MANAGER_NAME)
    except ValueError:
        pass
    cls = ray_tpu.remote(_JobManager)
    try:
        handle = cls.options(
            name=_MANAGER_NAME, num_cpus=0, max_concurrency=4
        ).remote()
        ray_tpu.get(handle.list_jobs.remote(), timeout=30)
        return handle
    except ValueError:
        return ray_tpu.get_actor(_MANAGER_NAME)


class JobSubmissionClient:
    """Mirrors the reference client surface (``job_submission/__init__``)."""

    def __init__(self, address: Optional[str] = None):
        if address and not ray_tpu.is_initialized():
            ray_tpu.init(address)
        self._mgr = _manager()

    def submit_job(self, *, entrypoint: str, job_id: Optional[str] = None,
                   runtime_env: Optional[dict] = None,
                   metadata: Optional[dict] = None) -> str:
        return ray_tpu.get(
            self._mgr.submit.remote(entrypoint, job_id, runtime_env, metadata),
            timeout=60,
        )

    def get_job_status(self, job_id: str) -> str:
        return ray_tpu.get(self._mgr.status.remote(job_id), timeout=30)

    def get_job_logs(self, job_id: str) -> str:
        return ray_tpu.get(self._mgr.logs.remote(job_id), timeout=30)

    def get_job_info(self, job_id: str) -> dict:
        return ray_tpu.get(self._mgr.info.remote(job_id), timeout=30)

    def list_jobs(self) -> List[dict]:
        return ray_tpu.get(self._mgr.list_jobs.remote(), timeout=30)

    def stop_job(self, job_id: str) -> bool:
        return ray_tpu.get(self._mgr.stop.remote(job_id), timeout=30)

    def wait_until_finished(self, job_id: str, timeout: float = 300.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in (JobStatus.SUCCEEDED, JobStatus.FAILED,
                          JobStatus.STOPPED):
                return status
            time.sleep(0.2)
        raise TimeoutError(f"job {job_id} did not finish in {timeout}s")
