"""Lazy DAG authoring: ``.bind()`` graphs executed over tasks/actors.

Reference parity: ``python/ray/dag`` — ``DAGNode`` (``dag_node.py:23``),
Function/ClassMethod nodes, ``InputNode`` placeholder, ``MultiOutputNode``;
used by Serve's deployment graphs and the Workflow layer.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.actor import ActorClass
from ray_tpu.remote_function import RemoteFunction


class DAGNode:
    def __init__(self, bound_args: tuple, bound_kwargs: dict):
        self._bound_args = bound_args
        self._bound_kwargs = bound_kwargs

    def _deps(self) -> List["DAGNode"]:
        out = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                out.append(a)
        return out

    def execute(self, *input_args, **input_kwargs):
        """Execute the whole graph; returns the root's result (resolved)."""
        refs = _execute_graph(self, input_args, input_kwargs)
        value = refs[self]
        if isinstance(value, list):
            return ray_tpu.get(value)
        return ray_tpu.get(value) if isinstance(value, ray_tpu.ObjectRef) else value

    # structural identity for workflow checkpoint keys
    def _structure_name(self) -> str:
        return type(self).__name__


class FunctionNode(DAGNode):
    def __init__(self, remote_fn: RemoteFunction, args: tuple, kwargs: dict):
        super().__init__(args, kwargs)
        self._fn = remote_fn

    def _structure_name(self) -> str:
        return getattr(self._fn.func, "__name__", "fn")

    def _submit(self, args, kwargs):
        return self._fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """Actor construction node: methods on it create ClassMethodNodes."""

    def __init__(self, actor_cls: ActorClass, args: tuple, kwargs: dict):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls

    def _structure_name(self) -> str:
        return self._actor_cls.cls.__name__

    def _submit(self, args, kwargs):
        return self._actor_cls.remote(*args, **kwargs)  # ActorHandle

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        class _MethodBinder:
            def __init__(self, node, method):
                self.node = node
                self.method = method

            def bind(self, *args, **kwargs):
                return ClassMethodNode(self.node, self.method, args, kwargs)

        return _MethodBinder(self, name)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        super().__init__((class_node, *args), kwargs)
        self._method = method

    def _structure_name(self) -> str:
        return f"{self._bound_args[0]._structure_name()}.{self._method}"

    def _submit(self, args, kwargs):
        handle, *rest = args
        return getattr(handle, self._method).remote(*rest, **kwargs)


class InputNode(DAGNode):
    """Placeholder for execute()-time input; usable as a context manager
    (``with InputNode() as inp:``)."""

    def __init__(self):
        super().__init__((), {})

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def _structure_name(self) -> str:
        return "input"


class MultiOutputNode(DAGNode):
    def __init__(self, outputs: List[DAGNode]):
        super().__init__(tuple(outputs), {})

    def _structure_name(self) -> str:
        return "multi_output"


def _execute_graph(root: DAGNode, input_args, input_kwargs) -> Dict[DAGNode, Any]:
    """Bottom-up execution with memoization (shared nodes run once)."""
    results: Dict[DAGNode, Any] = {}

    def resolve(node: DAGNode):
        if node in results:
            return results[node]
        if isinstance(node, InputNode):
            value = input_args[0] if input_args else input_kwargs
            results[node] = value
            return value
        args = [
            resolve(a) if isinstance(a, DAGNode) else a
            for a in node._bound_args
        ]
        kwargs = {
            k: resolve(v) if isinstance(v, DAGNode) else v
            for k, v in node._bound_kwargs.items()
        }
        if isinstance(node, MultiOutputNode):
            results[node] = list(args)
            return results[node]
        value = node._submit(args, kwargs)
        results[node] = value
        return value

    resolve(root)
    return results


def _fn_bind(self: RemoteFunction, *args, **kwargs) -> FunctionNode:
    return FunctionNode(self, args, kwargs)


def _cls_bind(self: ActorClass, *args, **kwargs) -> ClassNode:
    return ClassNode(self, args, kwargs)


# Install .bind on the decorator outputs (reference: @ray.remote objects
# expose .bind for DAG authoring).
RemoteFunction.bind = _fn_bind
ActorClass.bind = _cls_bind

__all__ = [
    "DAGNode",
    "FunctionNode",
    "ClassNode",
    "ClassMethodNode",
    "InputNode",
    "MultiOutputNode",
]
