"""State observability API: list tasks/actors/objects, summaries, timeline,
per-worker logs, stack dumps/profiles, and live worker telemetry.

Reference parity: ``python/ray/experimental/state/api.py:729,952,1269``
(``ray list tasks/actors/objects``, ``ray summary``), the Chrome-trace
timeline dump of ``ray timeline`` (``_private/state.py:414-431``), plus
the log/stack surface of ``ray logs`` / ``ray stack`` (the reference's
log_monitor + py-spy reporter agent; here ``util/stack_sampler``).
"""

from __future__ import annotations

import json
from collections import Counter
from typing import List, Optional

from ray_tpu._private import worker as _worker


def nodes() -> List[dict]:
    """Cluster node table with lifecycle state. Each record carries
    ``State`` (``ALIVE`` -> ``DRAINING`` -> ``DEAD``) plus ``DrainReason``
    / ``DeathCause`` so planned departures (autoscaler scale-down, spot
    preemption) are distinguishable from crashes."""
    backend = _worker.backend()
    if hasattr(backend, "nodes"):
        return backend.nodes()
    return []


def list_tasks(limit: int = 1000) -> List[dict]:
    backend = _worker.backend()
    if hasattr(backend, "list_tasks"):
        return backend.list_tasks(limit)
    return []


def list_actors() -> List[dict]:
    backend = _worker.backend()
    if hasattr(backend, "list_actors"):
        return backend.list_actors()
    return []


def list_objects(limit: int = 1000) -> List[dict]:
    backend = _worker.backend()
    if hasattr(backend, "list_objects"):
        return backend.list_objects(limit)
    return []


def list_logs() -> List[dict]:
    """Captured per-worker log files across the cluster (``ray logs``)."""
    backend = _worker.backend()
    if hasattr(backend, "list_logs"):
        return backend.list_logs()
    return []


def get_log(worker_id: str, stream: str = "out", tail_lines: int = 200,
            offset: Optional[int] = None, node_id: Optional[str] = None):
    """A worker's captured stdout/stderr.

    Default: the last ``tail_lines`` lines as a string. With ``offset``
    set (an integer byte position; pass 0 to start), returns the raw
    ``{"data", "offset", "size"}`` record so callers can poll-follow.
    """
    backend = _worker.backend()
    if not hasattr(backend, "get_log"):
        raise ValueError("this backend captures no per-worker logs")
    if offset is not None:
        return backend.get_log(worker_id, stream, offset=offset,
                               node_id=node_id)
    rec = backend.get_log(worker_id, stream, tail_lines=tail_lines,
                          node_id=node_id)
    return rec["data"]


def follow_log(worker_id: str, stream: str = "out", offset: int = 0,
               idle_timeout_s: float = 10.0,
               node_id: Optional[str] = None):
    """Iterator of ``{"offset", "data"}`` chunks, streamed over the RPC
    plane while the worker's log grows (``ray logs --follow``)."""
    backend = _worker.backend()
    if not hasattr(backend, "follow_log"):
        raise ValueError("this backend captures no per-worker logs")
    return backend.follow_log(worker_id, stream, offset, idle_timeout_s,
                              node_id)


def dump_stack(worker_id: Optional[str] = None,
               node_id: Optional[str] = None) -> str:
    """Instantaneous all-thread stack report of a worker (``ray stack``).
    On the local backend, dumps this process."""
    backend = _worker.backend()
    if not hasattr(backend, "dump_worker_stack"):
        raise ValueError("this backend supports no stack dumps")
    return backend.dump_worker_stack(worker_id, node_id=node_id)


def profile_worker(worker_id: Optional[str] = None,
                   duration_s: float = 1.0, interval_s: float = 0.01,
                   fmt: str = "raw", node_id: Optional[str] = None):
    """Time-sampled stack profile of a worker (py-spy record analog).

    ``fmt``: ``raw`` (plain-data profile dict), ``text`` (aggregated
    report), ``collapsed`` (flame-graph input), or ``chrome``
    (trace-event list mergeable with ``state.timeline()`` output).
    """
    backend = _worker.backend()
    if not hasattr(backend, "profile_worker"):
        raise ValueError("this backend supports no stack profiling")
    prof = backend.profile_worker(worker_id, duration_s, interval_s,
                                  node_id=node_id)
    from ray_tpu.util import stack_sampler

    if fmt == "raw":
        return prof
    if fmt == "text":
        return stack_sampler.text_report(prof)
    if fmt == "collapsed":
        return stack_sampler.collapsed(prof)
    if fmt == "chrome":
        return stack_sampler.chrome_trace(prof)
    raise ValueError(
        f"fmt must be raw|text|collapsed|chrome, got {fmt!r}")


def worker_stats(fresh: bool = False) -> List[dict]:
    """Live per-worker CPU/RSS/uptime telemetry across the cluster."""
    backend = _worker.backend()
    if hasattr(backend, "worker_stats"):
        return backend.worker_stats(fresh)
    return []


def summarize_tasks() -> dict:
    """Counts by (name, state) — `ray summary tasks` analog."""
    by_name: dict = {}
    for rec in list_tasks(limit=100_000):
        entry = by_name.setdefault(
            rec["name"], {"type": rec["type"], "states": Counter()}
        )
        entry["states"][rec["state"]] += 1
    return {
        name: {"type": e["type"], "states": dict(e["states"])}
        for name, e in by_name.items()
    }


def summarize_actors() -> dict:
    states = Counter()
    by_class: dict = {}
    for rec in list_actors():
        states[rec["state"]] += 1
        by_class.setdefault(rec["class_name"], Counter())[rec["state"]] += 1
    return {
        "total": dict(states),
        "by_class": {k: dict(v) for k, v in by_class.items()},
    }


def timeline(filename: Optional[str] = None) -> "list | str":
    """Chrome trace (``chrome://tracing`` / Perfetto) of task execution.

    Returns the event list, or writes JSON to ``filename`` if given.
    """
    events = []
    for rec in list_tasks(limit=100_000):
        if rec["start_time"] is None:
            continue
        end = rec["end_time"] or rec["start_time"]
        events.append({
            "name": rec["name"],
            "cat": rec["type"],
            "ph": "X",
            "ts": rec["start_time"] * 1e6,
            "dur": max(1.0, (end - rec["start_time"]) * 1e6),
            "pid": "ray_tpu",
            "tid": rec["task_id"][:8],
            "args": {"state": rec["state"]},
        })
    if filename is not None:
        with open(filename, "w") as f:
            json.dump(events, f)
        return filename
    return events
