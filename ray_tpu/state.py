"""State observability API: list tasks/actors/objects, summaries, timeline.

Reference parity: ``python/ray/experimental/state/api.py:729,952,1269``
(``ray list tasks/actors/objects``, ``ray summary``) and the Chrome-trace
timeline dump of ``ray timeline`` (``_private/state.py:414-431``).
"""

from __future__ import annotations

import json
from collections import Counter
from typing import List, Optional

from ray_tpu._private import worker as _worker


def list_tasks(limit: int = 1000) -> List[dict]:
    backend = _worker.backend()
    if hasattr(backend, "list_tasks"):
        return backend.list_tasks(limit)
    return []


def list_actors() -> List[dict]:
    backend = _worker.backend()
    if hasattr(backend, "list_actors"):
        return backend.list_actors()
    return []


def list_objects(limit: int = 1000) -> List[dict]:
    backend = _worker.backend()
    if hasattr(backend, "list_objects"):
        return backend.list_objects(limit)
    return []


def summarize_tasks() -> dict:
    """Counts by (name, state) — `ray summary tasks` analog."""
    by_name: dict = {}
    for rec in list_tasks(limit=100_000):
        entry = by_name.setdefault(
            rec["name"], {"type": rec["type"], "states": Counter()}
        )
        entry["states"][rec["state"]] += 1
    return {
        name: {"type": e["type"], "states": dict(e["states"])}
        for name, e in by_name.items()
    }


def summarize_actors() -> dict:
    states = Counter()
    by_class: dict = {}
    for rec in list_actors():
        states[rec["state"]] += 1
        by_class.setdefault(rec["class_name"], Counter())[rec["state"]] += 1
    return {
        "total": dict(states),
        "by_class": {k: dict(v) for k, v in by_class.items()},
    }


def timeline(filename: Optional[str] = None) -> "list | str":
    """Chrome trace (``chrome://tracing`` / Perfetto) of task execution.

    Returns the event list, or writes JSON to ``filename`` if given.
    """
    events = []
    for rec in list_tasks(limit=100_000):
        if rec["start_time"] is None:
            continue
        end = rec["end_time"] or rec["start_time"]
        events.append({
            "name": rec["name"],
            "cat": rec["type"],
            "ph": "X",
            "ts": rec["start_time"] * 1e6,
            "dur": max(1.0, (end - rec["start_time"]) * 1e6),
            "pid": "ray_tpu",
            "tid": rec["task_id"][:8],
            "args": {"state": rec["state"]},
        })
    if filename is not None:
        with open(filename, "w") as f:
            json.dump(events, f)
        return filename
    return events
