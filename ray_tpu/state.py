"""State observability API: list tasks/actors/objects, summaries, timeline,
per-worker logs, stack dumps/profiles, and live worker telemetry.

Reference parity: ``python/ray/experimental/state/api.py:729,952,1269``
(``ray list tasks/actors/objects``, ``ray summary``), the Chrome-trace
timeline dump of ``ray timeline`` (``_private/state.py:414-431``), plus
the log/stack surface of ``ray logs`` / ``ray stack`` (the reference's
log_monitor + py-spy reporter agent; here ``util/stack_sampler``).
"""

from __future__ import annotations

import json
from collections import Counter
from typing import List, Optional

from ray_tpu._private import worker as _worker


def nodes() -> List[dict]:
    """Cluster node table with lifecycle state. Each record carries
    ``State`` (``ALIVE`` -> ``DRAINING`` -> ``DEAD``) plus ``DrainReason``
    / ``DeathCause`` so planned departures (autoscaler scale-down, spot
    preemption) are distinguishable from crashes."""
    backend = _worker.backend()
    if hasattr(backend, "nodes"):
        return backend.nodes()
    return []


def placement_groups(pg_id: Optional[str] = None):
    """Placement-group table with lifecycle state. Each record carries
    ``state`` (``PENDING`` -> ``CREATED``, ``RESCHEDULING`` while the
    head migrates bundles off a dead/draining node, ``INFEASIBLE`` /
    ``REMOVED``), the ``bundle_nodes`` bundle->node map,
    ``live_bundles`` (indices whose node is alive and schedulable —
    what an elastic gang can run on right now), and ``reschedules``
    (completed bundle migrations). Pass ``pg_id`` for one record."""
    backend = _worker.backend()
    if not hasattr(backend, "placement_group_table"):
        return None if pg_id is not None else {}
    return backend.placement_group_table(pg_id)


def list_tasks(limit: int = 1000) -> List[dict]:
    backend = _worker.backend()
    if hasattr(backend, "list_tasks"):
        return backend.list_tasks(limit)
    return []


def list_actors() -> List[dict]:
    backend = _worker.backend()
    if hasattr(backend, "list_actors"):
        return backend.list_actors()
    return []


class ObjectList(list):
    """``list_objects`` result: a plain list of object records (size-
    descending) that also reports clipping — ``truncated`` is True when
    ``limit`` cut the listing and ``total`` is the unclipped count, so
    a capped listing is never mistaken for the whole cluster."""

    truncated: bool = False
    total: int = 0


def list_objects(limit: int = 1000) -> "ObjectList":
    """Cluster objects sorted by size DESCENDING (the limit keeps the
    largest, applied after the sort), enriched with the put-time
    attribution: owner worker id, creating task, callsite (when
    ``RAY_TPU_RECORD_CALLSITE`` is on), node replicas, and age."""
    backend = _worker.backend()
    out = ObjectList()
    if not hasattr(backend, "list_objects"):
        return out
    got = backend.list_objects(limit)
    if isinstance(got, dict):
        out.extend(got.get("objects") or [])
        out.truncated = bool(got.get("truncated"))
        out.total = int(got.get("total", len(out)))
    else:  # legacy backend shape: a bare list
        out.extend(got)
        out.total = len(out)
    return out


def memory_summary(top_k: int = 20, group_by: str = "callsite") -> dict:
    """Cluster-wide object/memory rollup (``ray memory`` analog):
    per-node shm occupancy + cluster totals, the top-K resident objects,
    and live bytes grouped by creation ``callsite`` / ``task`` /
    ``node`` / ``owner`` — the first stop when a TPU host's object store
    fills up (see also :func:`memory_leaks`)."""
    backend = _worker.backend()
    if not hasattr(backend, "memory_summary"):
        raise ValueError("this backend exposes no memory summary")
    return backend.memory_summary(top_k, group_by)


def memory_leaks() -> List[dict]:
    """Objects the head's leak sweeper currently flags: alive past
    ``RAY_TPU_LEAK_AGE_THRESHOLD_S`` with zero reachable refs (an owner
    died before registering its hold — a pinned, immortal shm copy) or
    held refs whose every replica is gone. Each record carries the
    creation attribution so the report says *what* leaked."""
    backend = _worker.backend()
    if hasattr(backend, "memory_leaks"):
        return backend.memory_leaks()
    return []


def object_store_stats(node_id: Optional[str] = None,
                       include_objects: bool = True) -> List[dict]:
    """Per-node object-store reports: shm ``stats()`` plus (optionally)
    the per-key size/refcount/pinned/attribution join and the node's
    OOM-report index."""
    backend = _worker.backend()
    if hasattr(backend, "object_store_stats"):
        return backend.object_store_stats(node_id, include_objects)
    return []


def list_logs() -> List[dict]:
    """Captured per-worker log files across the cluster (``ray logs``)."""
    backend = _worker.backend()
    if hasattr(backend, "list_logs"):
        return backend.list_logs()
    return []


def get_log(worker_id: str, stream: str = "out", tail_lines: int = 200,
            offset: Optional[int] = None, node_id: Optional[str] = None):
    """A worker's captured stdout/stderr.

    Default: the last ``tail_lines`` lines as a string. With ``offset``
    set (an integer byte position; pass 0 to start), returns the raw
    ``{"data", "offset", "size"}`` record so callers can poll-follow.
    """
    backend = _worker.backend()
    if not hasattr(backend, "get_log"):
        raise ValueError("this backend captures no per-worker logs")
    if offset is not None:
        return backend.get_log(worker_id, stream, offset=offset,
                               node_id=node_id)
    rec = backend.get_log(worker_id, stream, tail_lines=tail_lines,
                          node_id=node_id)
    return rec["data"]


def follow_log(worker_id: str, stream: str = "out", offset: int = 0,
               idle_timeout_s: float = 10.0,
               node_id: Optional[str] = None):
    """Iterator of ``{"offset", "data"}`` chunks, streamed over the RPC
    plane while the worker's log grows (``ray logs --follow``)."""
    backend = _worker.backend()
    if not hasattr(backend, "follow_log"):
        raise ValueError("this backend captures no per-worker logs")
    return backend.follow_log(worker_id, stream, offset, idle_timeout_s,
                              node_id)


def dump_stack(worker_id: Optional[str] = None,
               node_id: Optional[str] = None) -> str:
    """Instantaneous all-thread stack report of a worker (``ray stack``).
    On the local backend, dumps this process."""
    backend = _worker.backend()
    if not hasattr(backend, "dump_worker_stack"):
        raise ValueError("this backend supports no stack dumps")
    return backend.dump_worker_stack(worker_id, node_id=node_id)


def profile_worker(worker_id: Optional[str] = None,
                   duration_s: float = 1.0, interval_s: float = 0.01,
                   fmt: str = "raw", node_id: Optional[str] = None):
    """Time-sampled stack profile of a worker (py-spy record analog).

    ``fmt``: ``raw`` (plain-data profile dict), ``text`` (aggregated
    report), ``collapsed`` (flame-graph input), or ``chrome``
    (trace-event list mergeable with ``state.timeline()`` output).
    """
    backend = _worker.backend()
    if not hasattr(backend, "profile_worker"):
        raise ValueError("this backend supports no stack profiling")
    prof = backend.profile_worker(worker_id, duration_s, interval_s,
                                  node_id=node_id)
    from ray_tpu.util import stack_sampler

    if fmt == "raw":
        return prof
    if fmt == "text":
        return stack_sampler.text_report(prof)
    if fmt == "collapsed":
        return stack_sampler.collapsed(prof)
    if fmt == "chrome":
        return stack_sampler.chrome_trace(prof)
    raise ValueError(
        f"fmt must be raw|text|collapsed|chrome, got {fmt!r}")


def worker_stats(fresh: bool = False) -> List[dict]:
    """Live per-worker CPU/RSS/uptime telemetry across the cluster."""
    backend = _worker.backend()
    if hasattr(backend, "worker_stats"):
        return backend.worker_stats(fresh)
    return []


def device_stats(fresh: bool = False) -> List[dict]:
    """JAX/XLA device telemetry across the cluster: one snapshot per
    worker process that has jax loaded (per-device HBM bytes in use /
    peak / limit where the backend reports them, plus compile-cache
    counters). Stubs (``available: False``) where jax never loaded."""
    backend = _worker.backend()
    if hasattr(backend, "device_stats"):
        return backend.device_stats(fresh)
    return []


def data_stats() -> dict:
    """Input-pipeline rollup from the training goodput plane: per-stage
    wall time and per-block duration/rows/bytes distributions,
    consumer-loop wait vs user time, prefetch-buffer occupancy, and the
    derived **stall fraction** (the fraction of consumer loop wall time
    spent starved for data — check it before blaming kernels). Reads
    the federated metrics plane merged with this process's registry, so
    driver-side dataset work and in-worker (training) ingest both
    show."""
    from ray_tpu.util import goodput

    return goodput.data_stats()


def train_stats() -> dict:
    """Per-trial training goodput rollup: report counts, per-step phase
    histograms (data_wait / step / report / checkpoint_save /
    checkpoint_restore), per-rank step time with straggler skew, and
    the downtime ledger's cause attribution yielding a goodput %."""
    from ray_tpu.util import goodput

    return goodput.train_stats()


def query_metrics(spec: dict) -> dict:
    """Windowed query against the head's metrics history ring (the
    signal plane): ``{"op": "rate"|"delta"|"gauge_avg"|"gauge_max"|
    "gauge_last"|"trend"|"quantile"|"series_delta", "name": family,
    "window_s": s, "q"?, "match"?, "group_by"?}``. Answers
    ``{"ok": False, "error": ...}`` off-cluster or with the plane
    disabled — never raises for a cold ring."""
    backend = _worker.backend()
    if hasattr(backend, "query_metrics"):
        return backend.query_metrics(spec)
    return {"ok": False, "error": "no cluster backend"}


def slo_status() -> dict:
    """Every registered SLO's burn-rate state (ok/warning/burning),
    last evaluated value, threshold, and streaks — plus the ring's
    series count and eviction ledger."""
    backend = _worker.backend()
    if hasattr(backend, "slo_status"):
        return backend.slo_status()
    return {"ok": False, "error": "no cluster backend"}


def register_slo(name: str, expr: str) -> dict:
    """Register a declarative SLO evaluated by the head's burn-rate
    loop, e.g. ``ttft_p50{deployment="d"} < 2s over 60s`` or
    ``shed_ratio < 1% over 300s``. Transitions to/from burning publish
    events on the pubsub ``SLO`` channel."""
    backend = _worker.backend()
    if hasattr(backend, "register_slo"):
        return backend.register_slo(name, expr)
    return {"ok": False, "error": "no cluster backend"}


def remove_slo(name: str) -> dict:
    backend = _worker.backend()
    if hasattr(backend, "remove_slo"):
        return backend.remove_slo(name)
    return {"ok": False, "error": "no cluster backend"}


def signal_top(window_s: float = 60.0) -> dict:
    """The ``ray-tpu top`` rollup: per-node CPU/RSS/store occupancy,
    per-deployment QPS/TTFT/shed, per-trial goodput — every number a
    history-ring query, zero sleeps in the path."""
    backend = _worker.backend()
    if hasattr(backend, "signal_top"):
        return backend.signal_top(window_s)
    return {"ok": False, "error": "no cluster backend"}


def get_trace(trace_id: str) -> Optional[dict]:
    """One assembled trace from the flight recorder: clock-aligned
    spans, critical-path segments, and the TTFT decomposition. ``None``
    when the id is unknown (never reported, still assembling inside the
    quiet window, or tail-sampled out — only errored/slow/sampled-in
    traces are kept)."""
    backend = _worker.backend()
    if hasattr(backend, "get_trace"):
        return backend.get_trace(trace_id)
    return None


def list_traces(limit: int = 50) -> List[dict]:
    """Kept-trace summaries, newest first: ``{trace_id, root,
    duration_s, ts, kept_because, deployment, errored, spans,
    dominant}``."""
    backend = _worker.backend()
    if hasattr(backend, "list_traces"):
        return backend.list_traces(limit)
    return []


def trace_stats() -> dict:
    """Flight-recorder health: pending/kept counts, drop ledger by
    cause (sampled/evicted/span_cap), and per-node clock offsets."""
    backend = _worker.backend()
    if hasattr(backend, "trace_stats"):
        return backend.trace_stats()
    return {}


def ttft_decomposition(window_s: Optional[float] = None,
                       deployment: Optional[str] = None) -> dict:
    """Windowed per-phase TTFT decomposition (p50/p99/mean by named
    phase — queue/prefill/route/...) over every finalized trace,
    computed BEFORE tail sampling so the percentiles are unbiased.
    ``phase_sum_p50_s`` vs ``ttft_p50_s`` is the partition check."""
    backend = _worker.backend()
    if hasattr(backend, "ttft_decomposition"):
        return backend.ttft_decomposition(window_s=window_s,
                                          deployment=deployment)
    return {"traces": 0, "phases": {}}


def autoscaler_status() -> dict:
    """The fleet autoscaler's last state report: per-node-type counts
    and spot markers, quarantine/backoff benches, nodes draining for
    scale-down, and active SLO burns. ``{}`` until the autoscaler's
    first reconcile pass (or on the local backend)."""
    backend = _worker.backend()
    if hasattr(backend, "autoscaler_status"):
        return backend.autoscaler_status()
    return {}


def set_failpoints(specs: dict, include_workers: bool = True) -> dict:
    """Arm/disarm deterministic failpoints cluster-wide: ``{site: spec}``
    where spec is ``action[:arg][,selector...]`` (see
    ``ray_tpu.util.failpoints``; a falsy spec disarms the site). On a
    cluster backend the specs fan out head -> agents -> live workers;
    on the local backend they arm this process directly."""
    backend = _worker.backend()
    if hasattr(backend, "set_failpoints"):
        return backend.set_failpoints(specs, include_workers)
    from ray_tpu.util import failpoints as _fp

    return {"local": _fp.set_failpoints(specs)}


def list_failpoints() -> dict:
    """Armed failpoints per cluster process (head, agents, workers)."""
    backend = _worker.backend()
    if hasattr(backend, "list_failpoints"):
        return backend.list_failpoints()
    from ray_tpu.util import failpoints as _fp

    return {"local": _fp.list_armed()}


def set_channel_chaos(rules: list, label: str = "") -> dict:
    """Arm network-chaos rules on the RPC plane: the head, every alive
    agent, and (best-effort) each agent's live workers — workers tag
    their clients with their node's identity, so node-keyed partition
    rules cut worker-originated traffic too. The calling driver's own
    process arms via ``Cluster.partition``/``rpc.channel_chaos``
    directly. Rule dicts: action=delay|drop|duplicate|sever, src/dst
    address lists, method, arg, prob, times. Faults surface as
    ``ConnectionLost``, never silent corruption."""
    backend = _worker.backend()
    if hasattr(backend, "set_channel_chaos"):
        return backend.set_channel_chaos(rules, label)
    raise ValueError("network chaos requires a cluster backend")


def clear_channel_chaos(label: Optional[str] = None) -> dict:
    backend = _worker.backend()
    if hasattr(backend, "clear_channel_chaos"):
        return backend.clear_channel_chaos(label)
    raise ValueError("network chaos requires a cluster backend")


def capture_profile(worker_id: Optional[str] = None,
                    duration_s: float = 1.0, interval_s: float = 0.01,
                    out_dir: Optional[str] = None,
                    node_id: Optional[str] = None) -> dict:
    """Remote profiler capture (``ray-tpu tprof``): open a timed
    ``jax.profiler.trace()`` window in the target worker — XLA host +
    device activity in a TensorBoard-loadable trace directory — falling
    back to the stack sampler where ``jax.profiler`` is unavailable.
    Trace files stream back over the RPC plane; returns
    ``{kind, dir, files, ...}`` with the local paths written."""
    backend = _worker.backend()
    if not hasattr(backend, "capture_profile"):
        raise ValueError("this backend supports no profiler capture")
    return backend.capture_profile(
        worker_id, duration_s, interval_s, out_dir=out_dir,
        node_id=node_id)


def summarize_tasks() -> dict:
    """Counts by (name, state) — `ray summary tasks` analog — plus the
    per-phase latency distribution (``phases``: p50/p99/mean ms per
    get_args/execute/put_outputs) from the workers' phase breakdown."""
    by_name: dict = {}
    samples: dict = {}
    for rec in list_tasks(limit=100_000):
        entry = by_name.setdefault(
            rec["name"], {"type": rec["type"], "states": Counter()}
        )
        entry["states"][rec["state"]] += 1
        for phase, ns in (rec.get("phases") or {}).items():
            samples.setdefault(rec["name"], {}).setdefault(
                phase, []).append(ns / 1e6)
    from ray_tpu.util.metrics import latency_dist_ms

    out = {}
    for name, e in by_name.items():
        summary = {"type": e["type"], "states": dict(e["states"])}
        phases = {
            phase: latency_dist_ms(vals)
            for phase, vals in samples.get(name, {}).items()
        }
        if phases:
            summary["phases"] = phases
        out[name] = summary
    return out


def summarize_actors() -> dict:
    states = Counter()
    by_class: dict = {}
    for rec in list_actors():
        states[rec["state"]] += 1
        by_class.setdefault(rec["class_name"], Counter())[rec["state"]] += 1
    return {
        "total": dict(states),
        "by_class": {k: dict(v) for k, v in by_class.items()},
    }


# Phase slices nest in the order the worker records them.
_PHASE_ORDER = ("get_args", "execute", "put_outputs")


def timeline(filename: Optional[str] = None,
             include_spans: bool = True) -> "list | str":
    """Chrome trace (``chrome://tracing`` / Perfetto) of task execution.

    Each task slice carries nested per-phase child slices
    (``phase:get_args`` / ``phase:execute`` / ``phase:put_outputs``)
    on its track, and — when tracing is enabled — the distributed
    ``util/tracing`` spans are merged into the SAME trace, so one file
    follows a request submit → schedule → phase slices end to end.

    Returns the event list, or writes JSON to ``filename`` if given.
    """
    events = []
    for rec in list_tasks(limit=100_000):
        if rec["start_time"] is None:
            continue
        end = rec["end_time"] or rec["start_time"]
        tid = rec["task_id"][:8]
        events.append({
            "name": rec["name"],
            "cat": rec["type"],
            "ph": "X",
            "ts": rec["start_time"] * 1e6,
            "dur": max(1.0, (end - rec["start_time"]) * 1e6),
            "pid": "ray_tpu",
            "tid": tid,
            "args": {"state": rec["state"]},
        })
        # Nested phase slices: contiguous children from the task's
        # start, in recording order (Perfetto nests same-track slices
        # by time containment). In-flight tasks are skipped: their
        # parent slice is a 1µs stub while phases already carry real
        # durations, which would render children outside the parent.
        if rec["end_time"] is None:
            continue
        ts = rec["start_time"] * 1e6
        phases = rec.get("phases") or {}
        for phase in _PHASE_ORDER:
            ns = phases.get(phase)
            if ns is None:
                continue
            dur = max(0.1, ns / 1e3)
            events.append({
                "name": f"phase:{phase}",
                "cat": "phase",
                "ph": "X",
                "ts": ts,
                "dur": dur,
                "pid": "ray_tpu",
                "tid": tid,
                "args": {"task": rec["name"]},
            })
            ts += dur
    if include_spans:
        try:
            from ray_tpu.util import tracing as _tracing

            # Backend spans (cluster: the head's store, fed by worker
            # event batches) PLUS this process's own buffer — driver
            # submit spans never leave the driver, and without them the
            # submit → schedule → phase-slices chain has no head.
            # Dedup by span_id: on the local backend both sources are
            # the same buffer.
            spans = {}
            backend = _worker.backend()
            if hasattr(backend, "list_spans"):
                for s in backend.list_spans():
                    spans[s["span_id"]] = s
            for s in _tracing.collect():
                spans.setdefault(s["span_id"], s)
            events.extend(_tracing.chrome_events(list(spans.values())))
        except Exception:
            pass  # spans are an overlay; the task trace stands alone
    if filename is not None:
        with open(filename, "w") as f:
            json.dump(events, f)
        return filename
    return events
