"""Cluster launcher: YAML config -> running cluster (``ray up``/``down``).

Reference parity: ``python/ray/autoscaler`` commands + ``ray-schema.json``
— a YAML file declares the cluster (provider, node types with resources
and min/max workers, head node type); ``create_or_update_cluster`` brings
it up and attaches a ``StandardAutoscaler``; ``teardown_cluster`` tears
it down. Cloud providers plug in through ``register_node_provider`` (the
reference's aws/gcp/azure modules resolve the same way); the built-in
``"local"`` provider launches real head/agent processes on this machine
(fake_multi_node parity), which is also the TPU-pod dev story: one agent
per host shape.

    cluster_name: demo
    max_workers: 4
    provider: {type: local}
    head_node_type: head
    available_node_types:
      head:    {num_cpus: 4, min_workers: 0}
      worker:  {num_cpus: 2, resources: {TPU: 4}, min_workers: 1,
               max_workers: 3}
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from ray_tpu.autoscaler import LocalNodeProvider, NodeProvider, StandardAutoscaler

_PROVIDERS: Dict[str, Callable[..., NodeProvider]] = {}


def register_node_provider(type_name: str, factory) -> None:
    """Plugin registry (reference ``_get_node_provider`` import table)."""
    _PROVIDERS[type_name] = factory


def _provider_for(config: dict, cluster) -> NodeProvider:
    ptype = (config.get("provider") or {}).get("type", "local")
    if ptype == "local":
        return LocalNodeProvider(cluster)
    if ptype == "tpu_pod" and ptype not in _PROVIDERS:
        import ray_tpu.autoscaler.tpu_pod  # noqa: F401 — self-registers
    factory = _PROVIDERS.get(ptype)
    if factory is None:
        raise ValueError(
            f"unknown provider type {ptype!r}; registered: "
            f"{sorted(_PROVIDERS) + ['local']}"
        )
    return factory(config["provider"], cluster)


def load_cluster_config(path_or_dict) -> dict:
    if isinstance(path_or_dict, dict):
        config = dict(path_or_dict)
    else:
        import yaml

        with open(path_or_dict) as f:
            config = yaml.safe_load(f)
    config.setdefault("cluster_name", "default")
    config.setdefault("max_workers", 8)
    types = config.get("available_node_types")
    if not types:
        raise ValueError("config needs available_node_types")
    head_type = config.get("head_node_type")
    if head_type not in types:
        raise ValueError(f"head_node_type {head_type!r} not in "
                         f"available_node_types {sorted(types)}")
    return config


class ClusterHandle:
    """What ``create_or_update_cluster`` returns: address + teardown."""

    def __init__(self, config: dict, cluster, provider, autoscaler):
        self.config = config
        self.cluster = cluster
        self.provider = provider
        self.autoscaler = autoscaler

    @property
    def address(self) -> str:
        return self.cluster.address

    def teardown(self) -> None:
        if self.autoscaler is not None:
            self.autoscaler.stop()
        self.cluster.shutdown()


def create_or_update_cluster(
    config_path_or_dict,
    *,
    start_autoscaler: bool = True,
) -> ClusterHandle:
    """``ray up`` analog: start the head (head node type's shape), launch
    every node type's ``min_workers``, attach the autoscaler for demand
    beyond that."""
    from ray_tpu.cluster.cluster_utils import Cluster

    config = load_cluster_config(config_path_or_dict)
    types = config["available_node_types"]
    head_cfg = types[config["head_node_type"]]
    cluster = Cluster()
    cluster.add_node(
        num_cpus=head_cfg.get("num_cpus"),
        resources=head_cfg.get("resources"),
    )
    provider = _provider_for(config, cluster)
    for type_name, tcfg in types.items():
        extra = int(tcfg.get("min_workers", 0) or 0)
        for _ in range(extra):
            provider.create_node(type_name, tcfg)
    cluster.wait_for_nodes()

    autoscaler = None
    if start_autoscaler:
        def shape(tcfg):
            # Everything but min_workers flows through: cloud providers
            # read extra keys (accelerator_type, spot), and the
            # autoscaler reads max_workers as the per-type cap and spot
            # as the preemptible marker for its bin-packer.
            return {k: v for k, v in tcfg.items()
                    if k not in ("min_workers",)}

        node_types = {
            name: shape(tcfg)
            for name, tcfg in types.items()
            if name != config["head_node_type"]
        } or {config["head_node_type"]: shape(head_cfg)}
        autoscaler = StandardAutoscaler(
            cluster.address, provider,
            node_types=node_types,
            max_workers=int(config["max_workers"]),
            idle_timeout_s=float(config.get("idle_timeout_minutes", 1)) * 60,
        )
        autoscaler.start()  # spawns its own reconcile-loop daemon thread
    return ClusterHandle(config, cluster, provider, autoscaler)


def teardown_cluster(handle: ClusterHandle) -> None:
    """``ray down`` analog."""
    handle.teardown()
