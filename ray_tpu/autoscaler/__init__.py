"""Autoscaler: demand-driven node provisioning.

Reference parity: ``python/ray/autoscaler`` (SURVEY.md §2.2) —
``StandardAutoscaler.update`` reconciles resource demand against running
nodes (``_private/autoscaler.py:167``), a ``ResourceDemandScheduler``
bin-packs pending demands over node types
(``_private/resource_demand_scheduler.py:103``), and ``NodeProvider``
plugins do the actual provisioning (local/fake providers for tests,
``fake_multi_node/node_provider.py``). The TPU deployment target is pods:
a node type maps to a TPU host shape (e.g. ``{"CPU": 8, "TPU": 4}``).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ray_tpu.cluster.rpc import RpcClient


class NodeProvider:
    """Plugin interface (``autoscaler/node_provider.py``)."""

    def create_node(self, node_type: str, node_config: dict) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Provisions simulated nodes in a ``cluster_utils.Cluster``
    (FakeMultiNodeProvider parity: scaling without a cloud)."""

    def __init__(self, cluster):
        self.cluster = cluster
        self._agents: Dict[str, object] = {}

    def create_node(self, node_type: str, node_config: dict) -> str:
        agent = self.cluster.add_node(
            num_cpus=node_config.get("num_cpus"),
            resources=node_config.get("resources"),
        )
        self._agents[agent.node_id] = agent
        return agent.node_id

    def terminate_node(self, node_id: str) -> None:
        agent = self._agents.pop(node_id, None)
        if agent is not None:
            self.cluster.remove_node(agent)

    def non_terminated_nodes(self) -> List[str]:
        return [
            nid for nid, agent in self._agents.items()
            if not agent._shutdown.is_set()
        ]


class StandardAutoscaler:
    """One reconcile step per ``update()``; ``start()`` loops it."""

    def __init__(
        self,
        head_address: str,
        provider: NodeProvider,
        *,
        node_types: Dict[str, dict],
        max_workers: int = 8,
        idle_timeout_s: float = 60.0,
        launch_cooldown_s: float = 2.0,
        drain_deadline_s: float | None = None,
    ):
        from ray_tpu.core.config import config

        self.head = RpcClient(head_address)
        self.provider = provider
        self.node_types = node_types
        self.max_workers = max_workers
        self.idle_timeout_s = idle_timeout_s
        self.launch_cooldown_s = launch_cooldown_s
        self.drain_deadline_s = (
            config.drain_deadline_s if drain_deadline_s is None
            else drain_deadline_s)
        # Nodes whose scale-down drain was initiated; terminated once
        # the head reports them DEAD (possibly on a later pass).
        self._draining: set = set()
        self._idle_since: Dict[str, float] = {}
        self._last_launch = 0.0
        self._stop = threading.Event()
        self.launched: List[str] = []

    # -- demand -> nodes (ResourceDemandScheduler.get_nodes_to_launch) ----

    def _nodes_to_launch(self, demands: List[dict], n_current: int) -> List[str]:
        budget = self.max_workers - n_current
        if budget <= 0 or not demands:
            return []
        # First-fit-decreasing bin-pack of demands onto new node headrooms.
        launches: List[str] = []
        headrooms: List[dict] = []
        for demand in sorted(demands, key=lambda d: -sum(d.values())):
            placed = False
            for room in headrooms:
                if all(room.get(k, 0.0) >= v for k, v in demand.items()):
                    for k, v in demand.items():
                        room[k] = room.get(k, 0.0) - v
                    placed = True
                    break
            if placed:
                continue
            if len(launches) >= budget:
                continue
            for type_name, config in self.node_types.items():
                total = {"CPU": float(config.get("num_cpus", 0) or 0)}
                total.update(config.get("resources") or {})
                if all(total.get(k, 0.0) >= v for k, v in demand.items()):
                    launches.append(type_name)
                    room = dict(total)
                    for k, v in demand.items():
                        room[k] = room.get(k, 0.0) - v
                    headrooms.append(room)
                    break
        return launches

    def update(self) -> dict:
        """One reconcile round: scale up for pending demand, scale down
        idle provider nodes past the timeout."""
        demands = self.head.call("pending_demands", 10.0)
        nodes = self.head.call("nodes")
        alive = [n for n in nodes if n["Alive"]]
        report = {"launched": [], "terminated": []}

        now = time.monotonic()
        if demands and now - self._last_launch >= self.launch_cooldown_s:
            mine = set(self.provider.non_terminated_nodes())
            for type_name in self._nodes_to_launch(demands, len(mine)):
                config = self.node_types[type_name]
                node_id = self.provider.create_node(type_name, config)
                self.launched.append(node_id)
                report["launched"].append(node_id)
                self._last_launch = now

        # Scale down: provider-owned nodes fully idle past the timeout
        # are DRAINED before the provider terminate hook — a task that
        # landed during the idle window finishes (or its actors migrate)
        # instead of being killed mid-flight, and the node is excluded
        # from new placements the moment the drain starts, so the window
        # cannot refill either. Drains are initiated asynchronously
        # (wait=False) so one busy node cannot stall the whole reconcile
        # pass; termination lands once the head reports the node DEAD.
        self._reap_drained({n["NodeID"]: n for n in nodes}, report)
        by_id = {n["NodeID"]: n for n in alive}
        started: list = []
        for node_id in list(self.provider.non_terminated_nodes()):
            if node_id in self._draining:
                continue  # drain in flight; _reap_drained settles it
            info = by_id.get(node_id)
            if info is None or info.get("State", "ALIVE") != "ALIVE":
                continue
            idle = info["Available"] == info["Resources"]
            if not idle:
                self._idle_since.pop(node_id, None)
                continue
            since = self._idle_since.setdefault(node_id, now)
            if now - since >= self.idle_timeout_s:
                try:
                    self.head.call(
                        "drain_node", node_id, "autoscaler_idle",
                        self.drain_deadline_s, False, timeout=15.0)
                    self._draining.add(node_id)
                    started.append(node_id)
                except Exception:
                    # Head hiccup: terminate ungracefully (old behavior)
                    # rather than leak the provider node.
                    self.provider.terminate_node(node_id)
                    report["terminated"].append(node_id)
                self._idle_since.pop(node_id, None)
        if started:
            # Bounded settle: an idle node drains in well under a
            # second, so give this pass a brief window to finish the
            # common case in place; busy nodes settle on a later pass.
            deadline = time.monotonic() + min(3.0, self.drain_deadline_s + 1.0)
            while started and time.monotonic() < deadline:
                time.sleep(0.05)
                try:
                    table = {n["NodeID"]: n for n in self.head.call("nodes")}
                except Exception:
                    break
                self._reap_drained(table, report)
                started = [n for n in started if n in self._draining]
        return report

    def _reap_drained(self, node_table: dict, report: dict) -> None:
        """Terminate provider nodes whose scale-down drain completed."""
        for node_id in list(self._draining):
            info = node_table.get(node_id)
            if info is not None and info["Alive"]:
                continue  # still draining
            self._draining.discard(node_id)
            self.provider.terminate_node(node_id)
            report["terminated"].append(node_id)

    def start(self, interval_s: float = 1.0) -> None:
        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.update()
                except Exception:
                    continue

        threading.Thread(target=loop, daemon=True).start()

    def stop(self) -> None:
        self._stop.set()
