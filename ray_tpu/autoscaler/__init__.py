"""Autoscaler: demand-driven node provisioning.

Reference parity: ``python/ray/autoscaler`` (SURVEY.md §2.2) —
``StandardAutoscaler.update`` reconciles resource demand against running
nodes (``_private/autoscaler.py:167``), a ``ResourceDemandScheduler``
bin-packs pending demands over node types
(``_private/resource_demand_scheduler.py:103,171``), and ``NodeProvider``
plugins do the actual provisioning (local/fake providers for tests,
``fake_multi_node/node_provider.py``). The TPU deployment target is pods:
a node type maps to a TPU host shape (e.g. ``{"CPU": 8, "TPU": 4}``).

Round 17 — the execution half is robustness-first (Podracer runs fleets
on preemptible pods; preemption and boot failure are the NORMAL case):

* **Bin-packing over real pending demand.** The head's
  ``demand_snapshot`` merges queued task demands, pending (RESTARTING)
  actors and the unplaced bundles of PENDING/RESCHEDULING placement
  groups; the packer sizes a heterogeneous node-type catalog against
  it. STRICT_SPREAD bundles need N distinct nodes, not N bundles-worth
  of one node; a ``spot: false`` gang only counts against on-demand
  types.
* **Quarantine/backoff boot loop.** Every launch runs under a
  wall-clock timeout; a failed type waits out a jittered exponential
  backoff, and N consecutive failures bench the type for a cooldown —
  demand falls through to the next feasible type, and a flapping
  provider can never hot-loop ``create_node``.
* **Zero-goodput-loss scale-down.** Idle nodes (occupancy-coldest
  first, ranked by windowed signal-ring queries) drain through the
  head's ``ALIVE -> DRAINING -> DEAD`` protocol; the provider
  terminate only fires once the head reports the node dead, and the
  head gets a ``terminate_ack`` so the ledger closes.
* **SLO-burn scale-up.** The reconcile loop subscribes to the head's
  SLO pubsub channel; a burning SLO (``ttft_p50``,
  ``queue_depth_trend``, ...) adds one node-shape of demand ahead of
  the pending-work signal.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ray_tpu.cluster.rpc import RpcClient
from ray_tpu.util import failpoints


class NodeProvider:
    """Plugin interface (``autoscaler/node_provider.py``)."""

    def create_node(self, node_type: str, node_config: dict) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Provisions simulated nodes in a ``cluster_utils.Cluster``
    (FakeMultiNodeProvider parity: scaling without a cloud)."""

    def __init__(self, cluster):
        self.cluster = cluster
        self._agents: Dict[str, object] = {}

    def create_node(self, node_type: str, node_config: dict) -> str:
        agent = self.cluster.add_node(
            num_cpus=node_config.get("num_cpus"),
            resources=node_config.get("resources"),
            labels={"node_type": node_type,
                    "spot": bool(node_config.get("spot", False))},
        )
        self._agents[agent.node_id] = agent
        return agent.node_id

    def terminate_node(self, node_id: str) -> None:
        agent = self._agents.pop(node_id, None)
        if agent is not None:
            self.cluster.remove_node(agent)

    def non_terminated_nodes(self) -> List[str]:
        return [
            nid for nid, agent in self._agents.items()
            if not agent._shutdown.is_set()
        ]


class _TypeState:
    """Per-node-type boot-loop state: consecutive failures, the backoff
    gate, and the quarantine bench."""

    __slots__ = ("failures", "next_attempt", "quarantined_until")

    def __init__(self):
        self.failures = 0
        self.next_attempt = 0.0       # monotonic; 0 = launch freely
        self.quarantined_until = 0.0  # monotonic; 0 = not benched


class StandardAutoscaler:
    """One reconcile step per ``update()``; ``start()`` loops it."""

    def __init__(
        self,
        head_address: str,
        provider: NodeProvider,
        *,
        node_types: Dict[str, dict],
        max_workers: int = 8,
        idle_timeout_s: float = 60.0,
        launch_cooldown_s: float = 2.0,
        drain_deadline_s: float | None = None,
        launch_timeout_s: float | None = None,
        backoff_base_s: float | None = None,
        backoff_max_s: float | None = None,
        quarantine_failures: int | None = None,
        quarantine_cooldown_s: float | None = None,
    ):
        from ray_tpu.core.config import config

        self.head = RpcClient(head_address)
        self.provider = provider
        self.node_types = node_types
        self.max_workers = max_workers
        self.idle_timeout_s = idle_timeout_s
        self.launch_cooldown_s = launch_cooldown_s
        self.drain_deadline_s = (
            config.drain_deadline_s if drain_deadline_s is None
            else drain_deadline_s)
        self.launch_timeout_s = (
            config.autoscaler_launch_timeout_s if launch_timeout_s is None
            else launch_timeout_s)
        self.backoff_base_s = (
            config.autoscaler_launch_backoff_base_s if backoff_base_s is None
            else backoff_base_s)
        self.backoff_max_s = (
            config.autoscaler_launch_backoff_max_s if backoff_max_s is None
            else backoff_max_s)
        self.quarantine_failures = (
            config.autoscaler_quarantine_failures
            if quarantine_failures is None else quarantine_failures)
        self.quarantine_cooldown_s = (
            config.autoscaler_quarantine_cooldown_s
            if quarantine_cooldown_s is None else quarantine_cooldown_s)
        # Nodes whose scale-down drain was initiated; terminated once
        # the head reports them DEAD (possibly on a later pass).
        # Insertion-ordered (dict-as-set): drains started first are
        # reaped (and ledger-acked) first.
        self._draining: Dict[str, None] = {}
        self._idle_since: Dict[str, float] = {}
        self._last_launch = 0.0
        self._stop = threading.Event()
        self.launched: List[str] = []
        # Boot-loop state per type + which type each provider node is.
        self._type_state: Dict[str, _TypeState] = {}
        self._node_type_of: Dict[str, str] = {}
        # SLO-burn subscription state: active burns + boosts not yet
        # absorbed by a launch.
        self._slo_sub_id = f"autoscaler-{id(self):x}"
        self._slo_subscribed = False
        self._slo_burn: Dict[str, float] = {}
        self._boosts: List[str] = []

    # -- node-type catalog -------------------------------------------------

    def _shape(self, type_name: str) -> Dict[str, float]:
        cfg = self.node_types[type_name]
        total = {"CPU": float(cfg.get("num_cpus", 0) or 0)}
        total.update(cfg.get("resources") or {})
        return total

    def _is_spot(self, type_name: str) -> bool:
        return bool(self.node_types[type_name].get("spot", False))

    def _type_cap(self, type_name: str) -> Optional[int]:
        cap = self.node_types[type_name].get("max_workers")
        return None if cap is None else int(cap)

    def _state_of(self, type_name: str) -> _TypeState:
        st = self._type_state.get(type_name)
        if st is None:
            st = self._type_state[type_name] = _TypeState()
        return st

    def _quarantined(self, type_name: str, now: float) -> bool:
        return now < self._state_of(type_name).quarantined_until

    # -- demand normalization ---------------------------------------------

    @staticmethod
    def _entry(resources: dict, kind: str, *, group: str | None = None,
               strict_spread: bool = False, spot_ok: bool = True) -> dict:
        return {"resources": dict(resources), "kind": kind, "group": group,
                "strict_spread": strict_spread, "spot_ok": spot_ok}

    def _normalize(self, demands) -> List[dict]:
        """Accepts the rich ``demand_snapshot`` dict, a legacy flat list
        of resource dicts, or an already-normalized entry list."""
        if isinstance(demands, dict):
            entries = [self._entry(d, "task")
                       for d in demands.get("tasks") or [] if d]
            entries += [self._entry(d, "actor")
                        for d in demands.get("actors") or [] if d]
            for pg in demands.get("pg_bundles") or []:
                strict = pg.get("strategy") == "STRICT_SPREAD"
                spot_ok = bool(pg.get("spot", True))
                for b in pg.get("bundles") or []:
                    entries.append(self._entry(
                        b, "pg_bundle", group=pg.get("pg_id"),
                        strict_spread=strict, spot_ok=spot_ok))
            return entries
        out = []
        for d in demands or []:
            if isinstance(d, dict) and "resources" in d and "kind" in d:
                out.append(d)
            elif d:
                out.append(self._entry(d, "task"))
        return out

    # -- demand -> nodes (ResourceDemandScheduler.get_nodes_to_launch) ----

    def _nodes_to_launch(self, demands, n_current: int,
                         per_type_current: Optional[Dict[str, int]] = None,
                         now: Optional[float] = None,
                         existing_rooms: Optional[List[dict]] = None,
                         ) -> List[str]:
        now = time.monotonic() if now is None else now
        entries = self._normalize(demands)
        budget = self.max_workers - n_current
        if budget <= 0 or not entries:
            return []
        per_type_current = dict(per_type_current or {})
        # First-fit-decreasing bin-pack of demands onto headrooms:
        # EXISTING nodes' available capacity first (reference
        # ResourceDemandScheduler — a demand miss the client just
        # hasn't retried onto freshly launched capacity yet must not
        # trigger a second launch), then new nodes. Strict-spread gang
        # bundles go first (they constrain node COUNT, not just
        # capacity).
        launches: List[str] = []
        headrooms: List[dict] = [dict(r) for r in existing_rooms or []]

        def feasible_in(room: dict, e: dict) -> bool:
            if e["strict_spread"] and e["group"] in room["groups"]:
                return False  # distinct node per STRICT_SPREAD bundle
            if not e["spot_ok"] and room.get("spot"):
                return False  # gang-critical bundle: on-demand only
            res = e["resources"]
            return all(room["resources"].get(k, 0.0) >= v
                       for k, v in res.items())

        def debit(room: dict, e: dict) -> None:
            for k, v in e["resources"].items():
                room["resources"][k] = room["resources"].get(k, 0.0) - v
            if e["group"] is not None:
                room["groups"].add(e["group"])

        ordered = sorted(entries, key=lambda e: (
            0 if e["strict_spread"] else 1,
            -sum(e["resources"].values())))
        for e in ordered:
            placed = False
            for room in headrooms:
                if feasible_in(room, e):
                    debit(room, e)
                    placed = True
                    break
            if placed:
                continue
            if len(launches) >= budget:
                continue
            for type_name in self.node_types:
                if self._quarantined(type_name, now):
                    continue  # benched: demand falls through
                if not e["spot_ok"] and self._is_spot(type_name):
                    continue
                cap = self._type_cap(type_name)
                if cap is not None:
                    planned = per_type_current.get(type_name, 0) \
                        + sum(1 for t in launches if t == type_name)
                    if planned >= cap:
                        continue
                total = self._shape(type_name)
                if all(total.get(k, 0.0) >= v
                       for k, v in e["resources"].items()):
                    launches.append(type_name)
                    room = {"resources": dict(total), "type": type_name,
                            "spot": self._is_spot(type_name),
                            "groups": set()}
                    debit(room, e)
                    headrooms.append(room)
                    break
        return launches

    # -- launch pipeline (timeout / backoff / quarantine) ------------------

    def _timed_create(self, type_name: str, cfg: dict):
        """create_node bounded by the launch timeout: the provider call
        runs in a worker thread so a wedged cloud CLI fails the LAUNCH,
        not the reconcile loop (a late success is adopted through
        non_terminated_nodes on a later pass)."""
        result: dict = {}

        def _do():
            try:
                result["node_id"] = self.provider.create_node(
                    type_name, cfg)
            except Exception as e:
                result["error"] = e

        t0 = time.perf_counter()
        worker = threading.Thread(target=_do, daemon=True)
        worker.start()
        worker.join(self.launch_timeout_s)
        if worker.is_alive():
            raise TimeoutError(
                f"create_node({type_name!r}) exceeded "
                f"{self.launch_timeout_s}s")
        if "error" in result:
            raise result["error"]
        return result["node_id"], time.perf_counter() - t0

    def _on_launch_failure(self, type_name: str, now: float) -> None:
        from ray_tpu.util import metrics

        st = self._state_of(type_name)
        st.failures += 1
        metrics.AUTOSCALER_LAUNCH_FAILURES_TOTAL.inc(
            tags={"node_type": type_name})
        if st.failures >= self.quarantine_failures:
            # Benched: no attempts for the cooldown; the first attempt
            # after it is a single probe (failures stay high, so one
            # more failure re-benches immediately).
            st.quarantined_until = now + self.quarantine_cooldown_s
            st.next_attempt = st.quarantined_until
            metrics.AUTOSCALER_QUARANTINES_TOTAL.inc(
                tags={"node_type": type_name})
            return
        # Jittered exponential backoff, capped: jitter only shrinks
        # (0.5x-1x) so the cap is a true bound on the schedule.
        backoff = min(self.backoff_max_s,
                      self.backoff_base_s * (2 ** (st.failures - 1)))
        rng = failpoints.seeded_rng(
            f"autoscaler:{type_name}:{st.failures}")
        st.next_attempt = now + backoff * (0.5 + 0.5 * rng.random())

    # -- SLO-burn scale-up -------------------------------------------------

    def _poll_slo_events(self) -> None:
        """Drain the head's SLO channel; a burning transition queues one
        node-shape of boost demand, recovery clears the burn."""
        if not self._slo_subscribed:
            self.head.call("pubsub_subscribe", self._slo_sub_id, "SLO",
                           timeout=5.0)
            self._slo_subscribed = True
        polled = self.head.call("pubsub_poll", self._slo_sub_id, 0.0,
                                200, timeout=10.0)
        if polled is None:  # head restarted: pubsub state is gone
            self._slo_subscribed = False
            return
        msgs, _dropped = polled
        for m in msgs:
            ev = m.get("message") or {}
            slo = ev.get("slo") or m.get("key")
            if not slo:
                continue
            if ev.get("state") == "burning":
                if slo not in self._slo_burn:
                    self._slo_burn[slo] = time.monotonic()
                    self._boosts.append(slo)
            else:
                self._slo_burn.pop(slo, None)
                if slo in self._boosts:
                    self._boosts.remove(slo)

    def _boost_entries(self, now: float) -> List[dict]:
        """One smallest-feasible-node-shape demand per unabsorbed burn:
        capacity ahead of the pending-work signal."""
        entries = []
        shapes = sorted(
            (t for t in self.node_types if not self._quarantined(t, now)),
            key=lambda t: sum(self._shape(t).values()))
        if not shapes:
            return entries
        shape = self._shape(shapes[0])
        for _slo in self._boosts:
            entries.append(self._entry(shape, "slo_burn"))
        return entries

    # -- occupancy (signal-plane scale-down ranking) -----------------------

    def _occupancy(self, node_ids: List[str]) -> Dict[str, float]:
        """Windowed per-node CPU occupancy from the head's signal ring;
        empty when the ring is disabled (callers fall back to insertion
        order)."""
        try:
            res = self.head.call("query_metrics", {
                "op": "gauge_avg", "name": "ray_tpu_worker_cpu_percent",
                "window_s": max(30.0, self.idle_timeout_s),
                "group_by": "node_id",
            }, timeout=5.0)
        except Exception:
            return {}
        if not isinstance(res, dict) or not res.get("ok"):
            return {}
        value = res.get("value")
        if not isinstance(value, dict):
            return {}
        return {nid: float(v) for nid, v in value.items()
                if nid in node_ids}

    # -- reconcile ---------------------------------------------------------

    def update(self) -> dict:
        """One reconcile round: bin-pack pending demand into launches,
        scale down idle provider nodes past the timeout (drain first,
        terminate after the head reports them dead)."""
        failpoints.hit("autoscaler.tick")
        from ray_tpu.util import metrics

        now = time.monotonic()
        try:
            demands = self.head.call("demand_snapshot", 10.0)
        except Exception:
            # Older head: flat infeasible-task list only.
            demands = {"tasks": self.head.call("pending_demands", 10.0)}
        try:
            self._poll_slo_events()
        except Exception:
            self._slo_subscribed = False  # resubscribe next pass
        entries = self._normalize(demands) + self._boost_entries(now)
        counts: Dict[str, int] = {}
        for e in entries:
            counts[e["kind"]] = counts.get(e["kind"], 0) + 1
        for kind in ("task", "actor", "pg_bundle", "slo_burn"):
            metrics.AUTOSCALER_PENDING_DEMAND.set(
                float(counts.get(kind, 0)), tags={"kind": kind})
        nodes = self.head.call("nodes")
        alive = [n for n in nodes if n["Alive"]]
        report = {"launched": [], "terminated": [], "launch_failures": []}

        mine = set(self.provider.non_terminated_nodes())
        # Externally-dead tracked nodes: a spot preemption notice or an
        # operator drain lands as a head-side death the provider never
        # initiated — and a completed drain even shuts the agent down,
        # dropping it from the provider view before this pass runs.
        # Either way, reclaim the slot and close the goodput ledger
        # with the attributed cause: "preemption" for a preempted spot
        # node, "drain:<reason>" for an external drain,
        # "failure:<cause>" for an on-demand crash.
        table = {n["NodeID"]: n for n in nodes}
        for node_id in list(self._node_type_of):
            if node_id in self._draining:
                continue  # autoscaler-initiated: _reap_drained owns it
            info = table.get(node_id)
            dead = info is not None and not info["Alive"]
            if not dead:
                if node_id not in mine and info is None:
                    # Gone from the provider without ever registering
                    # (boot death): just untrack.
                    self._node_type_of.pop(node_id, None)
                continue
            cause = info.get("DeathCause") or ""
            if cause.startswith("drained: "):
                reason = cause[len("drained: "):]
                ack = ("preemption" if reason == "preemption"
                       else f"drain:{reason}")
            elif bool((info.get("Labels") or {}).get("spot")):
                ack = "preemption"  # spot died without notice
            else:
                ack = f"failure:{cause or 'unknown'}"
            if self._terminate(node_id, report, ack_cause=ack):
                mine.discard(node_id)
                self._idle_since.pop(node_id, None)
        per_type: Dict[str, int] = {}
        for nid in mine:
            t = self._node_type_of.get(nid)
            if t is not None:
                per_type[t] = per_type.get(t, 0) + 1

        # Live headroom: pending demand packs into ALIVE schedulable
        # nodes' available capacity before any launch is planned.
        # Existing rooms start with empty strict-spread group sets (the
        # autoscaler doesn't see which nodes hold a gang's PLACED
        # bundles — worst case it under-plans one node and the next
        # pass corrects), and carry the agent's spot label so
        # ``spot: false`` demand never counts preemptible headroom.
        existing_rooms = []
        for n in alive:
            if n.get("State", "ALIVE") != "ALIVE":
                continue
            labels = n.get("Labels") or {}
            existing_rooms.append({
                "resources": dict(n["Available"]),
                "type": labels.get("node_type") or "",
                "spot": bool(labels.get("spot")),
                "groups": set(),
            })

        if entries and now - self._last_launch >= self.launch_cooldown_s:
            for type_name in self._nodes_to_launch(
                    entries, len(mine), per_type, now, existing_rooms):
                st = self._state_of(type_name)
                if now < st.next_attempt:
                    continue  # backoff gate: this type waits its turn
                cfg = self.node_types[type_name]
                try:
                    failpoints.hit("autoscaler.before_create")
                    node_id, dt = self._timed_create(type_name, cfg)
                except Exception:
                    self._on_launch_failure(type_name, time.monotonic())
                    report["launch_failures"].append(type_name)
                    continue
                st.failures = 0
                st.next_attempt = 0.0
                self._node_type_of[node_id] = type_name
                self.launched.append(node_id)
                report["launched"].append(node_id)
                self._last_launch = time.monotonic()
                metrics.AUTOSCALER_LAUNCHES_TOTAL.inc(
                    tags={"node_type": type_name})
                metrics.AUTOSCALER_LAUNCH_SECONDS.observe(
                    dt, tags={"node_type": type_name})
            if report["launched"]:
                self._boosts.clear()  # burn demand absorbed

        # Scale down: provider-owned nodes fully idle past the timeout
        # are DRAINED before the provider terminate hook — a task that
        # landed during the idle window finishes (or its actors migrate)
        # instead of being killed mid-flight, and the node is excluded
        # from new placements the moment the drain starts, so the window
        # cannot refill either. Drains are initiated asynchronously
        # (wait=False) so one busy node cannot stall the whole reconcile
        # pass; termination lands once the head reports the node DEAD.
        self._reap_drained({n["NodeID"]: n for n in nodes}, report)
        by_id = {n["NodeID"]: n for n in alive}

        def fits_pending(info: dict) -> bool:
            # Scale-down must not race scale-up: a node that could
            # serve a pending demand entry is about to be used (the
            # client's retry just hasn't landed yet) — draining it now
            # would shoot the very capacity this pass exists to
            # provide, then relaunch it.
            avail = info["Available"]
            return any(
                all(avail.get(k, 0.0) >= v
                    for k, v in e["resources"].items())
                for e in entries)

        candidates: List[str] = []
        for node_id in list(self.provider.non_terminated_nodes()):
            if node_id in self._draining:
                continue  # drain in flight; _reap_drained settles it
            info = by_id.get(node_id)
            if info is None or info.get("State", "ALIVE") != "ALIVE":
                continue
            idle = info["Available"] == info["Resources"]
            if not idle or (entries and fits_pending(info)):
                self._idle_since.pop(node_id, None)
                continue
            since = self._idle_since.setdefault(node_id, now)
            if now - since >= self.idle_timeout_s:
                candidates.append(node_id)
        started: list = []
        if candidates:
            # Signal-plane ranking: drain the occupancy-coldest node
            # first — "fully idle right now" can still differ in recent
            # load, and the colder node's caches/objects are staler.
            occ = self._occupancy(candidates)
            candidates.sort(key=lambda nid: occ.get(nid, 0.0))
        for node_id in candidates:
            try:
                self.head.call(
                    "drain_node", node_id, "autoscaler_idle",
                    self.drain_deadline_s, False, timeout=15.0)
                self._draining[node_id] = None
                started.append(node_id)
            except Exception:
                # Head hiccup: terminate ungracefully (old behavior)
                # rather than leak the provider node.
                self._terminate(node_id, report)
            self._idle_since.pop(node_id, None)
        self._report_state(now, per_type)
        if started:
            # Bounded settle: an idle node drains in well under a
            # second, so give this pass a brief window to finish the
            # common case in place; busy nodes settle on a later pass.
            deadline = time.monotonic() + min(3.0, self.drain_deadline_s + 1.0)
            while started and time.monotonic() < deadline:
                time.sleep(0.05)
                try:
                    table = {n["NodeID"]: n for n in self.head.call("nodes")}
                except Exception:
                    break
                self._reap_drained(table, report)
                started = [n for n in started if n in self._draining]
        return report

    def _terminate(self, node_id: str, report: dict,
                   ack_cause: str | None = None) -> bool:
        """Provider terminate behind the failpoint + churn metric; a
        failure leaves the node for a later pass instead of leaking the
        drain state."""
        from ray_tpu.util import metrics

        try:
            failpoints.hit("autoscaler.before_terminate")
            self.provider.terminate_node(node_id)
        except Exception:
            return False
        report["terminated"].append(node_id)
        node_type = self._node_type_of.pop(node_id, None) or "unknown"
        metrics.AUTOSCALER_SCALE_DOWNS_TOTAL.inc(
            tags={"node_type": node_type})
        if ack_cause is not None:
            try:
                self.head.call("terminate_ack", node_id, ack_cause,
                               timeout=5.0)
            except Exception:
                pass  # ledger ack is best-effort; state is settled
        return True

    def _reap_drained(self, node_table: dict, report: dict) -> None:
        """Terminate provider nodes whose scale-down drain completed."""
        for node_id in list(self._draining):
            info = node_table.get(node_id)
            if info is not None and info["Alive"]:
                continue  # still draining
            if self._terminate(node_id, report,
                               ack_cause="drain:autoscaler_idle"):
                self._draining.pop(node_id, None)

    def _report_state(self, now: float,
                      per_type: Dict[str, int]) -> None:
        """Push per-type quarantine/backoff state to the head (full-state
        replace) so `ray-tpu status` and the dashboard can show it."""
        types = {}
        for t in self.node_types:
            st = self._state_of(t)
            types[t] = {
                "spot": self._is_spot(t),
                "nodes": per_type.get(t, 0),
                "failures": st.failures,
                "quarantined": now < st.quarantined_until,
                "quarantine_remaining_s": round(
                    max(0.0, st.quarantined_until - now), 3),
                "backoff_remaining_s": round(
                    max(0.0, st.next_attempt - now), 3),
            }
        try:
            self.head.call("autoscaler_report", {
                "types": types,
                "max_workers": self.max_workers,
                "draining": sorted(self._draining),
                "slo_burns": sorted(self._slo_burn),
            }, timeout=5.0)
        except Exception:
            pass  # status surface only; next tick replaces it anyway

    def start(self, interval_s: float = 1.0) -> None:
        def loop():
            from ray_tpu.util import metrics
            from ray_tpu.util import tracing

            while not self._stop.wait(interval_s):
                try:
                    # Suppressed: the reconcile pass fans out head/agent
                    # RPCs every second — cadence traffic that would
                    # swamp the span buffer with traces nobody asked
                    # for (same rule as the serve controller's loop).
                    with tracing.suppressed():
                        self.update()
                except Exception:
                    metrics.count_loop_restart("autoscaler.reconcile")
                    continue

        threading.Thread(target=loop, daemon=True).start()

    def stop(self) -> None:
        self._stop.set()
        from ray_tpu.util import metrics

        # Retract this fleet's per-kind demand series (and the loop
        # restart counter) from the registry: a torn-down autoscaler
        # must not linger on the federated scrape.
        for kind in ("task", "actor", "pg_bundle", "slo_burn"):
            metrics.AUTOSCALER_PENDING_DEMAND.remove(
                tags={"kind": kind})
        metrics.retract_loop_series(["autoscaler.reconcile"])
