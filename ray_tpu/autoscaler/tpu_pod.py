"""TPU-pod node provider: provision TPU VM hosts via external CLIs.

Reference parity: the cloud ``NodeProvider`` plugins + SSH/docker command
runner (``python/ray/autoscaler/node_provider.py:23``,
``_private/command_runner.py``), specialized for the TPU deployment story
(SURVEY.md §7 step 12): a worker node type maps to a TPU VM shape
(``gcloud compute tpus tpu-vm create ... --accelerator-type v5e-8``) or a
GKE node-pool resize.

The provider shells out through a pluggable :class:`CommandRunner`, so
the same reconcile logic drives:

* real ``gcloud`` (default command templates),
* any other CLI (override ``commands`` in the provider section),
* **dry-run mode** (``dry_run: true``): commands are recorded instead of
  executed, and each "created" pod is simulated by attaching a local node
  of the declared shape to the cluster — the full autoscaler loop
  (demand -> launch -> join -> idle -> terminate) runs end-to-end with no
  cloud, the fake_multi_node testing story.

YAML:

    provider:
      type: tpu_pod
      project: my-proj
      zone: us-central2-b
      runtime_version: tpu-ubuntu2204-base
      dry_run: true
    available_node_types:
      v5e_host:
        num_cpus: 8
        resources: {TPU: 4}
        accelerator_type: v5litepod-4
        min_workers: 0
        max_workers: 4
"""

from __future__ import annotations

import shlex
import subprocess
from typing import Dict, List, Optional

from ray_tpu.autoscaler import NodeProvider

# Command templates; {name}/{zone}/{project}/{accelerator_type}/
# {runtime_version} are filled per call. Overridable via the provider
# section's "commands" mapping.
DEFAULT_COMMANDS = {
    "create": (
        "gcloud compute tpus tpu-vm create {name} --zone {zone} "
        "--project {project} --accelerator-type {accelerator_type} "
        "--version {runtime_version}"
    ),
    "delete": (
        "gcloud compute tpus tpu-vm delete {name} --zone {zone} "
        "--project {project} --quiet"
    ),
    "list": (
        "gcloud compute tpus tpu-vm list --zone {zone} "
        "--project {project} --format value(name)"
    ),
}


class CommandRunner:
    """Executes provisioning commands (reference command_runner.py).
    ``timeout`` bounds one command's wall clock — a wedged cloud CLI
    fails the launch (feeding the autoscaler's backoff/quarantine
    schedule) instead of hanging the reconcile pass."""

    def run(self, argv: List[str],
            timeout: Optional[float] = None) -> str:
        return subprocess.check_output(argv, text=True, timeout=timeout)


class DryRunCommandRunner(CommandRunner):
    """Records what WOULD run; returns empty output."""

    def __init__(self):
        self.commands: List[List[str]] = []

    def run(self, argv: List[str],
            timeout: Optional[float] = None) -> str:
        self.commands.append(list(argv))
        return ""


class TPUPodNodeProvider(NodeProvider):
    def __init__(self, provider_config: dict, cluster=None,
                 runner: Optional[CommandRunner] = None):
        self.config = dict(provider_config)
        self.dry_run = bool(self.config.get("dry_run"))
        self.runner = runner or (
            DryRunCommandRunner() if self.dry_run else CommandRunner())
        self.commands = {**DEFAULT_COMMANDS,
                         **(self.config.get("commands") or {})}
        self.cluster = cluster  # simulation target in dry-run mode
        self._seq = 0
        # pod name -> simulated local agent (dry-run) or None (real)
        self._pods: Dict[str, object] = {}

    # -- command plumbing --------------------------------------------------

    def _argv(self, which: str, **fields) -> List[str]:
        tpl = self.commands[which]
        filled = tpl.format(
            project=self.config.get("project", ""),
            zone=self.config.get("zone", ""),
            runtime_version=self.config.get(
                "runtime_version", "tpu-ubuntu2204-base"),
            **fields,
        )
        return shlex.split(filled)

    # -- NodeProvider ------------------------------------------------------

    def create_node(self, node_type: str, node_config: dict) -> str:
        self._seq += 1
        prefix = self.config.get("name_prefix", "ray-tpu")
        name = f"{prefix}-{node_type}-{self._seq}"
        accel = node_config.get(
            "accelerator_type",
            self.config.get("accelerator_type", "v5litepod-4"))
        from ray_tpu.core.config import config as _config

        self.runner.run(
            self._argv("create", name=name, accelerator_type=accel),
            timeout=_config.autoscaler_launch_timeout_s or None)
        agent = None
        if self.dry_run and self.cluster is not None:
            # Simulate the pod host joining the cluster with the declared
            # shape, so demand actually drains and idle-scale-down has a
            # real node to observe.
            agent = self.cluster.add_node(
                num_cpus=node_config.get("num_cpus"),
                resources=node_config.get("resources"),
                labels={"node_type": node_type,
                        "spot": bool(node_config.get("spot", False))},
            )
        self._pods[name] = agent
        # In dry-run the provider's node id must match the joined node's
        # cluster id (the autoscaler cross-references the head's view).
        return agent.node_id if agent is not None else name

    def terminate_node(self, node_id: str) -> None:
        name = self._name_of(node_id)
        if name is None:
            return
        self.runner.run(self._argv("delete", name=name))
        agent = self._pods.pop(name, None)
        if agent is not None and self.cluster is not None:
            self.cluster.remove_node(agent)

    def non_terminated_nodes(self) -> List[str]:
        if not self.dry_run:
            # Reconcile against the cloud's view (a restarted launcher
            # must adopt — and be able to terminate — pods a previous
            # incarnation created, instead of double-provisioning).
            prefix = self.config.get("name_prefix", "ray-tpu") + "-"
            try:
                out = self.runner.run(self._argv("list"))
            except (OSError, subprocess.CalledProcessError):
                out = ""
            for line in out.splitlines():
                name = line.strip()
                if name.startswith(prefix) and name not in self._pods:
                    self._pods[name] = None
        return [
            (agent.node_id if agent is not None else name)
            for name, agent in self._pods.items()
        ]

    def _name_of(self, node_id: str) -> Optional[str]:
        for name, agent in self._pods.items():
            if name == node_id or (
                    agent is not None and agent.node_id == node_id):
                return name
        return None


def _factory(provider_config: dict, cluster) -> TPUPodNodeProvider:
    return TPUPodNodeProvider(provider_config, cluster)


def register() -> None:
    from ray_tpu.autoscaler.launcher import register_node_provider

    register_node_provider("tpu_pod", _factory)


register()
