"""Trial schedulers: early stopping + population-based training.

Reference parity: ``python/ray/tune/schedulers/`` — FIFO,
AsyncHyperBand/ASHA (``async_hyperband.py``), median stopping rule
(``median_stopping_rule.py``), and PBT (``pbt.py``) exploit/explore.
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def on_trial_result(self, runner, trial, result: dict) -> str:
        return CONTINUE

    def on_trial_complete(self, runner, trial, result: Optional[dict]) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Async successive halving: at each rung, stop trials below the top
    1/reduction_factor quantile of peers that reached the rung."""

    def __init__(
        self,
        metric: str = "score",
        mode: str = "max",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: int = 4,
        time_attr: str = "training_iteration",
    ):
        if mode not in ("max", "min"):
            raise ValueError("mode must be max|min")
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        # rung value t -> {trial_id: recorded metric value at that rung}.
        # Each trial is recorded at most once per rung so rung populations
        # are peers-that-reached-the-rung, not per-iteration duplicates.
        self.rungs: Dict[int, Dict[str, float]] = {}
        t = grace_period
        while t < max_t:
            self.rungs[t] = {}
            t *= reduction_factor
        # Highest rung each trial has been recorded at (a trial never
        # late-records into a rung it already skipped past).
        self._trial_top: Dict[str, int] = {}

    def on_trial_result(self, runner, trial, result: dict) -> str:
        t = result.get(self.time_attr, 0)
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        if self.mode == "min":
            value = -value
        if t >= self.max_t:
            return STOP
        for rung_t in sorted(self.rungs, reverse=True):
            if t < rung_t:
                continue
            if self._trial_top.get(trial.trial_id, -1) >= rung_t:
                break  # already judged at (or above) this rung
            recorded = self.rungs[rung_t]
            # Cutoff from peers already at the rung, BEFORE recording
            # this trial (mirrors the async-successive-halving rule).
            cutoff = None
            if recorded:
                vals = sorted(recorded.values(), reverse=True)
                k = max(1, len(vals) // self.rf)
                cutoff = vals[k - 1]
            recorded[trial.trial_id] = value
            self._trial_top[trial.trial_id] = rung_t
            if cutoff is not None and value < cutoff:
                return STOP
            break
        return CONTINUE


class HyperBandScheduler(TrialScheduler):
    """HyperBand (Li et al. 2017): several successive-halving brackets with
    different exploration/exploitation trade-offs run side by side; new
    trials deal round-robin into brackets, each bracket stops trials below
    its top-1/eta quantile at its rung milestones.

    Async-bracket formulation (the reference's
    ``schedulers/async_hyperband.py`` with ``brackets=N``; its synchronous
    ``hyperband.py`` blocks rungs on stragglers — deliberately avoided
    here, same trade-off the reference recommends): bracket s has grace
    period max_t * eta^-s, so s=0 never early-stops and higher s cut
    earlier and more aggressively.
    """

    def __init__(self, metric: str = "score", mode: str = "max",
                 max_t: int = 81, eta: int = 3, brackets: int = 3,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.brackets = [
            ASHAScheduler(
                metric=metric, mode=mode, max_t=max_t,
                grace_period=max(1, int(max_t * eta ** -s)),
                reduction_factor=eta, time_attr=time_attr,
            )
            for s in range(brackets)
        ]
        self._assignment: Dict[str, int] = {}
        self._next = 0

    def _bracket_for(self, trial) -> "ASHAScheduler":
        b = self._assignment.get(trial.trial_id)
        if b is None:
            b = self._assignment[trial.trial_id] = (
                self._next % len(self.brackets))
            self._next += 1
        return self.brackets[b]

    def on_trial_result(self, runner, trial, result: dict) -> str:
        return self._bracket_for(trial).on_trial_result(
            runner, trial, result)

    def on_trial_complete(self, runner, trial, result) -> None:
        if trial.trial_id in self._assignment:
            self._bracket_for(trial).on_trial_complete(
                runner, trial, result)


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running mean is below the median of completed
    means at the same step."""

    def __init__(self, metric: str = "score", mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self.time_attr = time_attr
        self.histories: Dict[str, List[float]] = {}

    def on_trial_result(self, runner, trial, result: dict) -> str:
        value = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if value is None or t < self.grace:
            return CONTINUE
        if self.mode == "min":
            value = -value
        hist = self.histories.setdefault(trial.trial_id, [])
        hist.append(value)
        means = [
            sum(h) / len(h)
            for tid, h in self.histories.items()
            if tid != trial.trial_id and h
        ]
        if len(means) < self.min_samples:
            return CONTINUE
        means.sort()
        median = means[len(means) // 2]
        mine = sum(hist) / len(hist)
        return STOP if mine < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT: every ``perturbation_interval`` results, bottom-quantile trials
    exploit (copy checkpoint + config of) a top-quantile trial, then
    explore (perturb hyperparameters) and restart from that checkpoint.

    The runner performs the actual restart (see TrialRunner._pbt_exploit).
    """

    def __init__(
        self,
        metric: str = "score",
        mode: str = "max",
        perturbation_interval: int = 4,
        hyperparam_mutations: Optional[dict] = None,
        quantile_fraction: float = 0.25,
        time_attr: str = "training_iteration",
        seed: Optional[int] = None,
    ):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.time_attr = time_attr
        self.last_perturb: Dict[str, int] = {}
        self.latest: Dict[str, float] = {}
        self.rng = random.Random(seed)

    def _score(self, result) -> Optional[float]:
        v = result.get(self.metric)
        if v is None:
            return None
        return -v if self.mode == "min" else v

    def on_trial_result(self, runner, trial, result: dict) -> str:
        score = self._score(result)
        if score is not None:
            self.latest[trial.trial_id] = score
        t = result.get(self.time_attr, 0)
        if t - self.last_perturb.get(trial.trial_id, 0) < self.interval:
            return CONTINUE
        self.last_perturb[trial.trial_id] = t
        ranked = sorted(self.latest.items(), key=lambda kv: kv[1])
        n = len(ranked)
        if n < 2:
            return CONTINUE
        k = max(1, int(n * self.quantile))
        bottom = {tid for tid, _ in ranked[:k]}
        top = [tid for tid, _ in ranked[-k:]]
        if trial.trial_id in bottom:
            donor_id = self.rng.choice(top)
            if donor_id != trial.trial_id:
                runner._pbt_exploit(trial, donor_id, self)
        return CONTINUE

    def perturb_config(self, config: dict) -> dict:
        out = dict(config)
        for key, mutation in self.mutations.items():
            if callable(mutation):
                out[key] = mutation()
            elif isinstance(mutation, list):
                out[key] = self.rng.choice(mutation)
            elif isinstance(mutation, tuple) and len(mutation) == 2:
                lo, hi = mutation
                factor = self.rng.choice([0.8, 1.2])
                out[key] = min(hi, max(lo, out.get(key, lo) * factor))
        return out
