"""Search space primitives + variant generation.

Reference parity: ``python/ray/tune/search/sample.py`` (Domain classes:
uniform/loguniform/randint/choice/...), ``grid_search`` markers, and the
``BasicVariantGenerator`` grid×sample expansion
(``tune/search/basic_variant.py``).
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Dict, List, Optional

import numpy as np


class Domain:
    def sample(self, rng: np.random.Generator):
        raise NotImplementedError


class Uniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return float(rng.uniform(self.low, self.high))


class QUniform(Domain):
    def __init__(self, low, high, q):
        self.low, self.high, self.q = low, high, q

    def sample(self, rng):
        v = rng.uniform(self.low, self.high)
        return float(np.round(v / self.q) * self.q)


class LogUniform(Domain):
    def __init__(self, low, high):
        if low <= 0 or high <= 0:
            raise ValueError("loguniform bounds must be positive")
        self.low, self.high = low, high

    def sample(self, rng):
        return float(np.exp(rng.uniform(np.log(self.low), np.log(self.high))))


class RandInt(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return int(rng.integers(self.low, self.high))


class Choice(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return self.categories[int(rng.integers(0, len(self.categories)))]


class SampleFrom(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng):
        return self.fn(None)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def quniform(low, high, q) -> QUniform:
    return QUniform(low, high, q)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> RandInt:
    return RandInt(low, high)


def choice(categories) -> Choice:
    return Choice(categories)


def sample_from(fn) -> SampleFrom:
    return SampleFrom(fn)


def grid_search(values: list) -> dict:
    return {"grid_search": list(values)}


def _is_grid(v) -> bool:
    return isinstance(v, dict) and set(v.keys()) == {"grid_search"}


def generate_variants(
    param_space: Dict[str, Any],
    num_samples: int = 1,
    seed: Optional[int] = None,
) -> List[Dict[str, Any]]:
    """Cross-product of grid_search values × num_samples draws of sampled
    domains (BasicVariantGenerator semantics: grids multiply, samples
    repeat)."""
    rng = np.random.default_rng(seed)
    grid_keys = [k for k, v in param_space.items() if _is_grid(v)]
    grid_values = [param_space[k]["grid_search"] for k in grid_keys]
    combos = list(itertools.product(*grid_values)) if grid_keys else [()]
    variants = []
    for _ in range(num_samples):
        for combo in combos:
            cfg = {}
            for k, v in param_space.items():
                if k in grid_keys:
                    cfg[k] = combo[grid_keys.index(k)]
                elif isinstance(v, Domain):
                    cfg[k] = v.sample(rng)
                elif isinstance(v, dict) and not _is_grid(v):
                    cfg[k] = generate_variants(v, 1, int(rng.integers(1 << 31)))[0]
                else:
                    cfg[k] = v
            variants.append(cfg)
    return variants
