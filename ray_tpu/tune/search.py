"""Search algorithms: the ask/tell ``Searcher`` plugin API, the default
variant generator behind it, and a model-based TPE searcher.

Reference parity: ``python/ray/tune/search/searcher.py:21`` (Searcher:
``suggest`` / ``on_trial_result`` / ``on_trial_complete`` /
``set_search_properties``), ``search/basic_variant.py`` (grid x random),
and the model-based integrations (``search/optuna``, ``search/hyperopt``,
...). Rather than wrapping external libraries, the model-based searcher is
implemented here directly: a Tree-structured Parzen Estimator — the
algorithm behind hyperopt and optuna's default sampler — over the native
search-space ``Domain`` types.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.tune.search_space import (
    Choice,
    Domain,
    LogUniform,
    QUniform,
    RandInt,
    Uniform,
    _is_grid,
    generate_variants,
)


class Searcher:
    """Ask/tell interface. Subclasses implement ``suggest`` and (usually)
    ``on_trial_complete``; the TrialRunner drives:

        cfg = searcher.suggest(trial_id)      # None = wait / exhausted
        ...trial runs...
        searcher.on_trial_result(trial_id, result)      # each report
        searcher.on_trial_complete(trial_id, result)    # final
    """

    #: True for searchers that pre-expand their own trial budget (grid x
    #: num_samples). The runner must then run them to exhaustion instead of
    #: capping at ``num_samples`` — a grid of 3 with num_samples=2 is 6
    #: trials, not 2.
    expands_variants = False

    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        self.metric = metric
        self.mode = mode

    def set_search_properties(self, metric: Optional[str], mode: Optional[str],
                              config: Dict[str, Any]) -> bool:
        """Late-bind metric/mode/space from the Tuner. Returns False if the
        searcher was already configured with a conflicting space."""
        if metric:
            self.metric = metric
        if mode:
            self.mode = mode
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        pass

    def on_trial_complete(self, trial_id: str, result: Optional[dict] = None,
                          error: bool = False) -> None:
        pass

    def tell(self, config: Dict[str, Any],
             result: Optional[dict]) -> None:
        """Inject an out-of-band observation (experiment restore replays
        finished trials this way — their ids were never suggest()-ed)."""
        pass

    # -- shared helpers ----------------------------------------------------

    def _score(self, result: Optional[dict]) -> Optional[float]:
        if not result or self.metric is None:
            return None
        v = result.get(self.metric)
        if v is None:
            return None
        return -float(v) if self.mode == "min" else float(v)


class BasicVariantSearcher(Searcher):
    """The default searcher: pre-expands grid x num_samples variants and
    deals them out (``search/basic_variant.py`` semantics)."""

    expands_variants = True

    def __init__(self, param_space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None, **kw):
        super().__init__(**kw)
        self._variants = generate_variants(
            param_space, num_samples=num_samples, seed=seed)
        self._next = 0

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._next >= len(self._variants):
            return None
        cfg = self._variants[self._next]
        self._next += 1
        return cfg


# ---------------------------------------------------------------------------
# TPE
# ---------------------------------------------------------------------------


def _flatten(space: dict, prefix: str = "") -> Dict[str, Any]:
    out = {}
    for k, v in space.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict) and not _is_grid(v):
            out.update(_flatten(v, key + "."))
        else:
            out[key] = v
    return out


def _unflatten(flat: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key, v in flat.items():
        parts = key.split(".")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return out


class _NumDim:
    """A numeric dimension in the (possibly log-) transformed unit space."""

    def __init__(self, domain):
        self.domain = domain
        self.log = isinstance(domain, LogUniform)
        if isinstance(domain, (Uniform, LogUniform)):
            lo, hi = domain.low, domain.high
        elif isinstance(domain, QUniform):
            lo, hi = domain.low, domain.high
        elif isinstance(domain, RandInt):
            lo, hi = domain.low, domain.high - 1
        else:
            raise TypeError(domain)
        self.lo = np.log(lo) if self.log else float(lo)
        self.hi = np.log(hi) if self.log else float(hi)
        self.width = max(self.hi - self.lo, 1e-12)

    def to_unit(self, v: float) -> float:
        x = np.log(v) if self.log else float(v)
        return float(np.clip((x - self.lo) / self.width, 0.0, 1.0))

    def from_unit(self, u: float):
        x = self.lo + float(np.clip(u, 0.0, 1.0)) * self.width
        v = float(np.exp(x)) if self.log else float(x)
        d = self.domain
        if isinstance(d, QUniform):
            v = float(np.round(v / d.q) * d.q)
        elif isinstance(d, RandInt):
            v = int(np.clip(round(v), d.low, d.high - 1))
        return v


def _parzen_logpdf(x: np.ndarray, centers: np.ndarray,
                   bws: np.ndarray) -> np.ndarray:
    """log density of a gaussian-mixture KDE (per-component bandwidths)
    blended with a uniform prior over [0,1] (weight 1/(n+1), shrinking as
    data accumulates). The prior keeps the l/g ratio well-conditioned in
    unexplored regions — without it TPE ping-pongs between empty corners
    where both densities underflow."""
    if centers.size == 0:
        return np.zeros_like(x)  # uniform prior only
    d = (x[:, None] - centers[None, :]) / bws[None, :]
    log_k = -0.5 * d * d - np.log(bws[None, :] * np.sqrt(2 * np.pi))
    m = log_k.max(axis=1, keepdims=True)
    kde = m[:, 0] + np.log(np.mean(np.exp(log_k - m), axis=1))
    prior_w = 1.0 / (centers.size + 1.0)
    return np.logaddexp(np.log(prior_w), np.log1p(-prior_w) + kde)


def _adaptive_bw(centers: np.ndarray, bw_min: float = 0.03) -> np.ndarray:
    """Per-point bandwidth = the larger neighbor gap after sorting (domain
    ends [0,1] count as neighbors) — hyperopt's heuristic: dense clusters
    get sharp kernels, isolated points stay wide."""
    if centers.size == 0:
        return centers
    order = np.argsort(centers)
    s = centers[order]
    ext = np.concatenate([[0.0], s, [1.0]])
    gaps = np.maximum(ext[1:-1] - ext[:-2], ext[2:] - ext[1:-1])
    out = np.empty_like(gaps)
    out[order] = np.clip(gaps, bw_min, 1.0)
    return out


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (Bergstra et al. 2011 — the model
    behind hyperopt; cf. the reference's ``search/hyperopt`` integration).

    After ``n_initial`` random suggestions, observations split into a
    "good" top-``gamma`` quantile and the rest; each dimension gets 1-D
    Parzen density estimates l(x) (good) and g(x) (bad), and the next
    suggestion maximizes l/g over ``n_candidates`` draws from l.

    ``multivariate`` (optuna's ``TPESampler(multivariate=True)`` analog,
    default ``"auto"``): model the good/bad sets with JOINT per-
    observation product kernels over the whole unit hypercube instead of
    independent per-dimension estimates. Candidates are drawn as whole
    vectors around good observations, so correlations between dimensions
    (e.g. lr x batch-size ridges) survive into the suggestions — the
    canonical independent model mixes marginals and loses them. "auto"
    uses the joint model when every dimension is numeric/categorical and
    both split sides have >= 2 observations, falling back to the
    univariate model otherwise.
    """

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 param_space: Optional[Dict[str, Any]] = None,
                 n_initial: int = 8, gamma: float = 0.25,
                 n_candidates: int = 32, seed: Optional[int] = None,
                 multivariate: "bool | str" = "auto"):
        super().__init__(metric=metric, mode=mode)
        self.multivariate = multivariate
        self._space: Dict[str, Any] = {}
        if param_space:
            self._set_space(param_space)
        self.n_initial = n_initial
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = np.random.default_rng(seed)
        self._live: Dict[str, Dict[str, Any]] = {}   # trial_id -> flat cfg
        self._obs: list[tuple[Dict[str, Any], float]] = []

    def _set_space(self, space: Dict[str, Any]) -> None:
        flat = _flatten(space)
        self._space = {}
        for k, v in flat.items():
            if _is_grid(v):
                v = Choice(v["grid_search"])  # grids become categoricals
            self._space[k] = v

    def set_search_properties(self, metric, mode, config) -> bool:
        if self._space and config:
            return False  # space fixed at construction
        super().set_search_properties(metric, mode, config)
        if config:
            self._set_space(config)
        return True

    # -- ask ---------------------------------------------------------------

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if not self._space:
            raise ValueError("TPESearcher has no search space")
        if len(self._obs) < self.n_initial:
            flat = self._random_flat()
        else:
            flat = self._model_flat()
        self._live[trial_id] = flat
        return _unflatten(flat)

    def _random_flat(self) -> Dict[str, Any]:
        out = {}
        for k, v in self._space.items():
            out[k] = v.sample(self.rng) if isinstance(v, Domain) else v
        return out

    def _model_flat(self) -> Dict[str, Any]:
        scores = np.array([s for _, s in self._obs])
        # hyperopt's split: the good set is the top ceil(gamma * sqrt(n)),
        # capped — a handful of elite points keeps l(x) sharp. A
        # proportional split (gamma * n) flattens l over mediocre points
        # and measures no better than random on the test surfaces.
        n_good = max(2, min(25, int(np.ceil(
            self.gamma * np.sqrt(len(scores))))))
        order = np.argsort(-scores)  # maximize internally
        good_idx = set(order[:n_good].tolist())
        if self.multivariate in (True, "auto"):
            # The joint KDE needs a denser good set than the univariate
            # elite-only split: per-observation product kernels around 2
            # points don't carve out a manifold. Proportional split
            # (optuna's gamma): ~15% of observations, at least 4.
            n_good_j = max(4, min(25, int(np.ceil(0.15 * len(scores)))))
            out = self._joint_suggest(set(order[:n_good_j].tolist()))
            if out is not None:
                return out
        out = {}
        for k, dom in self._space.items():
            if not isinstance(dom, Domain):
                out[k] = dom
                continue
            good = [cfg[k] for i, (cfg, _) in enumerate(self._obs)
                    if i in good_idx and k in cfg]
            bad = [cfg[k] for i, (cfg, _) in enumerate(self._obs)
                   if i not in good_idx and k in cfg]
            if isinstance(dom, Choice):
                out[k] = self._suggest_categorical(dom, good, bad)
            elif isinstance(dom, (Uniform, LogUniform, QUniform, RandInt)):
                out[k] = self._suggest_numeric(dom, good, bad)
            else:
                # Unmodellable domain (e.g. SampleFrom): keep sampling
                # from it rather than crash the search mid-experiment.
                out[k] = dom.sample(self.rng)
        return out

    # -- joint (multivariate) model ---------------------------------------

    def _joint_suggest(self, good_idx) -> Optional[Dict[str, Any]]:
        """Joint-kernel TPE over the whole space: l(x) and g(x) are
        mixtures of per-OBSERVATION product kernels (gaussian on numeric
        dims in unit space, Aitchison–Aitken-style on categoricals), and
        candidates are whole vectors drawn around good observations.
        Returns None when the space/observations don't support the joint
        model (caller falls back to the univariate path)."""
        numd: list = []   # (key, _NumDim)
        catd: list = []   # (key, Choice, {cat_key: idx})
        fixed: Dict[str, Any] = {}
        for k, dom in self._space.items():
            if not isinstance(dom, Domain):
                fixed[k] = dom
            elif isinstance(dom, Choice):
                catd.append((k, dom, {self._cat_key(c): i
                                      for i, c in enumerate(dom.categories)}))
            elif isinstance(dom, (Uniform, LogUniform, QUniform, RandInt)):
                numd.append((k, _NumDim(dom)))
            else:
                return None  # SampleFrom etc.: not jointly modellable
        if not numd and not catd:
            return None

        def rows(idx_filter):
            num, cat = [], []
            for i, (cfg, _) in enumerate(self._obs):
                if not idx_filter(i):
                    continue
                try:
                    num.append([nd.to_unit(cfg[k]) for k, nd in numd])
                    cat.append([lut[self._cat_key(cfg[k])]
                                for k, _dom, lut in catd])
                except (KeyError, TypeError):
                    continue  # stale/partial observation: skip
            return (np.array(num, dtype=float).reshape(len(num), len(numd)),
                    np.array(cat, dtype=int).reshape(len(cat), len(catd)))

        g_num, g_cat = rows(lambda i: i in good_idx)
        b_num, b_cat = rows(lambda i: i not in good_idx)
        if len(g_num) < 2 or len(b_num) < 2:
            return None

        # Per-point per-dim bandwidths from the neighbor-gap heuristic.
        def bws(mat):
            out = np.empty_like(mat)
            for d in range(mat.shape[1]):
                out[:, d] = _adaptive_bw(mat[:, d])
            return out

        bw_g, bw_b = bws(g_num), bws(b_num)
        ncat = np.array([len(dom.categories) for _k, dom, _l in catd],
                        dtype=float)
        w_same = 0.8  # categorical kernel mass on the observed category

        n = max(self.n_candidates, 4 * (len(numd) + len(catd)))
        rng = self.rng
        w_prior = 1.0 / (len(g_num) + 1.0)
        from_prior = rng.uniform(size=n) < w_prior
        pick = rng.integers(0, len(g_num), n)
        # Numeric dims: gaussian around the picked good ROW (whole-vector
        # draws keep cross-dim structure), reflected at the bounds.
        if numd:
            centers = np.where(from_prior[:, None],
                               rng.uniform(0, 1, (n, len(numd))),
                               g_num[pick])
            widths = np.where(from_prior[:, None], 0.25, bw_g[pick])
            cand = centers + rng.normal(0, 1, (n, len(numd))) * widths
            cand = np.abs(cand)
            cand = 1.0 - np.abs(1.0 - cand)
            cand = np.clip(cand, 0.0, 1.0)
        else:
            cand = np.zeros((n, 0))
        if catd:
            keep = rng.uniform(size=(n, len(catd))) < w_same
            rand_cat = np.stack(
                [rng.integers(0, len(dom.categories), n)
                 for _k, dom, _l in catd], axis=1)
            cand_cat = np.where(from_prior[:, None] | ~keep,
                                rand_cat, g_cat[pick])
        else:
            cand_cat = np.zeros((n, 0), dtype=int)

        def log_density(num_mat, cat_mat, bw):
            """log mixture density of each candidate under the set's
            per-observation product kernels (+ uniform prior mixture)."""
            if len(num_mat) == 0:
                return np.zeros(n)
            # [n_cand, n_obs, D] broadcasting; n and n_obs are both small
            # (tens), so the dense intermediate is fine.
            if numd:
                d = (cand[:, None, :] - num_mat[None, :, :]) / bw[None, :, :]
                log_k = (-0.5 * d * d
                         - np.log(bw[None, :, :] * np.sqrt(2 * np.pi))
                         ).sum(axis=2)
            else:
                log_k = np.zeros((n, len(num_mat)))
            if catd:
                same = cand_cat[:, None, :] == cat_mat[None, :, :]
                log_k = log_k + np.where(
                    same, np.log(w_same),
                    np.log((1 - w_same) / np.maximum(ncat - 1, 1.0))
                ).sum(axis=2)
            m = log_k.max(axis=1, keepdims=True)
            kde = m[:, 0] + np.log(
                np.mean(np.exp(log_k - m), axis=1))
            # Uniform prior over the hypercube: density 1 on numeric
            # dims, 1/K per categorical dim.
            log_uniform = -np.log(ncat).sum() if catd else 0.0
            pw = 1.0 / (len(num_mat) + 1.0)
            return np.logaddexp(np.log(pw) + log_uniform,
                                np.log1p(-pw) + kde)

        score = (log_density(g_num, g_cat, bw_g)
                 - log_density(b_num, b_cat, bw_b))
        best = int(np.argmax(score))
        out = dict(fixed)
        for j, (k, nd) in enumerate(numd):
            out[k] = nd.from_unit(float(cand[best, j]))
        for j, (k, dom, _lut) in enumerate(catd):
            out[k] = dom.categories[int(cand_cat[best, j])]
        return out

    def _suggest_numeric(self, dom, good, bad):
        nd = _NumDim(dom)
        gu = np.array([nd.to_unit(v) for v in good])
        bu = np.array([nd.to_unit(v) for v in bad])
        bw_g = _adaptive_bw(gu)
        bw_b = _adaptive_bw(bu)
        # Candidates drawn from l(x) itself — a gaussian around a random
        # good point, or (with the prior's weight) a uniform draw, which
        # is ALL the exploration TPE needs once the prior is a genuine
        # mixture component. Reflect at the bounds instead of clipping: a
        # clip piles an atom of candidates ON the boundary, whose KDE
        # spike then self-selects forever (boundary lock-in).
        n = self.n_candidates
        w_prior = 1.0 / (len(gu) + 1.0)
        from_prior = self.rng.uniform(size=n) < w_prior
        if len(gu):
            pick = self.rng.integers(0, len(gu), n)
            centers = np.where(from_prior, self.rng.uniform(0, 1, n),
                               gu[pick])
            widths = np.where(from_prior, 0.25, bw_g[pick])
        else:
            centers = self.rng.uniform(0, 1, n)
            widths = np.full(n, 0.25)
        cand = centers + self.rng.normal(0, 1, n) * widths
        cand = np.abs(cand)
        cand = 1.0 - np.abs(1.0 - cand)
        cand = np.clip(cand, 0.0, 1.0)
        score = _parzen_logpdf(cand, gu, bw_g) - _parzen_logpdf(cand, bu, bw_b)
        return nd.from_unit(float(cand[int(np.argmax(score))]))

    def _suggest_categorical(self, dom: Choice, good, bad):
        cats = dom.categories
        idx = {self._cat_key(c): i for i, c in enumerate(cats)}
        g = np.ones(len(cats))
        b = np.ones(len(cats))
        for v in good:
            i = idx.get(self._cat_key(v))
            if i is not None:
                g[i] += 1
        for v in bad:
            i = idx.get(self._cat_key(v))
            if i is not None:
                b[i] += 1
        ratio = (g / g.sum()) / (b / b.sum())
        probs = ratio / ratio.sum()
        return cats[int(self.rng.choice(len(cats), p=probs))]

    @staticmethod
    def _cat_key(v):
        try:
            return hash(v)
        except TypeError:
            return repr(v)

    # -- tell --------------------------------------------------------------

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        pass  # TPE learns from final results only

    def on_trial_complete(self, trial_id: str, result: Optional[dict] = None,
                          error: bool = False) -> None:
        flat = self._live.pop(trial_id, None)
        if flat is None or error:
            return
        score = self._score(result)
        if score is not None and np.isfinite(score):
            self._obs.append((flat, score))

    def tell(self, config: Dict[str, Any],
             result: Optional[dict]) -> None:
        score = self._score(result)
        if score is not None and np.isfinite(score):
            self._obs.append((_flatten(config), score))


class BOHBSearcher(TPESearcher):
    """BOHB's model-based half (Falkner et al. 2018; the reference wires
    it as ``search/bohb/TuneBOHB`` + ``HyperBandForBOHB``): TPE
    suggestions fit on observations grouped by BUDGET (training
    iteration at which the score was reported), always modeling the
    LARGEST budget that has enough observations — early-rung data guides
    the search until enough high-budget results exist, then the model
    upgrades to the fidelity that matters. Pair it with
    ``HyperBandScheduler`` (the successive-halving rungs produce exactly
    the multi-fidelity observations this models).

    Unlike plain TPE (final results only), intermediate results feed the
    model: every ``on_trial_result`` records (config, score) at that
    budget, keeping the freshest score per trial per budget.
    """

    def __init__(self, *args, min_points_in_model: int = 8, **kwargs):
        super().__init__(*args, **kwargs)
        self.min_points_in_model = min_points_in_model
        # budget -> {trial_id: (flat_cfg, score)}
        self._by_budget: Dict[int, Dict[str, tuple]] = {}

    def _record(self, trial_id: str, result: dict) -> None:
        flat = self._live.get(trial_id)
        score = self._score(result)
        if flat is None or score is None or not np.isfinite(score):
            return
        budget = int(result.get("training_iteration", 1))
        self._by_budget.setdefault(budget, {})[trial_id] = (flat, score)

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        self._record(trial_id, result)

    def on_trial_complete(self, trial_id: str, result: Optional[dict] = None,
                          error: bool = False) -> None:
        if result is not None and not error:
            self._record(trial_id, result)
        self._live.pop(trial_id, None)

    def _refresh_obs(self) -> None:
        """Point self._obs at the largest budget with enough points
        (falling back to pooling everything when no budget qualifies)."""
        best_budget = None
        for budget in sorted(self._by_budget, reverse=True):
            if len(self._by_budget[budget]) >= self.min_points_in_model:
                best_budget = budget
                break
        if best_budget is not None:
            self._obs = list(self._by_budget[best_budget].values())
        else:
            pooled: Dict[str, tuple] = {}
            for budget in sorted(self._by_budget):  # highest budget wins
                pooled.update(self._by_budget[budget])
            self._obs = list(pooled.values())

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        self._refresh_obs()
        return super().suggest(trial_id)

    def tell(self, config: Dict[str, Any], result: Optional[dict]) -> None:
        score = self._score(result)
        if score is not None and np.isfinite(score):
            budget = int((result or {}).get("training_iteration", 1))
            pool = self._by_budget.setdefault(budget, {})
            # Budget-qualified key: the pooled fallback in _refresh_obs
            # merges budget dicts by key, so bare counters would collide
            # across budgets and drop distinct observations.
            pool[f"told-b{budget}-{len(pool)}"] = (_flatten(config), score)
