"""Tune: distributed hyperparameter search over trial actors.

Reference parity: ``python/ray/tune`` (SURVEY.md §2.3) — search spaces,
variant generation, trial runner over actors with per-trial resources,
ASHA / median-stopping / PBT schedulers, per-trial checkpoints + retries.
"""

from ray_tpu._private.usage import record_library_usage as _rlu
_rlu("tune")

from ray_tpu.train import session as _session
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.search import (
    BasicVariantSearcher,
    BOHBSearcher,
    Searcher,
    TPESearcher,
)
from ray_tpu.tune.search_space import (
    choice,
    grid_search,
    loguniform,
    quniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.stopper import (
    CombinedStopper,
    FunctionStopper,
    MaximumIterationStopper,
    Stopper,
    TrialPlateauStopper,
)
from ray_tpu.tune.trial_runner import Trial, TrialRunner
from ray_tpu.tune.tuner import (ResultGrid, TrialResult, TuneConfig,
                                Tuner, run, with_resources)


def report(metrics: dict | None = None, *, checkpoint: Checkpoint | None = None,
           **kwargs) -> None:
    """``tune.report``: accepts a dict or keyword metrics."""
    payload = dict(metrics or {})
    payload.update(kwargs)
    _session.report(payload, checkpoint=checkpoint)


def get_checkpoint() -> Checkpoint | None:
    return _session.get_checkpoint()


def get_trial_id() -> str | None:
    info = _session.get_trial_info()
    return info["trial_id"] if info else None


__all__ = [
    "Stopper",
    "MaximumIterationStopper",
    "TrialPlateauStopper",
    "FunctionStopper",
    "CombinedStopper",
    "with_resources",
    "Tuner",
    "TuneConfig",
    "ResultGrid",
    "TrialResult",
    "Trial",
    "TrialRunner",
    "run",
    "report",
    "get_checkpoint",
    "get_trial_id",
    "uniform",
    "quniform",
    "loguniform",
    "randint",
    "choice",
    "grid_search",
    "sample_from",
    "TrialScheduler",
    "FIFOScheduler",
    "ASHAScheduler",
    "BOHBSearcher",
    "HyperBandScheduler",
    "MedianStoppingRule",
    "PopulationBasedTraining",
    "Searcher",
    "BasicVariantSearcher",
    "TPESearcher",
    "Checkpoint",
]
