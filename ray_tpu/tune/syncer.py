"""Experiment-directory syncing to remote storage.

Reference parity: ``python/ray/tune/syncer.py:185`` (``Syncer`` — the
abstraction that mirrors a local experiment directory to cloud/NFS
storage so experiments survive node loss and restore anywhere) and the
``storage_path`` URI handling in air/tune. Here: a ``Syncer`` base with
an incremental local/``file://`` implementation and an ``s3://`` stub
gated on boto3 (not shipped in this image); ``get_syncer`` dispatches on
the URI scheme. ``RunConfig(storage_path="file://...")`` routes Tuner
persistence through a local mirror + sync-up, and ``Tuner.restore`` on a
URI syncs down first.
"""

from __future__ import annotations

import os
import shutil
import time


class Syncer:
    """Mirror a local directory to/from a remote URI."""

    #: Minimum seconds between periodic sync_up calls (final sync always
    #: runs).
    sync_period: float = 5.0

    def sync_up(self, local_dir: str, remote_uri: str) -> bool:
        raise NotImplementedError

    def sync_down(self, remote_uri: str, local_dir: str) -> bool:
        raise NotImplementedError

    def delete(self, remote_uri: str) -> bool:
        raise NotImplementedError

    def wait(self) -> None:
        """Block until any async sync completes (base impl is sync)."""


def _mirror(src: str, dst: str) -> None:
    """Incremental one-way mirror: copy files that are missing or newer
    (mtime+size) at the destination. Deletions do NOT propagate — an
    interrupted experiment must never erase its remote history."""
    os.makedirs(dst, exist_ok=True)
    for root, _dirs, files in os.walk(src):
        rel = os.path.relpath(root, src)
        out_root = os.path.join(dst, rel) if rel != "." else dst
        os.makedirs(out_root, exist_ok=True)
        for f in files:
            s = os.path.join(root, f)
            d = os.path.join(out_root, f)
            try:
                st = os.stat(s)
            except OSError:
                continue  # racing writer: next sync gets it
            if os.path.exists(d):
                dt = os.stat(d)
                if dt.st_mtime >= st.st_mtime and dt.st_size == st.st_size:
                    continue
            tmp = d + ".sync_tmp"
            shutil.copy2(s, tmp)
            os.replace(tmp, d)  # atomic: restorers never see partials


class FileSyncer(Syncer):
    """``file://`` / plain-path syncer (NFS mounts look like this too)."""

    @staticmethod
    def _path(uri: str) -> str:
        return uri[len("file://"):] if uri.startswith("file://") else uri

    def sync_up(self, local_dir: str, remote_uri: str) -> bool:
        _mirror(local_dir, self._path(remote_uri))
        return True

    def sync_down(self, remote_uri: str, local_dir: str) -> bool:
        remote = self._path(remote_uri)
        if not os.path.isdir(remote):
            return False
        _mirror(remote, local_dir)
        return True

    def delete(self, remote_uri: str) -> bool:
        shutil.rmtree(self._path(remote_uri), ignore_errors=True)
        return True


class S3Syncer(Syncer):
    """``s3://`` syncer. Requires boto3 (not baked into this image): the
    constructor raises a clear error when it's absent, so experiments
    fail at configuration time rather than mid-run."""

    def __init__(self):
        try:
            import boto3  # noqa: F401
        except ImportError as e:
            raise RuntimeError(
                "s3:// storage_path requires boto3, which is not "
                "installed in this environment; use file:// or a plain "
                "path (NFS) instead"
            ) from e
        import boto3

        self._s3 = boto3.client("s3")

    @staticmethod
    def _bucket_key(uri: str):
        rest = uri[len("s3://"):]
        bucket, _, prefix = rest.partition("/")
        return bucket, prefix.rstrip("/")

    def sync_up(self, local_dir: str, remote_uri: str) -> bool:
        bucket, prefix = self._bucket_key(remote_uri)
        for root, _dirs, files in os.walk(local_dir):
            rel = os.path.relpath(root, local_dir)
            for f in files:
                key = "/".join(
                    p for p in (prefix, "" if rel == "." else rel, f) if p)
                self._s3.upload_file(os.path.join(root, f), bucket, key)
        return True

    def sync_down(self, remote_uri: str, local_dir: str) -> bool:
        bucket, prefix = self._bucket_key(remote_uri)
        paginator = self._s3.get_paginator("list_objects_v2")
        found = False
        for page in paginator.paginate(Bucket=bucket, Prefix=prefix):
            for obj in page.get("Contents", []):
                found = True
                rel = obj["Key"][len(prefix):].lstrip("/")
                dest = os.path.join(local_dir, rel)
                os.makedirs(os.path.dirname(dest), exist_ok=True)
                self._s3.download_file(bucket, obj["Key"], dest)
        return found

    def delete(self, remote_uri: str) -> bool:
        bucket, prefix = self._bucket_key(remote_uri)
        paginator = self._s3.get_paginator("list_objects_v2")
        for page in paginator.paginate(Bucket=bucket, Prefix=prefix):
            keys = [{"Key": o["Key"]} for o in page.get("Contents", [])]
            if keys:
                self._s3.delete_objects(
                    Bucket=bucket, Delete={"Objects": keys})
        return True


_SCHEMES = {"file": FileSyncer, "s3": S3Syncer}


def register_syncer(scheme: str, cls) -> None:
    _SCHEMES[scheme] = cls


def is_remote_uri(path: str) -> bool:
    scheme, sep, _ = path.partition("://")
    return bool(sep) and scheme in _SCHEMES


def get_syncer(uri: str) -> Syncer:
    scheme, sep, _ = uri.partition("://")
    if not sep:
        return FileSyncer()
    try:
        cls = _SCHEMES[scheme]
    except KeyError:
        raise ValueError(
            f"no syncer registered for scheme {scheme!r} "
            f"(known: {sorted(_SCHEMES)})") from None
    return cls()


class _PeriodicSync:
    """Throttled sync-up helper the TrialRunner drives after each
    persisted snapshot; ``final()`` always syncs."""

    def __init__(self, syncer: Syncer, local_dir: str, uri: str):
        self.syncer = syncer
        self.local_dir = local_dir
        self.uri = uri
        self._last = 0.0

    def maybe_sync(self) -> None:
        now = time.monotonic()
        if now - self._last >= self.syncer.sync_period:
            self._last = now
            self.syncer.sync_up(self.local_dir, self.uri)

    def final(self) -> None:
        self.syncer.sync_up(self.local_dir, self.uri)
