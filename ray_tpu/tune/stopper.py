"""Stoppers: declarative trial-stop conditions (reference
``python/ray/tune/stopper/``). Attach via ``RunConfig(stop=...)`` — a
Stopper instance, a ``{metric: threshold}`` dict (stop when
``result[metric] >= threshold``), or a callable
``(trial_id, result) -> bool``."""

from __future__ import annotations

import collections
import statistics
from typing import Callable, Dict


class Stopper:
    def __call__(self, trial_id: str, result: dict) -> bool:
        raise NotImplementedError

    def stop_all(self) -> bool:
        """True = terminate the whole experiment, not just one trial."""
        return False


class MaximumIterationStopper(Stopper):
    def __init__(self, max_iter: int):
        self._max_iter = max_iter

    def __call__(self, trial_id: str, result: dict) -> bool:
        return result.get("training_iteration", 0) >= self._max_iter


class TrialPlateauStopper(Stopper):
    """Stop a trial whose metric stopped moving: std of the last
    ``num_results`` values <= ``std`` (reference trial_plateau shape)."""

    def __init__(self, metric: str, *, std: float = 0.01,
                 num_results: int = 4, grace_period: int = 4):
        self._metric = metric
        self._std = std
        self._num_results = num_results
        self._grace = grace_period
        self._window: Dict[str, collections.deque] = {}
        self._count: Dict[str, int] = {}

    def __call__(self, trial_id: str, result: dict) -> bool:
        if self._metric not in result:
            return False
        w = self._window.setdefault(
            trial_id, collections.deque(maxlen=self._num_results))
        w.append(float(result[self._metric]))
        self._count[trial_id] = self._count.get(trial_id, 0) + 1
        if self._count[trial_id] < self._grace or \
                len(w) < self._num_results:
            return False
        return statistics.pstdev(w) <= self._std


class FunctionStopper(Stopper):
    def __init__(self, fn: Callable[[str, dict], bool]):
        self._fn = fn

    def __call__(self, trial_id: str, result: dict) -> bool:
        return bool(self._fn(trial_id, result))


class CombinedStopper(Stopper):
    def __init__(self, *stoppers: Stopper):
        self._stoppers = stoppers

    def __call__(self, trial_id: str, result: dict) -> bool:
        return any(s(trial_id, result) for s in self._stoppers)

    def stop_all(self) -> bool:
        return any(s.stop_all() for s in self._stoppers)


def coerce_stopper(stop) -> Stopper | None:
    """RunConfig(stop=...) accepts Stopper | dict | callable | None."""
    if stop is None or isinstance(stop, Stopper):
        return stop
    if isinstance(stop, dict):
        conditions = dict(stop)

        def check(_tid, result):
            return any(
                m in result and result[m] >= v
                for m, v in conditions.items()
            )

        return FunctionStopper(check)
    if callable(stop):
        return FunctionStopper(stop)
    raise TypeError(f"stop must be a Stopper, dict, or callable; got "
                    f"{type(stop).__name__}")
