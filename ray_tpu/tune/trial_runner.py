"""Trial + TrialRunner: the Tune execution event loop.

Reference parity: ``python/ray/tune/execution/trial_runner.py:319,961`` —
the step loop asks the variant generator for configs, starts trials as
actors (``RayTrialExecutor``), consumes reported results, applies
scheduler decisions (ASHA stop / PBT exploit), retries failed trials from
their last checkpoint, and tracks per-trial checkpoints.

Function trainables run the user function inside the trial actor and
report through the shared queue (``trainable/function_trainable.py:126``).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core.object_ref import ActorError, TaskError
from ray_tpu.train import session as session_mod
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.tune.schedulers import CONTINUE, STOP, FIFOScheduler
from ray_tpu.util.queue import Queue

PENDING = "PENDING"
RUNNING = "RUNNING"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


class Trial:
    _counter = 0

    def __init__(self, config: dict, resources: Optional[dict] = None):
        Trial._counter += 1
        self.trial_id = f"trial_{Trial._counter:05d}_{os.urandom(2).hex()}"
        self.config = dict(config)
        # Flat dict ({"CPU": 1}) or a gang spec
        # ({"bundles": [{...}, ...], "strategy": "PACK"}): gang trials
        # reserve a placement group atomically, so two multi-bundle
        # trials can never deadlock each other by each grabbing half
        # (reference: tune/execution/placement_groups.py
        # PlacementGroupFactory).
        self.resources = resources or {"CPU": 1}
        self.pg = None  # PlacementGroup handle for gang trials
        self.status = PENDING
        self.last_result: Optional[dict] = None
        self.metrics_history: List[dict] = []
        self.checkpoint: Optional[Checkpoint] = None
        self.error: Optional[BaseException] = None
        self.num_failures = 0
        self.generation = 0  # bumped on restart; stale reports are dropped
        self.actor = None
        self.run_ref = None
        self.version = 0  # monotonic dirty counter, see __setattr__
        # Downtime ledger (the trainer's accounting, shared
        # implementation): opened when an attempt fails, closed at the
        # restarted attempt's first accepted report.
        from ray_tpu.util.goodput import GoodputLedger

        self.ledger = GoodputLedger(self.trial_id)

    def mark_down(self, cause: str) -> None:
        self.ledger.mark_down(cause)

    def close_downtime(self) -> None:
        self.ledger.mark_progress()

    def goodput(self) -> dict:
        """Per-trial goodput % — a NON-mutating read (an open downtime
        interval shows in the view but stays open for the eventual
        recovery to attribute)."""
        return self.ledger.snapshot()

    # Persisted fields bump a monotonic version so the snapshot change
    # signature never relies on id() — a fresh object at a GC-reused
    # address would otherwise compare equal and skip a real state change
    # (advisor r4).
    _VERSIONED = frozenset(
        {"status", "last_result", "checkpoint", "num_failures", "error"})

    def __setattr__(self, name, value):
        object.__setattr__(self, name, value)
        if name in Trial._VERSIONED:
            object.__setattr__(self, "version",
                               getattr(self, "version", 0) + 1)

    def __repr__(self):
        return f"Trial({self.trial_id}, {self.status})"


class _TrialActor:
    """Actor hosting one trial's function trainable."""

    def run(self, train_fn, config, session_kwargs):
        session_mod.init_session(**session_kwargs)
        try:
            train_fn(config)
        finally:
            q = session_kwargs["results_queue"]
            q.put({
                "type": "finished",
                "trial_info": session_kwargs.get("trial_info"),
            })
            session_mod.shutdown_session()
        return True


class TrialRunner:
    def __init__(
        self,
        trainable: Callable,
        trials: List[Trial],
        *,
        scheduler=None,
        max_concurrent: int = 8,
        max_failures: int = 0,
        stopper=None,
        searcher=None,
        num_samples: int = 0,
        trial_resources: Optional[dict] = None,
        experiment_dir: Optional[str] = None,
        sync=None,
    ):
        self.trainable = trainable
        self.trials = trials
        self.by_id: Dict[str, Trial] = {t.trial_id: t for t in trials}
        self.scheduler = scheduler or FIFOScheduler()
        self.max_concurrent = max_concurrent
        self.max_failures = max_failures
        self.stopper = stopper  # RunConfig(stop=...) condition
        # Ask/tell search (reference searcher.py:21): when set, trials are
        # created lazily — suggest() sees completed results before it
        # proposes the next config (model-based search needs that).
        self.searcher = searcher
        self.num_samples = num_samples
        self.trial_resources = trial_resources
        # Experiment persistence (reference experiment_state snapshots):
        # a changed trial state rewrites <dir>/experiment_state.json so
        # Tuner.restore can resume unfinished trials after a crash.
        self.experiment_dir = experiment_dir
        # Optional syncer driver (tune/syncer.py _PeriodicSync): pushes
        # the persisted experiment dir to remote storage, throttled
        # during the run + unconditionally at the end.
        self.sync = sync
        self.experiment_meta: dict = {}  # metric/mode etc., persisted too
        self._persisted_sig = None
        # Pinned to the driver's node: the shared results queue riding a
        # node a drain/preemption takes would masquerade as a
        # drain-caused failure of EVERY trial wired to it — retried
        # exempt, forever (see queue.driver_node_options).
        from ray_tpu.util.queue import driver_node_options

        self.queue = Queue(actor_options=driver_node_options())
        self._actor_cls = ray_tpu.remote(_TrialActor)

    # -- experiment persistence -------------------------------------------

    @staticmethod
    def _json_default(o):
        import numpy as np

        if isinstance(o, np.generic):
            return o.item()
        raise TypeError(type(o).__name__)

    def _persist(self) -> None:
        if not self.experiment_dir:
            return
        sig = tuple((t.trial_id, t.version) for t in self.trials)
        if sig == self._persisted_sig:
            return  # nothing changed since the last snapshot
        import json
        import pickle

        os.makedirs(self.experiment_dir, exist_ok=True)
        records = []
        for t in self.trials:
            ckpt_file = None
            if t.checkpoint is not None:
                ckpt_file = os.path.join(
                    self.experiment_dir, f"ckpt_{t.trial_id}.pkl")
                if getattr(t, "_persisted_ckpt", None) is t.checkpoint \
                        and os.path.exists(ckpt_file):
                    pass  # unchanged since last snapshot
                else:
                    try:
                        with open(ckpt_file + ".tmp", "wb") as f:
                            pickle.dump(t.checkpoint.to_dict(), f)
                        os.replace(ckpt_file + ".tmp", ckpt_file)
                        t._persisted_ckpt = t.checkpoint
                    except Exception:
                        ckpt_file = None  # unserializable (e.g. dead ref)
            rec = {
                "trial_id": t.trial_id,
                "config": t.config,
                "status": t.status,
                "last_result": t.last_result,
                "num_failures": t.num_failures,
                "checkpoint_file": ckpt_file,
                "resources": t.resources,
                "error": repr(t.error) if t.error is not None else None,
            }
            try:
                json.dumps(rec, default=self._json_default)
            except TypeError:
                # Exotic values (beyond numpy scalars) can't round-trip:
                # mark the record so restore refuses to re-run it with a
                # corrupted config instead of silently stringifying.
                rec["config"] = repr(t.config)
                rec["last_result"] = None
                rec["lossy"] = True
            records.append(rec)
        tmp = os.path.join(self.experiment_dir, "experiment_state.tmp")
        with open(tmp, "w") as f:
            json.dump({"trials": records, "meta": self.experiment_meta},
                      f, default=self._json_default)
        os.replace(
            tmp, os.path.join(self.experiment_dir,
                              "experiment_state.json"))
        self._persisted_sig = sig
        if self.sync is not None:
            try:
                self.sync.maybe_sync()
            except Exception:
                pass  # remote hiccup must not kill the experiment

    def _maybe_create_trial(self) -> Optional[Trial]:
        if self.searcher is None:
            return None
        # Variant-expanding searchers (grid x num_samples) own their trial
        # budget: run them until suggest() returns None. Capping those at
        # num_samples would silently drop grid variants (advisor r4).
        if (not getattr(self.searcher, "expands_variants", False)
                and len(self.trials) >= self.num_samples):
            return None
        trial = Trial({}, self.trial_resources)
        cfg = self.searcher.suggest(trial.trial_id)
        if cfg is None:
            return None  # exhausted, or waiting on results
        trial.config = cfg
        self.trials.append(trial)
        self.by_id[trial.trial_id] = trial
        return trial

    # -- lifecycle of one trial -------------------------------------------

    def _start_trial(self, trial: Trial) -> bool:
        """Try to start a trial. Returns False when its gang placement
        group is not reserved yet (the event loop retries on its next
        tick — starting must never block the loop, or a finished trial's
        PG removal could never be processed: deadlock)."""
        bundles = trial.resources.get("bundles")
        opts: dict
        if bundles:
            from ray_tpu.util.placement_group import (
                placement_group,
                placement_group_table,
                remove_placement_group,
            )
            from ray_tpu.util.scheduling_strategies import (
                PlacementGroupSchedulingStrategy,
            )

            if trial.pg is None:
                trial.pg = placement_group(
                    bundles, trial.resources.get("strategy", "PACK"),
                    name=trial.trial_id,
                )
            state = (placement_group_table(trial.pg) or {}).get("state")
            if state == "INFEASIBLE":
                remove_placement_group(trial.pg)
                trial.pg = None
                trial.status = ERROR
                trial.error = ValueError(
                    f"trial gang {bundles} is infeasible on this cluster"
                )
                return True  # handled (terminally)
            if state != "CREATED":
                return False  # PG pending: the event loop retries
            # Demand exactly what bundle 0 provides (default 0, not 1: a
            # CPU-less bundle, e.g. TPU-only, could never grant CPU).
            opts = {
                "num_cpus": bundles[0].get("CPU", 0),
                "scheduling_strategy": PlacementGroupSchedulingStrategy(
                    placement_group=trial.pg,
                    placement_group_bundle_index=0,
                    placement_group_capture_child_tasks=True,
                ),
            }
            if bundles[0].get("TPU"):
                opts["num_tpus"] = bundles[0]["TPU"]
        else:
            opts = {"num_cpus": trial.resources.get("CPU", 1)}
            if trial.resources.get("TPU"):
                opts["num_tpus"] = trial.resources["TPU"]
        trial.generation += 1
        session_kwargs = {
            "world_rank": 0,
            "world_size": 1,
            "local_rank": 0,
            "node_rank": 0,
            "results_queue": self.queue,
            "checkpoint": trial.checkpoint,
            "dataset_shards": {},
            "trial_info": {
                "trial_id": trial.trial_id,
                "generation": trial.generation,
                "config": trial.config,
            },
        }
        trial.actor = self._actor_cls.options(**opts).remote()
        trial.run_ref = trial.actor.run.remote(
            self.trainable, trial.config, session_kwargs
        )
        trial.status = RUNNING
        return True

    def _stop_actor(self, trial: Trial, keep_pg: bool = False):
        if trial.actor is not None:
            try:
                ray_tpu.kill(trial.actor)
            except Exception:
                pass
        trial.actor = None
        trial.run_ref = None
        if trial.pg is not None and not keep_pg:
            # Release the gang reservation so the next pending trial's
            # placement group can commit. Drain/preemption-exempt
            # restarts KEEP it: the head is migrating its bundles
            # (RESCHEDULING -> CREATED on healthy nodes), and the
            # retried trial re-enters the same reservation instead of
            # re-queuing a fresh gang behind everyone else.
            from ray_tpu.util.placement_group import remove_placement_group

            try:
                remove_placement_group(trial.pg)
            except Exception:
                pass
            trial.pg = None

    def _pbt_exploit(self, trial: Trial, donor_id: str, scheduler) -> None:
        """Exploit+explore: adopt a perturbed copy of the donor's config and
        restart from the donor's checkpoint (``pbt.py`` _exploit)."""
        donor = self.by_id.get(donor_id)
        if donor is None or donor.checkpoint is None:
            return
        self._stop_actor(trial)
        trial.config = scheduler.perturb_config(donor.config)
        trial.checkpoint = donor.checkpoint
        trial.status = PENDING  # the event loop restarts it

    # -- event loop --------------------------------------------------------

    def run(self) -> List[Trial]:
        try:
            while True:
                running = [t for t in self.trials if t.status == RUNNING]
                pending = [t for t in self.trials if t.status == PENDING]
                # A gang trial waiting on its PG occupies a concurrency
                # slot too — otherwise every pending trial would create
                # (and possibly commit) a PG up front, hoarding cluster
                # resources far beyond max_concurrent.
                slots = len(running)
                while (slots + len(pending) < self.max_concurrent):
                    t = self._maybe_create_trial()
                    if t is None:
                        break
                    pending.append(t)
                for t in pending:
                    if slots >= self.max_concurrent:
                        break
                    started = self._start_trial(t)
                    if started and t.status == RUNNING:
                        running.append(t)
                        slots += 1
                    elif not started:
                        slots += 1  # PG pending: holds its slot
                if not running and not any(
                        t.status == PENDING for t in self.trials):
                    # With a searcher, idle + no new suggestion means the
                    # search is exhausted (suggest() already saw every
                    # completed result).
                    break
                self._drain_queue()
                self._poll_completions()
                self._persist()
        finally:
            self._persist()
            if self.sync is not None:
                try:
                    self.sync.final()
                except Exception:
                    pass
            for t in self.trials:
                self._stop_actor(t)
            self.queue.shutdown()
        return self.trials

    def _notify_searcher_complete(self, trial, result, error=False):
        if self.searcher is not None:
            self.searcher.on_trial_complete(
                trial.trial_id, result, error=error)

    def _drain_queue(self):
        try:
            msg = self.queue.get(timeout=0.2)
        except Exception:
            return
        while True:
            self._handle_message(msg)
            try:
                msg = self.queue.get(block=False)
            except Exception:
                return

    def _handle_message(self, msg: dict):
        info = msg.get("trial_info") or {}
        trial = self.by_id.get(info.get("trial_id", ""))
        if trial is None or msg["type"] != "report":
            return
        if info.get("generation") != trial.generation or trial.status != RUNNING:
            return  # stale report from a superseded attempt
        trial.close_downtime()  # a report proves progress again
        result = dict(msg["metrics"])
        result.setdefault("training_iteration", msg["iteration"])
        trial.last_result = result
        trial.metrics_history.append(result)
        if self.searcher is not None:
            self.searcher.on_trial_result(trial.trial_id, result)
        if msg["checkpoint"] is not None:
            trial.checkpoint = msg["checkpoint"]
        if self.stopper is not None and self.stopper(
                trial.trial_id, result):
            self._stop_actor(trial)
            trial.status = TERMINATED
            self.scheduler.on_trial_complete(self, trial, result)
            self._notify_searcher_complete(trial, result)
            if self.stopper.stop_all():
                for t in self.trials:
                    if t.status in (RUNNING, PENDING):
                        self._stop_actor(t)
                        t.status = TERMINATED
                        self.scheduler.on_trial_complete(
                            self, t, t.last_result or {})
                        self._notify_searcher_complete(t, t.last_result)
            return
        decision = self.scheduler.on_trial_result(self, trial, result)
        if decision == STOP:
            self._stop_actor(trial)
            trial.status = TERMINATED
            self.scheduler.on_trial_complete(self, trial, result)
            self._notify_searcher_complete(trial, result)

    def _drain_all_nowait(self):
        while True:
            try:
                msg = self.queue.get(block=False)
            except Exception:
                return
            self._handle_message(msg)

    def _poll_completions(self):
        for trial in self.trials:
            if trial.status != RUNNING or trial.run_ref is None:
                continue
            ready, _ = ray_tpu.wait([trial.run_ref], num_returns=1, timeout=0)
            if not ready:
                continue
            # All of this attempt's reports were enqueued before run()
            # returned — apply them before completing the trial.
            self._drain_all_nowait()
            if trial.status != RUNNING:
                continue  # a drained report stopped it
            try:
                ray_tpu.get(trial.run_ref)
            except (ActorError, TaskError) as e:
                from ray_tpu.util import goodput as _goodput

                cause = _goodput.downtime_cause(e)
                trial.mark_down(cause)
                # Retry-budget exemption, extended from actors to gangs
                # (the PR-2 discipline): a trial lost to a planned
                # drain / preemption restarts WITHOUT consuming
                # max_failures, and a gang trial keeps its placement
                # group — the head is rescheduling its bundles onto
                # healthy nodes, so the retry waits for the SAME
                # reservation to come back instead of burning it.
                exempt = cause == "preemption" or cause.startswith("drain")
                if not exempt:
                    trial.num_failures += 1
                if exempt or trial.num_failures <= self.max_failures:
                    # Retry from the last checkpoint; back to PENDING so
                    # the event loop restarts it (a gang trial may need
                    # to wait for its new PG without blocking the loop).
                    self._stop_actor(trial, keep_pg=exempt)
                    trial.status = PENDING
                    continue
                trial.status = ERROR
                trial.error = e
                self._stop_actor(trial)
                self.scheduler.on_trial_complete(self, trial, None)
                self._notify_searcher_complete(trial, None, error=True)
                continue
            trial.status = TERMINATED
            self._stop_actor(trial)
            self.scheduler.on_trial_complete(self, trial, trial.last_result)
            self._notify_searcher_complete(trial, trial.last_result)
