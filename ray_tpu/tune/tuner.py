"""Tuner / tune.run: the user-facing experiment API.

Reference parity: ``python/ray/tune/tuner.py:44,239`` (Tuner.fit ->
ResultGrid), ``tune/tune.py:131`` (tune.run), with trainers runnable as
trainables (``Trainer.fit`` wraps itself into a 1-trial experiment,
``train/base_trainer.py:339-363`` — here the composition goes the other
way: a Tuner can run a DataParallelTrainer factory per trial).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig
from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.search_space import generate_variants
from ray_tpu.tune.trial_runner import ERROR, TERMINATED, Trial, TrialRunner


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 8
    scheduler: Optional[TrialScheduler] = None
    # Ask/tell search algorithm (reference tune.TuneConfig.search_alg);
    # None = the BasicVariant grid x random expansion.
    search_alg: Optional[Any] = None
    seed: Optional[int] = None


@dataclass
class TrialResult:
    trial_id: str
    config: dict
    metrics: Optional[dict]
    checkpoint: Optional[Checkpoint]
    error: Optional[BaseException]
    metrics_history: List[dict] = field(default_factory=list)


class ResultGrid:
    def __init__(self, results: List[TrialResult],
                 default_metric: Optional[str], default_mode: str):
        self._results = results
        self._metric = default_metric
        self._mode = default_mode

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i):
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> List[BaseException]:
        return [r.error for r in self._results if r.error is not None]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> TrialResult:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (none set in TuneConfig)")
        candidates = [
            r for r in self._results
            if r.metrics is not None and metric in r.metrics
        ]
        if not candidates:
            raise ValueError(f"no trial reported metric {metric!r}")
        key = lambda r: r.metrics[metric]
        return max(candidates, key=key) if mode == "max" else min(candidates, key=key)

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for r in self._results:
            row = {"trial_id": r.trial_id, **{f"config/{k}": v for k, v in r.config.items()}}
            row.update(r.metrics or {})
            rows.append(row)
        return pd.DataFrame(rows)


class Tuner:
    def __init__(
        self,
        trainable: Callable,
        *,
        param_space: Optional[Dict[str, Any]] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config: Optional[RunConfig] = None,
        resources_per_trial: Optional[dict] = None,
    ):
        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self.resources_per_trial = resources_per_trial
        self._restored_trials: Optional[List[Trial]] = None

    def experiment_dir(self) -> Optional[str]:
        """Where experiment state snapshots live LOCALLY (None = no
        persistence): RunConfig(storage_path=...)/[name]. A remote
        ``storage_path`` URI (``file://``, ``s3://``) persists to a local
        mirror that the Syncer pushes up (reference tune/syncer.py:185)."""
        local, _uri = self._storage()
        return local

    def _storage(self):
        """(local_experiment_dir, remote_uri_or_None)."""
        import hashlib
        import os

        sp = self.run_config.storage_path
        if not sp:
            return None, None
        name = self.run_config.name or "experiment"
        from ray_tpu.tune.syncer import is_remote_uri

        if is_remote_uri(sp):
            uri = sp.rstrip("/") + "/" + name
            mirror = os.path.join(
                os.path.expanduser("~/.ray_tpu/mirrors"),
                hashlib.sha1(uri.encode()).hexdigest()[:12], name)
            return mirror, uri
        return os.path.join(sp, name), None

    @classmethod
    def restore(cls, path: str, trainable: Callable,
                *, param_space: Optional[Dict[str, Any]] = None,
                tune_config: Optional[TuneConfig] = None,
                run_config: Optional[RunConfig] = None,
                resources_per_trial: Optional[dict] = None) -> "Tuner":
        """Resume a crashed/interrupted experiment from its state
        snapshots (reference ``Tuner.restore(path, trainable)``):
        finished trials keep their results, errored trials keep their
        error, unfinished ones restart from their last persisted
        checkpoint when ``fit()`` is called. Pass ``tune_config`` to
        continue a search_alg-driven experiment (un-suggested samples are
        generated with the restored results replayed into the searcher);
        metric/mode default to the persisted values."""
        import copy
        import json
        import os
        import pickle

        from ray_tpu.tune.syncer import get_syncer, is_remote_uri

        restore_uri = None
        if is_remote_uri(path):
            # Pull the synced experiment down into the deterministic
            # mirror dir, then restore from there; fit() keeps syncing
            # up to the same URI.
            import hashlib

            restore_uri = path.rstrip("/")
            name = os.path.basename(restore_uri)
            local = os.path.join(
                os.path.expanduser("~/.ray_tpu/mirrors"),
                hashlib.sha1(restore_uri.encode()).hexdigest()[:12], name)
            if not get_syncer(restore_uri).sync_down(restore_uri, local):
                raise FileNotFoundError(
                    f"nothing to restore at {restore_uri}")
            path = local
        else:
            path = os.path.abspath(path)
        state_file = os.path.join(path, "experiment_state.json")
        with open(state_file) as f:
            state = json.load(f)
        from ray_tpu.tune.trial_runner import ERROR, PENDING, TERMINATED

        trials: List[Trial] = []
        for rec in state["trials"]:
            t = Trial(rec["config"] if not rec.get("lossy") else {},
                      rec.get("resources"))
            t.trial_id = rec["trial_id"]
            t.last_result = rec.get("last_result")
            if t.last_result:
                t.metrics_history = [t.last_result]
            t.num_failures = rec.get("num_failures", 0)
            ckpt_file = rec.get("checkpoint_file")
            if ckpt_file and os.path.exists(ckpt_file):
                with open(ckpt_file, "rb") as f:
                    t.checkpoint = Checkpoint.from_dict(pickle.load(f))
            status = rec.get("status")
            if status == ERROR or rec.get("lossy"):
                # Keep the failure (or the un-round-trippable config)
                # visible instead of re-running or masquerading as done.
                t.status = ERROR
                t.error = RuntimeError(
                    rec.get("error")
                    or "config could not be restored losslessly")
            elif status == TERMINATED:
                t.status = TERMINATED
            else:
                t.status = PENDING  # re-runs from its checkpoint
            trials.append(t)
        meta = state.get("meta") or {}
        if tune_config is None:
            tune_config = TuneConfig(
                metric=meta.get("metric"),
                mode=meta.get("mode") or "max",
                num_samples=int(meta.get("num_samples") or len(trials)),
            )
        if restore_uri is not None:
            storage_root, name = restore_uri.rsplit("/", 1)
        else:
            storage_root, name = os.path.split(path.rstrip(os.sep))
        rc = copy.copy(run_config) if run_config is not None \
            else RunConfig()
        rc.storage_path = storage_root
        rc.name = name
        tuner = cls(trainable, param_space=param_space,
                    tune_config=tune_config, run_config=rc,
                    resources_per_trial=resources_per_trial)
        tuner._restored_trials = trials
        return tuner

    def fit(self) -> ResultGrid:
        from ray_tpu.tune.stopper import coerce_stopper

        resources = self.resources_per_trial or getattr(
            self.trainable, "_tune_resources", None)
        searcher = self.tune_config.search_alg
        if self._restored_trials is not None:
            trials = self._restored_trials
            if searcher is not None:
                # Continue the search: replay finished trials into the
                # ask/tell state, then let the runner request the
                # remaining num_samples suggestions.
                searcher.set_search_properties(
                    self.tune_config.metric, self.tune_config.mode,
                    self.param_space)
                from ray_tpu.tune.trial_runner import TERMINATED as _T

                if getattr(searcher, "expands_variants", False):
                    # Variant-expanding searchers pre-deal a fixed set:
                    # consume one variant per restored trial so resume
                    # deals only what was never created, instead of
                    # re-running the whole grid as duplicates.
                    for t in trials:
                        searcher.suggest(t.trial_id)
                for t in trials:
                    if t.status == _T and t.last_result:
                        # tell(), not on_trial_complete(): these ids were
                        # never suggest()-ed by THIS searcher instance.
                        searcher.tell(t.config, t.last_result)
        elif searcher is not None:
            ok = searcher.set_search_properties(
                self.tune_config.metric, self.tune_config.mode,
                self.param_space)
            if not ok:
                raise ValueError(
                    "search_alg was constructed with its own space/metric; "
                    "pass param_space/metric only in one place")
            trials: List[Trial] = []
        else:
            variants = generate_variants(
                self.param_space,
                num_samples=self.tune_config.num_samples,
                seed=self.tune_config.seed,
            )
            trials = [Trial(cfg, resources) for cfg in variants]
        local_dir, sync_uri = self._storage()
        sync = None
        if sync_uri:
            from ray_tpu.tune.syncer import _PeriodicSync, get_syncer

            sync = _PeriodicSync(get_syncer(sync_uri), local_dir, sync_uri)
        runner = TrialRunner(
            self.trainable,
            trials,
            scheduler=self.tune_config.scheduler,
            max_concurrent=self.tune_config.max_concurrent_trials,
            max_failures=self.run_config.failure_config.max_failures,
            stopper=coerce_stopper(self.run_config.stop),
            searcher=searcher,
            num_samples=self.tune_config.num_samples,
            trial_resources=resources,
            experiment_dir=local_dir,
            sync=sync,
        )
        runner.experiment_meta = {
            "metric": self.tune_config.metric,
            "mode": self.tune_config.mode,
            "num_samples": self.tune_config.num_samples,
        }
        runner.run()
        trials = runner.trials
        results = [
            TrialResult(
                t.trial_id, t.config, t.last_result, t.checkpoint, t.error,
                t.metrics_history,
            )
            for t in trials
        ]
        return ResultGrid(results, self.tune_config.metric, self.tune_config.mode)


def run(
    trainable: Callable,
    *,
    config: Optional[dict] = None,
    num_samples: int = 1,
    scheduler: Optional[TrialScheduler] = None,
    metric: Optional[str] = None,
    mode: str = "max",
    max_concurrent_trials: int = 8,
    **_kw,
) -> ResultGrid:
    """Legacy ``tune.run`` entry point (``tune/tune.py:131``)."""
    return Tuner(
        trainable,
        param_space=config,
        tune_config=TuneConfig(
            metric=metric, mode=mode, num_samples=num_samples,
            scheduler=scheduler, max_concurrent_trials=max_concurrent_trials,
        ),
    ).fit()


def with_resources(trainable, resources: dict):
    """Attach per-trial resource requests to a trainable (reference
    ``tune.with_resources``): ``{"CPU": 2, "TPU": 4}`` or a
    placement-group shape ``{"bundles": [...], "strategy": "PACK"}``.
    Always returns a NEW wrapper — re-wrapping never mutates a trainable
    another experiment may still be holding."""
    import functools

    @functools.wraps(trainable)
    def wrapped(*a, **kw):
        return trainable(*a, **kw)

    wrapped._tune_resources = dict(resources)
    return wrapped
