"""Build the native C++ components on first import (cached by mtime).

The reference builds its native core via bazel (``src/ray/BUILD``); here the
native surface is small enough that a direct g++ invocation with an mtime
cache is simpler and hermetic (no generated build files in-tree).
"""

from __future__ import annotations

import os
import subprocess
import threading

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_LIB_DIR = os.path.join(os.path.dirname(__file__), "lib")
_LOCK = threading.Lock()

_LIBS = {
    # lib name -> source files
    "shm_store": ["shm_store.cc"],
    "scheduler": ["scheduler.cc"],
}


def lib_path(name: str) -> str:
    return os.path.join(_LIB_DIR, f"lib{name}.so")


def ensure_built(name: str) -> str:
    """Compile lib<name>.so if missing or stale; return its path."""
    sources = [os.path.join(_SRC_DIR, s) for s in _LIBS[name]]
    out = lib_path(name)
    with _LOCK:
        if os.path.exists(out):
            src_mtime = max(os.path.getmtime(s) for s in sources)
            if os.path.getmtime(out) >= src_mtime:
                return out
        os.makedirs(_LIB_DIR, exist_ok=True)
        tmp = out + f".tmp.{os.getpid()}"
        cmd = [
            "g++",
            "-O2",
            "-g",
            "-fPIC",
            "-shared",
            "-std=c++17",
            "-pthread",
            "-o",
            tmp,
            *sources,
        ]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)  # atomic w.r.t. concurrent builders
    return out
