"""Build the native C++ components on first import (cached by mtime).

The reference builds its native core via bazel (``src/ray/BUILD``); here the
native surface is small enough that a direct g++ invocation with an mtime
cache is simpler and hermetic (no generated build files in-tree).
"""

from __future__ import annotations

import os
import subprocess
import threading

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_LIB_DIR = os.path.join(os.path.dirname(__file__), "lib")
_LOCK = threading.Lock()

_LIBS = {
    # lib name -> source files
    "shm_store": ["shm_store.cc"],
    "scheduler": ["scheduler.cc"],
}


def lib_path(name: str) -> str:
    return os.path.join(_LIB_DIR, f"lib{name}.so")


def _compile(out: str, sources: list, flags: list) -> str:
    """mtime-cached g++ compile to ``out`` (atomic tmp+rename)."""
    with _LOCK:
        if os.path.exists(out):
            # Headers count: every .cc includes headers from src/, and a
            # protocol change in e.g. rpc_channel.h must invalidate cached
            # binaries or old workers would fail the new handshake.
            headers = [
                os.path.join(_SRC_DIR, f)
                for f in os.listdir(_SRC_DIR) if f.endswith(".h")
            ]
            src_mtime = max(os.path.getmtime(s) for s in sources + headers)
            if os.path.getmtime(out) >= src_mtime:
                return out
        os.makedirs(_LIB_DIR, exist_ok=True)
        tmp = out + f".tmp.{os.getpid()}"
        cmd = ["g++", *flags, "-std=c++17", "-pthread", "-o", tmp, *sources]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)  # atomic w.r.t. concurrent builders
    return out


def ensure_built(name: str, force: bool = False) -> str:
    """Compile lib<name>.so if missing or stale; return its path.
    ``force`` discards the cached binary first — the dlopen self-heal
    path for a checked-out .so built against an incompatible glibc."""
    out = lib_path(name)
    if force:
        with _LOCK:
            try:
                os.unlink(out)
            except OSError:
                pass
    sources = [os.path.join(_SRC_DIR, s) for s in _LIBS[name]]
    return _compile(out, sources, ["-O2", "-g", "-fPIC", "-shared"])


def build_cpp_worker() -> str:
    """Build the sample C++ worker/driver binary (the native worker API's
    reference executable — ``cpp/`` worker parity). Also usable as a
    template: user worker binaries compile their own functions against
    raytpu.h + raytpu_runtime.cc the same way."""
    sources = [
        os.path.join(_SRC_DIR, "sample_worker.cc"),
        os.path.join(_SRC_DIR, "raytpu_runtime.cc"),
        os.path.join(_SRC_DIR, "shm_store.cc"),
    ]
    return _compile(
        os.path.join(_LIB_DIR, "raytpu_sample_worker"), sources, ["-O2", "-g"])


def build_stress_binary(sanitize: str | None = None) -> str:
    """Build the multithreaded store stress driver (store_stress.cc +
    shm_store.cc in one binary), optionally under a sanitizer
    ("address" / "thread" / "undefined") — SURVEY §5.2 race detection.
    Cached by mtime per sanitizer flavor."""
    tag = sanitize or "plain"
    sources = [
        os.path.join(_SRC_DIR, "store_stress.cc"),
        os.path.join(_SRC_DIR, "shm_store.cc"),
    ]
    flags = ["-O1", "-g"]
    if sanitize:
        flags += [f"-fsanitize={sanitize}", "-fno-omit-frame-pointer"]
    return _compile(
        os.path.join(_LIB_DIR, f"store_stress_{tag}"), sources, flags)
