"""ctypes binding for the C++ shared-memory object store.

Python-side counterpart of the reference's plasma client
(``src/ray/object_manager/plasma/client.h``): create/seal/get/release/delete
against the node-local segment, with zero-copy reads — ``get`` returns
memoryviews sliced straight out of the mmap'd segment, which numpy /
pickle-5 consume without copying.
"""

from __future__ import annotations

import ctypes
import hashlib
import mmap
import os
import threading
import time
from contextlib import contextmanager

from ray_tpu._native.build import ensure_built

ID_SIZE = 20


class StoreFullError(Exception):
    """Segment cannot fit the object even after evicting everything evictable."""


class ObjectExistsError(Exception):
    pass


def _load():
    try:
        lib = ctypes.CDLL(ensure_built("shm_store"))
    except OSError:
        # The cached (possibly checked-in) binary doesn't load on THIS
        # machine — e.g. built against a newer glibc than the container
        # ships. Rebuild from source and retry once.
        lib = ctypes.CDLL(ensure_built("shm_store", force=True))
    lib.ts_create.restype = ctypes.c_void_p
    lib.ts_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64]
    lib.ts_attach.restype = ctypes.c_void_p
    lib.ts_attach.argtypes = [ctypes.c_char_p]
    lib.ts_detach.argtypes = [ctypes.c_void_p]
    lib.ts_unlink.argtypes = [ctypes.c_char_p]
    lib.ts_alloc.restype = ctypes.c_int64
    lib.ts_alloc.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.c_uint64,
    ]
    for fn in ("ts_seal", "ts_release", "ts_contains", "ts_delete", "ts_abort",
               "ts_evict"):
        f = getattr(lib, fn)
        f.restype = ctypes.c_int
        f.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ts_pin.restype = ctypes.c_int
    lib.ts_pin.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.ts_release_dead.restype = ctypes.c_int64
    lib.ts_release_dead.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.ts_info.restype = ctypes.c_int
    lib.ts_info.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.ts_get.restype = ctypes.c_int
    lib.ts_get.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.ts_stats.argtypes = [ctypes.c_void_p] + [
        ctypes.POINTER(ctypes.c_uint64)
    ] * 4
    lib.ts_list.restype = ctypes.c_uint64
    lib.ts_list.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
    return lib


_lib = None


def _get_lib():
    global _lib
    if _lib is None:
        _lib = _load()
    return _lib


def store_key(object_id: str) -> bytes:
    """Map a framework object-id string to the store's fixed 20-byte key."""
    return hashlib.sha1(object_id.encode()).digest()


class ShmStore:
    """One node-local segment. ``create=True`` initializes it (node daemon);
    workers/drivers attach to the existing segment."""

    def __init__(self, path: str, capacity: int = 0, *, create: bool = False):
        lib = _get_lib()
        self.path = path
        if create:
            self._h = lib.ts_create(path.encode(), capacity, 0)
            if not self._h:
                raise OSError(f"failed to create shm store at {path}")
        else:
            self._h = lib.ts_attach(path.encode())
            if not self._h:
                raise OSError(f"failed to attach shm store at {path}")
        # Python-side view of the same segment for zero-copy buffers.
        self._fd = os.open(path, os.O_RDWR)
        self._mm = mmap.mmap(self._fd, 0)
        self._owner = create
        # In-process close gate: every native call runs inside _op(),
        # and close() waits for in-flight ops to drain before ts_detach
        # munmaps the segment — without this, a teardown-time close
        # racing a concurrent caller (reap loop's release_dead, a spill
        # pass, a buffer finalizer) is a native use-after-free segfault.
        self._op_cv = threading.Condition()
        self._ops = 0

    @contextmanager
    def _op(self):
        """Yield the native handle (or None if closed), holding off a
        concurrent close() for the duration of the native call."""
        with self._op_cv:
            h = self._h
            if h:
                self._ops += 1
        try:
            yield h
        finally:
            if h:
                with self._op_cv:
                    self._ops -= 1
                    if self._ops == 0:
                        self._op_cv.notify_all()

    # -- object lifecycle -------------------------------------------------

    def create(self, object_id: str, data_size: int, meta: bytes = b"") -> memoryview:
        """Allocate an unsealed object; returns a writable view of its data
        region. Write, then ``seal``."""
        key = store_key(object_id)
        with self._op() as h:
            if not h:
                raise OSError(f"store {self.path} is closed")
            off = _get_lib().ts_alloc(h, key, data_size, len(meta))
        if off == -2:
            raise ObjectExistsError(object_id)
        if off < 0:
            raise StoreFullError(
                f"cannot allocate {data_size + len(meta)} bytes (code {off})"
            )
        if meta:
            self._mm[off + data_size : off + data_size + len(meta)] = meta
        return memoryview(self._mm)[off : off + data_size]

    def put(self, object_id: str, data, meta: bytes = b"") -> None:
        """create + write + seal in one call. ``data`` is bytes-like or a
        list of bytes-like chunks (written back to back)."""
        chunks = data if isinstance(data, (list, tuple)) else [data]
        total = sum(len(c) for c in chunks)
        buf = self.create(object_id, total, meta)
        pos = 0
        for c in chunks:
            n = len(c)
            buf[pos : pos + n] = bytes(c) if not isinstance(c, (bytes, bytearray, memoryview)) else c
            pos += n
        self.seal(object_id)

    def seal(self, object_id: str) -> None:
        with self._op() as h:
            if not h:
                raise KeyError(f"seal({object_id}): store is closed")
            rc = _get_lib().ts_seal(h, store_key(object_id))
        if rc != 0:
            raise KeyError(f"seal({object_id}) failed: {rc}")

    def get(self, object_id: str) -> tuple[memoryview, bytes] | None:
        """Zero-copy read: (data view, metadata bytes), or None if absent.
        Caller must ``release`` when done with the view."""
        off = ctypes.c_uint64()
        dsz = ctypes.c_uint64()
        msz = ctypes.c_uint64()
        with self._op() as h:
            if not h:
                return None
            rc = _get_lib().ts_get(
                h, store_key(object_id), ctypes.byref(off),
                ctypes.byref(dsz), ctypes.byref(msz),
            )
        if rc != 0:
            return None
        o, d, m = off.value, dsz.value, msz.value
        data = memoryview(self._mm)[o : o + d]
        meta = bytes(self._mm[o + d : o + d + m])
        return data, meta

    def release(self, object_id: str) -> None:
        # Guard post-close calls: zero-copy buffer finalizers (weakref)
        # can fire at interpreter exit, after shutdown() detached the
        # store — ts_* on a NULL handle is a segfault.
        with self._op() as h:
            if not h:
                return
            _get_lib().ts_release(h, store_key(object_id))

    def contains(self, object_id: str) -> bool:
        with self._op() as h:
            if not h:
                return False
            return bool(_get_lib().ts_contains(h, store_key(object_id)))

    def delete(self, object_id: str) -> bool:
        with self._op() as h:
            if not h:
                return False
            return _get_lib().ts_delete(h, store_key(object_id)) == 0

    def abort(self, object_id: str) -> bool:
        with self._op() as h:
            if not h:
                return False
            return _get_lib().ts_abort(h, store_key(object_id)) == 0

    def release_dead(self, pid: int) -> int:
        """Reclaim all pins held by a dead process + abort its unsealed
        creations; returns slots touched (crash-leak cleanup). A no-op
        once the store is closed — cleanup of a dead process is moot
        when the segment itself is gone (this call racing teardown was
        the observed whole-process segfault)."""
        with self._op() as h:
            if not h:
                return 0
            return _get_lib().ts_release_dead(h, pid)

    def pin(self, object_id: str, pinned: bool = True) -> bool:
        """Primary-copy pin: pinned objects are never LRU-evicted (only
        spilled). Set on put by owners; cleared when the cluster
        ref-counter frees the object."""
        with self._op() as h:
            if not h:
                return False
            return _get_lib().ts_pin(
                h, store_key(object_id), int(pinned)) == 0

    def evict(self, object_id: str) -> bool:
        """Remove a sealed object regardless of pin (its bytes are safe
        elsewhere, e.g. spilled). Fails if actively read (refcount > 0)."""
        with self._op() as h:
            if not h:
                return False
            return _get_lib().ts_evict(h, store_key(object_id)) == 0

    def _check_linked(self) -> None:
        """Fail LOUD when the segment file was unlinked by another
        process while our handle is still open (e.g. the owning agent
        shut down and a stale client keeps introspecting): the native
        stats/info would read a mapping whose backing file is gone and
        hand back garbage. Mirrors the closed-handle guards — but an
        unlinked segment is an error, not an empty result."""
        try:
            os.stat(self.path)
        except FileNotFoundError:
            raise RuntimeError(
                f"shm store segment {self.path} was unlinked by another "
                f"process (owner shut down?); reattach to a live store"
            ) from None
        except OSError:
            pass  # stat hiccup: let the native call proceed

    def info(self, object_id: str) -> dict | None:
        """Sealed-object metadata (spill-candidate selection)."""
        dsz = ctypes.c_uint64()
        msz = ctypes.c_uint64()
        ref = ctypes.c_int64()
        pin = ctypes.c_uint32()
        lru = ctypes.c_uint64()
        with self._op() as h:
            if not h:
                return None
            self._check_linked()
            rc = _get_lib().ts_info(
                h, store_key(object_id), ctypes.byref(dsz),
                ctypes.byref(msz), ctypes.byref(ref), ctypes.byref(pin),
                ctypes.byref(lru),
            )
        if rc != 0:
            return None
        return {
            "data_size": dsz.value,
            "meta_size": msz.value,
            "refcount": ref.value,
            "pinned": bool(pin.value),
            "lru_tick": lru.value,
        }

    # -- introspection ----------------------------------------------------

    def stats(self) -> dict:
        vals = [ctypes.c_uint64() for _ in range(4)]
        with self._op() as h:
            if not h:
                return {"capacity": 0, "used": 0, "num_objects": 0,
                        "num_evictions": 0}
            self._check_linked()
            _get_lib().ts_stats(h, *[ctypes.byref(v) for v in vals])
        return {
            "capacity": vals[0].value,
            "used": vals[1].value,
            "num_objects": vals[2].value,
            "num_evictions": vals[3].value,
        }

    def list_keys(self, max_ids: int = 1 << 16) -> list[bytes]:
        buf = ctypes.create_string_buffer(max_ids * ID_SIZE)
        with self._op() as h:
            if not h:
                return []
            n = _get_lib().ts_list(h, buf, max_ids)
        return [buf.raw[i * ID_SIZE : (i + 1) * ID_SIZE] for i in range(n)]

    # -- lifecycle --------------------------------------------------------

    def close(self, unlink: bool = False) -> None:
        # Null the handle first (new callers see "closed"), then wait
        # for in-flight native calls to drain before detaching — the
        # reverse order left a window where ts_* ran on a just-munmapped
        # segment (observed as a release_dead segfault at teardown that
        # took the whole test process down).
        with self._op_cv:
            h, self._h = self._h, None
            deadline = time.monotonic() + 5.0
            while h and self._ops > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break  # a stuck caller must not hang shutdown
                self._op_cv.wait(remaining)
        if h:
            _get_lib().ts_detach(h)
            try:
                self._mm.close()
            except BufferError:
                # Zero-copy views into the segment are still alive somewhere;
                # the mapping stays until they are collected (plasma keeps
                # client mappings for the process lifetime for the same
                # reason). The OS reclaims it at process exit.
                pass
            os.close(self._fd)
        if unlink and self._owner:
            try:
                os.unlink(self.path)
            except OSError:
                pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
