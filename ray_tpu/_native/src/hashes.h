// Minimal SHA-1 / SHA-256 / HMAC-SHA-256 for the C++ worker API.
//
// Why hand-rolled: the image has no OpenSSL dev headers, and the two uses
// are tiny — store keys are SHA1(object_id) (matching
// ray_tpu/_native/shm_store.py:store_key) and the cluster-token handshake
// is HMAC-SHA256 over a 32-byte challenge (ray_tpu/cluster/rpc.py).
// Both are public-domain-style textbook implementations of FIPS 180-4 /
// RFC 2104; no attempt at constant-time — the worker is a cluster-internal
// peer, not a verifier.
//
// Reference parity: the reference's C++ worker links real crypto via gRPC;
// this build's wire plane is the repo's own RPC (SURVEY.md §2.1 RPC layer).
#pragma once

#include <cstdint>
#include <cstring>
#include <string>

namespace raytpu {

// ---------------------------------------------------------------- SHA-1
struct Sha1 {
  uint32_t h[5] = {0x67452301u, 0xEFCDAB89u, 0x98BADCFEu, 0x10325476u,
                   0xC3D2E1F0u};
  uint64_t total = 0;
  uint8_t buf[64];
  size_t buflen = 0;

  static uint32_t rol(uint32_t x, int n) { return (x << n) | (x >> (32 - n)); }

  void block(const uint8_t* p) {
    uint32_t w[80];
    for (int i = 0; i < 16; i++)
      w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
             (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    for (int i = 16; i < 80; i++)
      w[i] = rol(w[i - 3] ^ w[i - 8] ^ w[i - 14] ^ w[i - 16], 1);
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4];
    for (int i = 0; i < 80; i++) {
      uint32_t f, k;
      if (i < 20) {
        f = (b & c) | ((~b) & d);
        k = 0x5A827999u;
      } else if (i < 40) {
        f = b ^ c ^ d;
        k = 0x6ED9EBA1u;
      } else if (i < 60) {
        f = (b & c) | (b & d) | (c & d);
        k = 0x8F1BBCDCu;
      } else {
        f = b ^ c ^ d;
        k = 0xCA62C1D6u;
      }
      uint32_t t = rol(a, 5) + f + e + k + w[i];
      e = d; d = c; c = rol(b, 30); b = a; a = t;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d; h[4] += e;
  }

  void update(const void* data, size_t n) {
    const uint8_t* p = static_cast<const uint8_t*>(data);
    total += n;
    while (n) {
      size_t take = 64 - buflen;
      if (take > n) take = n;
      std::memcpy(buf + buflen, p, take);
      buflen += take; p += take; n -= take;
      if (buflen == 64) { block(buf); buflen = 0; }
    }
  }

  void final(uint8_t out[20]) {
    uint64_t bits = total * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (buflen != 56) update(&zero, 1);
    uint8_t len[8];
    for (int i = 0; i < 8; i++) len[i] = uint8_t(bits >> (56 - 8 * i));
    update(len, 8);
    for (int i = 0; i < 5; i++) {
      out[4 * i] = uint8_t(h[i] >> 24);
      out[4 * i + 1] = uint8_t(h[i] >> 16);
      out[4 * i + 2] = uint8_t(h[i] >> 8);
      out[4 * i + 3] = uint8_t(h[i]);
    }
  }
};

inline void sha1(const void* data, size_t n, uint8_t out[20]) {
  Sha1 s;
  s.update(data, n);
  s.final(out);
}

// -------------------------------------------------------------- SHA-256
struct Sha256 {
  static constexpr uint32_t K[64] = {
      0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
      0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
      0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
      0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
      0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
      0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
      0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
      0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
      0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
      0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
      0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
  uint32_t h[8] = {0x6a09e667u, 0xbb67ae85u, 0x3c6ef372u, 0xa54ff53au,
                   0x510e527fu, 0x9b05688cu, 0x1f83d9abu, 0x5be0cd19u};
  uint64_t total = 0;
  uint8_t buf[64];
  size_t buflen = 0;

  static uint32_t ror(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

  void block(const uint8_t* p) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
      w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
             (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    for (int i = 16; i < 64; i++) {
      uint32_t s0 = ror(w[i - 15], 7) ^ ror(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = ror(w[i - 2], 17) ^ ror(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
    uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
    for (int i = 0; i < 64; i++) {
      uint32_t S1 = ror(e, 6) ^ ror(e, 11) ^ ror(e, 25);
      uint32_t ch = (e & f) ^ ((~e) & g);
      uint32_t t1 = hh + S1 + ch + K[i] + w[i];
      uint32_t S0 = ror(a, 2) ^ ror(a, 13) ^ ror(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const void* data, size_t n) {
    const uint8_t* p = static_cast<const uint8_t*>(data);
    total += n;
    while (n) {
      size_t take = 64 - buflen;
      if (take > n) take = n;
      std::memcpy(buf + buflen, p, take);
      buflen += take; p += take; n -= take;
      if (buflen == 64) { block(buf); buflen = 0; }
    }
  }

  void final(uint8_t out[32]) {
    uint64_t bits = total * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (buflen != 56) update(&zero, 1);
    uint8_t len[8];
    for (int i = 0; i < 8; i++) len[i] = uint8_t(bits >> (56 - 8 * i));
    update(len, 8);
    for (int i = 0; i < 8; i++) {
      out[4 * i] = uint8_t(h[i] >> 24);
      out[4 * i + 1] = uint8_t(h[i] >> 16);
      out[4 * i + 2] = uint8_t(h[i] >> 8);
      out[4 * i + 3] = uint8_t(h[i]);
    }
  }
};

inline void sha256(const void* data, size_t n, uint8_t out[32]) {
  Sha256 s;
  s.update(data, n);
  s.final(out);
}

// RFC 2104 over SHA-256 (block size 64).
inline void hmac_sha256(const uint8_t* key, size_t keylen, const uint8_t* msg,
                        size_t msglen, uint8_t out[32]) {
  uint8_t k[64];
  std::memset(k, 0, sizeof(k));
  if (keylen > 64) {
    sha256(key, keylen, k);  // long keys are hashed first
  } else {
    std::memcpy(k, key, keylen);
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; i++) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  uint8_t inner[32];
  Sha256 si;
  si.update(ipad, 64);
  si.update(msg, msglen);
  si.final(inner);
  Sha256 so;
  so.update(opad, 64);
  so.update(inner, 32);
  so.final(out);
}

}  // namespace raytpu
