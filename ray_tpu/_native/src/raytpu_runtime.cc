// Implementation of the C++ worker / driver API (raytpu.h).
//
// Worker-lease parity with the Python worker (cluster/workerproc.py): the
// node agent spawns this binary with the same flags, the worker registers
// back with its RPC address, serves push_task/ping/cancel_task, executes
// registered functions from a FIFO queue on one executor thread, writes
// results directly into the node's C++ shm store (src/shm_store.cc), and
// reports add_location to the head + task_done / worker_events to the
// agent — indistinguishable from a Python worker to the rest of the
// cluster.
#include "raytpu.h"

#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <random>
#include <thread>

#include "hashes.h"
#include "rpc_channel.h"

// ---- shm store C API (defined in shm_store.cc, linked in) --------------
extern "C" {
void* ts_attach(const char* path);
void ts_detach(void* hp);
int64_t ts_alloc(void* hp, const uint8_t* id, uint64_t data_size,
                 uint64_t meta_size);
int ts_seal(void* hp, const uint8_t* id);
int ts_get(void* hp, const uint8_t* id, uint64_t* offset, uint64_t* data_size,
           uint64_t* meta_size);
int ts_release(void* hp, const uint8_t* id);
int ts_contains(void* hp, const uint8_t* id);
int ts_pin(void* hp, const uint8_t* id, int pinned);
uint8_t* ts_base_ptr(void* hp);
}

namespace raytpu {

namespace {

double now_s() {
  return std::chrono::duration<double>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

std::string env_token() {
  const char* t = std::getenv("RAY_TPU_CLUSTER_TOKEN");
  return t ? std::string(t) : std::string();
}

std::string random_hex(size_t nbytes) {
  static thread_local std::mt19937_64 rng{std::random_device{}()};
  static const char* digits = "0123456789abcdef";
  std::string out;
  out.reserve(nbytes * 2);
  for (size_t i = 0; i < nbytes; i++) {
    uint8_t b = uint8_t(rng());
    out.push_back(digits[b >> 4]);
    out.push_back(digits[b & 15]);
  }
  return out;
}

// Store key convention: SHA1 of the object-id string
// (ray_tpu/_native/shm_store.py:store_key).
void store_key(const std::string& oid, uint8_t out[20]) {
  sha1(oid.data(), oid.size(), out);
}

// Node-local store handle over the ts_* API.
class Store {
 public:
  void attach(const std::string& path) {
    h_ = ts_attach(path.c_str());
    if (!h_) throw RpcError("cannot attach shm store at " + path);
  }
  ~Store() {
    if (h_) ts_detach(h_);
  }
  bool attached() const { return h_ != nullptr; }

  void put(const std::string& oid, const std::string& data,
           const std::string& meta) {
    uint8_t key[20];
    store_key(oid, key);
    int64_t off = ts_alloc(h_, key, data.size(), meta.size());
    if (off == -2) return;  // already present (idempotent re-put)
    if (off < 0)
      throw RpcError("store full putting " + oid.substr(0, 16) + "… (code " +
                     std::to_string(off) + ")");
    uint8_t* base = ts_base_ptr(h_);
    std::memcpy(base + off, data.data(), data.size());
    std::memcpy(base + off + data.size(), meta.data(), meta.size());
    if (ts_seal(h_, key) != 0) throw RpcError("seal failed for " + oid);
  }

  // (data, meta) copies, or nullopt. Copies are fine for the C++ paths —
  // zero-copy reads are the Python side's numpy-view specialty.
  std::optional<std::pair<std::string, std::string>> get(
      const std::string& oid) {
    uint8_t key[20];
    store_key(oid, key);
    uint64_t off = 0, dsz = 0, msz = 0;
    if (ts_get(h_, key, &off, &dsz, &msz) != 0) return std::nullopt;
    uint8_t* base = ts_base_ptr(h_);
    std::string data(reinterpret_cast<char*>(base + off), dsz);
    std::string meta(reinterpret_cast<char*>(base + off + dsz), msz);
    ts_release(h_, key);
    return std::make_pair(std::move(data), std::move(meta));
  }

  void pin(const std::string& oid) {
    uint8_t key[20];
    store_key(oid, key);
    ts_pin(h_, key, 1);
  }

 private:
  void* h_ = nullptr;
};

std::map<std::string, TaskFn>& registry() {
  static std::map<std::string, TaskFn> r;
  return r;
}

}  // namespace

void RegisterFunction(const std::string& name, TaskFn fn) {
  registry()[name] = std::move(fn);
}

// ----------------------------------------------------------------- worker

namespace {

struct WorkerCtx {
  std::string head_addr, agent_addr, node_id, store_path, worker_id;
  std::string token;
  Store store;
  std::unique_ptr<RpcChannel> head, agent;
  std::mutex mu;
  std::condition_variable cv;
  std::deque<Value> queue;  // push_task specs
  std::atomic<bool> stopped{false};
  std::vector<Value> events;  // task records pending worker_events flush
  // Owner-directory channels (exec_loop-only; no lock needed): results
  // are announced to the submitting client's owner service so its get()
  // resolves locally instead of long-polling the head (client.py
  // _OwnerService parity with the Python worker path).
  std::map<std::string, std::unique_ptr<RpcChannel>> owner_chans;

  void report_owner(const std::string& owner, const std::string& oid,
                    bool is_error, int64_t size) {
    if (owner.empty()) return;
    try {
      auto it = owner_chans.find(owner);
      if (it == owner_chans.end())
        it = owner_chans
                 .emplace(owner, std::make_unique<RpcChannel>(owner, token))
                 .first;
      it->second->call(
          "owner_add_location",
          {Value::Str(oid), Value::Str(node_id), Value::Str(agent_addr),
           Value::Str(store_path), Value::Bool(is_error), Value::Int(size)});
    } catch (const std::exception&) {
      owner_chans.erase(owner);  // best-effort; the head's view covers
    }
  }

  // Serialize a Value result into the store + announce the location.
  void store_result(const std::string& oid, const Value& v,
                    const std::string& owner) {
    std::string payload = pickle_dumps(v);
    std::string meta = meta_encode('V', payload.size());
    store.put(oid, payload, meta);
    store.pin(oid);  // primary copy (put_with_id parity)
    Value kw = Value::Dict();
    kw.set("is_error", Value::Bool(false));
    kw.set("size", Value::Int(int64_t(payload.size())));
    kw.set("owner_addr", Value::Str(owner));
    head->call("add_location", {Value::Str(oid), Value::Str(node_id)},
               std::move(kw));
    report_owner(owner, oid, false, int64_t(payload.size()));
  }

  // Store a TaskError instance Python can re-raise at get()
  // (core/object_ref.py TaskError.__reduce__ shape).
  void store_error(const std::string& oid, const std::string& fname,
                   const std::string& message, const std::string& owner) {
    std::string payload;
    payload.push_back('\x80');
    payload.push_back('\x03');
    payload.push_back('c');
    payload += "ray_tpu.core.object_ref\nTaskError\n";
    Value args = Value::Tuple({Value::Str(fname), Value::Str(message),
                               Value::Str("cpp-task-error")});
    pickle_encode_into(args, payload);
    payload.push_back('R');
    payload.push_back('.');
    std::string meta = meta_encode('E', payload.size());
    store.put(oid, payload, meta);
    store.pin(oid);
    Value kw = Value::Dict();
    kw.set("is_error", Value::Bool(true));
    kw.set("size", Value::Int(int64_t(payload.size())));
    kw.set("owner_addr", Value::Str(owner));
    head->call("add_location", {Value::Str(oid), Value::Str(node_id)},
               std::move(kw));
    report_owner(owner, oid, true, int64_t(payload.size()));
  }

  void record_event(const std::string& task_id, const std::string& name,
                    double start, double end, const std::string& error) {
    Value rec = Value::Dict();
    rec.set("task_id", Value::Str(task_id));
    rec.set("name", Value::Str(name));
    rec.set("type", Value::Str("NORMAL_TASK"));
    rec.set("state", error.empty() ? Value::Str("FINISHED")
                                   : Value::Str("FAILED"));
    rec.set("submitted_at", Value::None());
    rec.set("start_time", Value::Float(start));
    rec.set("end_time", Value::Float(end));
    rec.set("error", error.empty() ? Value::None() : Value::Str(error));
    rec.set("lang", Value::Str("cpp"));
    std::lock_guard<std::mutex> g(mu);
    events.push_back(std::move(rec));
  }

  void flush_events() {
    std::vector<Value> batch;
    {
      std::lock_guard<std::mutex> g(mu);
      batch.swap(events);
    }
    if (batch.empty()) return;
    try {
      agent->call("worker_events",
                  {Value::Str(worker_id), Value::Int(int64_t(getpid())),
                   Value::List(std::move(batch)), Value::List()});
    } catch (const std::exception&) {
      // observability is best-effort, like the Python worker's reporter
    }
  }

  void run_one(const Value& spec) {
    const Value* tid = spec.get("task_id");
    const Value* fname = spec.get("fname");
    const Value* oids = spec.get("oids");
    std::string name = fname && fname->kind == Value::STR ? fname->s : "task";
    std::string task_id =
        tid && tid->kind == Value::STR ? tid->s : random_hex(16);
    double start = now_s();
    std::string error;
    try {
      if (!oids || oids->items.empty())
        throw CodecError("cpp task spec has no oids");
      const Value* blob = spec.get("cpp_args");
      std::vector<Value> args;
      if (blob && blob->kind == Value::BYTES) {
        Value decoded = pickle_loads(blob->s);
        args = std::move(decoded.items);
      }
      auto it = registry().find(name);
      if (it == registry().end())
        throw CodecError("no C++ function registered under '" + name +
                         "' in this worker binary");
      Value result = it->second(args);
      const Value* ow = spec.get("owner_addr");
      std::string owner = ow && ow->kind == Value::STR ? ow->s : "";
      if (spec.get("num_returns") && spec.get("num_returns")->as_int() > 1) {
        // multi-return: the function returns a tuple/list, one oid each
        const auto& outs = result.items;
        if (int64_t(outs.size()) != spec.get("num_returns")->as_int())
          throw CodecError("num_returns mismatch");
        for (size_t k = 0; k < outs.size(); k++)
          store_result(oids->items[k].as_str(), outs[k], owner);
      } else {
        store_result(oids->items[0].as_str(), result, owner);
      }
    } catch (const std::exception& e) {
      error = e.what();
      const Value* ow = spec.get("owner_addr");
      std::string owner = ow && ow->kind == Value::STR ? ow->s : "";
      if (oids)
        for (const auto& o : oids->items) {
          try {
            store_error(o.as_str(), name, error, owner);
          } catch (const std::exception&) {
          }
        }
    }
    record_event(task_id, name, start, now_s(), error);
    try {
      agent->call("task_done", {Value::Str(worker_id)});
    } catch (const std::exception&) {
    }
  }

  void exec_loop() {
    while (!stopped) {
      Value spec;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [this] { return stopped || !queue.empty(); });
        if (stopped) return;
        spec = std::move(queue.front());
        queue.pop_front();
      }
      run_one(spec);
      flush_events();
    }
  }
};

}  // namespace

int WorkerMain(int argc, char** argv) {
  WorkerCtx ctx;
  for (int i = 1; i + 1 < argc; i += 2) {
    std::string flag = argv[i], val = argv[i + 1];
    if (flag == "--head") ctx.head_addr = val;
    else if (flag == "--agent") ctx.agent_addr = val;
    else if (flag == "--node-id") ctx.node_id = val;
    else if (flag == "--store") ctx.store_path = val;
    else if (flag == "--worker-id") ctx.worker_id = val;
  }
  if (ctx.head_addr.empty() || ctx.agent_addr.empty() ||
      ctx.store_path.empty()) {
    fprintf(stderr,
            "usage: worker --head H:P --agent H:P --node-id N --store PATH "
            "--worker-id W\n");
    return 2;
  }
  std::string token = env_token();
  ctx.token = token;
  try {
    ctx.store.attach(ctx.store_path);
    ctx.head = std::make_unique<RpcChannel>(ctx.head_addr, token);
    ctx.agent = std::make_unique<RpcChannel>(ctx.agent_addr, token);

    RpcServer server(
        [&ctx](const std::string& m, const Value& args, const Value&) -> Value {
          if (m == "ping") return Value::Str("pong");
          if (m == "push_task") {
            if (args.items.empty()) throw CodecError("push_task needs a spec");
            {
              std::lock_guard<std::mutex> g(ctx.mu);
              ctx.queue.push_back(args.items[0]);
            }
            ctx.cv.notify_one();
            return Value::Bool(true);
          }
          if (m == "cancel_task") return Value::Bool(false);  // not supported
          if (m == "create_actor")
            throw CodecError("C++ workers do not host actors");
          if (m == "exit") {
            ctx.stopped = true;
            ctx.cv.notify_all();
            return Value::Bool(true);
          }
          throw CodecError("unknown worker rpc: " + m);
        },
        token);

    std::thread exec([&ctx] { ctx.exec_loop(); });
    ctx.agent->call("register_worker",
                    {Value::Str(ctx.worker_id), Value::Str(server.address()),
                     Value::None()});

    // Heartbeat the agent link; exit when the agent goes away (agent
    // death must reap its workers, matching Python worker lifetime).
    while (!ctx.stopped) {
      std::this_thread::sleep_for(std::chrono::seconds(2));
      try {
        ctx.agent->call("ping", {});
      } catch (const std::exception&) {
        break;
      }
    }
    ctx.stopped = true;
    ctx.cv.notify_all();
    exec.join();
    server.stop();
    return 0;
  } catch (const std::exception& e) {
    fprintf(stderr, "raytpu worker fatal: %s\n", e.what());
    return 1;
  }
}

// ----------------------------------------------------------------- driver

class DriverImpl {
 public:
  std::string head_addr, token;
  std::unique_ptr<RpcChannel> head;
  std::unique_ptr<RpcChannel> agent;  // the co-located node's agent
  std::string node_id, agent_addr, store_path;
  Store store;
  std::map<std::string, std::unique_ptr<RpcChannel>> peers;

  void connect(const std::string& addr) {
    head_addr = addr;
    token = env_token();
    head = std::make_unique<RpcChannel>(addr, token);
    Value nodes = head->call("nodes", {});
    for (const auto& n : nodes.items) {
      const Value* alive = n.get("Alive");
      if (alive && !alive->truthy()) continue;
      node_id = n.get("NodeID") ? n.get("NodeID")->as_str() : "";
      agent_addr = n.get("Address") ? n.get("Address")->as_str() : "";
      store_path = n.get("StorePath") ? n.get("StorePath")->as_str() : "";
      break;
    }
    if (agent_addr.empty())
      throw RpcError("cluster has no alive nodes to attach to");
    agent = std::make_unique<RpcChannel>(agent_addr, token);
    store.attach(store_path);
  }

  RpcChannel* peer(const std::string& addr) {
    if (addr == agent_addr) return agent.get();
    auto it = peers.find(addr);
    if (it != peers.end()) return it->second.get();
    auto ch = std::make_unique<RpcChannel>(addr, token);
    RpcChannel* raw = ch.get();
    peers[addr] = std::move(ch);
    return raw;
  }

  std::string put(const Value& v) {
    std::string oid = random_hex(16) + "00000000";  // task_id + index 0
    std::string payload = pickle_dumps(v);
    store.put(oid, payload, meta_encode('V', payload.size()));
    store.pin(oid);
    Value kw = Value::Dict();
    kw.set("is_error", Value::Bool(false));
    kw.set("size", Value::Int(int64_t(payload.size())));
    head->call("add_location", {Value::Str(oid), Value::Str(node_id)},
               std::move(kw));
    return oid;
  }

  Value get(const std::string& oid, double timeout_s) {
    double deadline = now_s() + timeout_s;
    while (true) {
      // local store first (results land here when the task ran locally)
      auto local = store.get(oid);
      std::string data, meta;
      if (local) {
        data = std::move(local->first);
        meta = std::move(local->second);
      } else {
        Value loc = head->call("locations", {Value::Str(oid)});
        const Value* ns = loc.is_none() ? nullptr : loc.get("nodes");
        if (ns && !ns->items.empty()) {
          // (node_id, agent_address, store_path) triples
          const Value& first = ns->items[0];
          std::string addr = first.items.at(1).as_str();
          Value got = peer(addr)->call("fetch_object", {Value::Str(oid)});
          if (!got.is_none()) {
            meta = got.items.at(0).as_str();
            data = got.items.at(1).as_str();
          }
        }
      }
      if (!meta.empty()) {
        char flag = 0;
        std::vector<uint64_t> sizes = meta_decode(meta, &flag);
        uint64_t payload_len = sizes.empty() ? data.size() : sizes[0];
        std::string payload = data.substr(0, payload_len);
        if (flag == 'E') {
          std::string desc;
          try {
            Value err = pickle_loads(payload);
            desc = err.kind == Value::STR ? err.s : "task failed";
          } catch (const CodecError&) {
            desc = "task failed (undecodable error object)";
          }
          throw RpcError("task error for " + oid.substr(0, 16) + "…: " + desc);
        }
        if (sizes.size() > 1)
          throw RpcError("object " + oid.substr(0, 16) +
                         "… has out-of-band buffers (numpy?) — not "
                         "representable in the C++ type set");
        return pickle_loads(payload);
      }
      if (now_s() > deadline)
        throw RpcError("get(" + oid.substr(0, 16) + "…) timed out");
      std::this_thread::sleep_for(std::chrono::milliseconds(20));
    }
  }

  std::string submit(const std::string& fname, std::vector<Value> args,
                     const std::string& worker_bin, double num_cpus) {
    std::string task_id = random_hex(16);
    std::string oid = task_id + "00000000";
    Value demand = Value::Dict();
    demand.set("CPU", Value::Float(num_cpus));

    Value kw = Value::Dict();
    kw.set("caller_node", Value::None());
    kw.set("strategy", Value::None());
    kw.set("node_affinity", Value::None());
    kw.set("task_id", Value::Str(task_id));
    Value placed = head->call("schedule", {demand}, std::move(kw));
    if (placed.is_none())
      throw RpcError("demand infeasible: no node has " +
                     std::to_string(num_cpus) + " CPU");
    std::string addr = placed.items.at(1).as_str();

    Value spec = Value::Dict();
    spec.set("task_id", Value::Str(task_id));
    spec.set("oids", Value::List({Value::Str(oid)}));
    spec.set("fname", Value::Str(fname));
    spec.set("lang", Value::Str("cpp"));
    if (!worker_bin.empty())
      spec.set("cpp_worker_bin", Value::Str(worker_bin));
    spec.set("cpp_args",
             Value::Bytes(pickle_dumps(Value::List(std::move(args)))));
    spec.set("num_returns", Value::Int(1));
    spec.set("demand", demand);
    spec.set("assigned_node", placed.items.at(0));
    peer(addr)->call("submit_task", {std::move(spec)});
    return oid;
  }
};

Driver::Driver() : impl_(new DriverImpl) {}
Driver::~Driver() { delete impl_; }
void Driver::Connect(const std::string& head_address) {
  impl_->connect(head_address);
}
ObjectRef Driver::Put(const Value& v) { return {impl_->put(v)}; }
Value Driver::Get(const ObjectRef& ref, double timeout_s) {
  return impl_->get(ref.id, timeout_s);
}
ObjectRef Driver::Submit(const std::string& fname, std::vector<Value> args,
                         const std::string& worker_bin, double num_cpus) {
  return {impl_->submit(fname, std::move(args), worker_bin, num_cpus)};
}
void Driver::Shutdown() {
  impl_->head.reset();
  impl_->agent.reset();
  impl_->peers.clear();
}

}  // namespace raytpu
