// ray_tpu C++ worker / driver API.
//
// Reference parity: the reference ships a native C++ worker API
// (``cpp/include/ray/api.h`` — ``ray::Init``, ``ray::Task(f).Remote()``,
// ``ray::Get``) running on its CoreWorker. This build's equivalent rides
// the repo's own planes: the pickle RPC (cluster/rpc.py) for control and
// the C++ shared-memory store (src/shm_store.cc) for data — a C++ task
// result is written straight into the node's shm segment, zero extra
// copies, and any Python peer reads it zero-copy.
//
// Two roles, one library:
//  * WORKER: an executable that registers functions and calls
//    raytpu::WorkerMain(argc, argv). The node agent spawns it like a
//    Python worker when a task's lang is "cpp" (worker-lease parity);
//    Python drivers invoke its functions by name via
//    ray_tpu.cross_language.cpp_function("name").remote(...).
//  * DRIVER: any C++ program: Driver d; d.Connect(head_addr);
//    auto ref = d.Submit("add", {Value::Int(1), Value::Int(2)});
//    Value out = d.Get(ref, 30.0);
//
// Cross-language values are the restricted set {None, bool, int, float,
// str, bytes, list, tuple, dict} (pyvalue.h) — the same restriction the
// reference places on cross-language calls (python/ray/cross_language.py).
#pragma once

#include <functional>
#include <optional>
#include <string>
#include <utility>
#include <vector>

#include "pyvalue.h"

namespace raytpu {

using TaskFn = std::function<Value(const std::vector<Value>&)>;

// Register a function under a cross-language name. Call before
// WorkerMain(); typically from static initializers via RAYTPU_FUNC.
void RegisterFunction(const std::string& name, TaskFn fn);

#define RAYTPU_FUNC(name, fn)                                         \
  static const bool _raytpu_reg_##fn = [] {                           \
    ::raytpu::RegisterFunction(name, fn);                             \
    return true;                                                      \
  }()

// Worker entrypoint: connects to the node agent + head given the standard
// worker flags (--head --agent --node-id --store --worker-id), serves
// push_task, executes registered functions, writes results into the shm
// store. Blocks until the agent connection drops. Returns exit code.
int WorkerMain(int argc, char** argv);

// ------------------------------------------------------------- driver
class DriverImpl;

struct ObjectRef {
  std::string id;
};

class Driver {
 public:
  Driver();
  ~Driver();

  // Connect to a running cluster. Discovers a host node (agent address +
  // store path) from the head's node table; the driver must be co-located
  // with that node to attach its shm segment (same-machine requirement,
  // like a raylet-attached reference driver).
  void Connect(const std::string& head_address);

  ObjectRef Put(const Value& v);
  // Blocks until the object is ready or timeout (seconds). Throws
  // RpcError on task failure / timeout.
  Value Get(const ObjectRef& ref, double timeout_s = 60.0);
  // Submit a cross-language task executed by a C++ worker running
  // `worker_bin` (empty = cluster-configured default binary).
  ObjectRef Submit(const std::string& fname, std::vector<Value> args,
                   const std::string& worker_bin = "", double num_cpus = 1.0);
  void Shutdown();

 private:
  DriverImpl* impl_;
};

}  // namespace raytpu
