// Restricted-pickle + msgpack codec for the C++ worker API.
//
// The repo's wire plane is length-prefixed pickle (ray_tpu/cluster/rpc.py)
// and stored objects are pickled payloads with a msgpack size header
// (ray_tpu/core/serialization.py). A native worker therefore needs to
// read and write *restricted* pickle: the closed type set
// {None, bool, int, float, str, bytes, list, tuple, dict} — exactly the
// restriction the reference places on cross-language values (its
// cross_language.py limits args to msgpack-able types; here the envelope
// is pickle, the restriction is the same).
//
// Decode handles the opcodes CPython's protocol-5 pickler emits for these
// types (FRAME/MEMOIZE/BINGET included). Encode declares protocol 3 and
// uses the plain binary opcodes. Anything outside the type set raises
// CodecError — a C++ worker receiving a cloudpickled Python closure fails
// loudly, it does not guess.
#pragma once

#include <cstdint>
#include <cstring>
#include <map>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

namespace raytpu {

struct CodecError : std::runtime_error {
  explicit CodecError(const std::string& m) : std::runtime_error(m) {}
};

// ------------------------------------------------------------- Value
struct Value {
  enum Kind { NONE, BOOL, INT, FLOAT, STR, BYTES, LIST, TUPLE, DICT } kind =
      NONE;
  bool b = false;
  int64_t i = 0;
  double f = 0.0;
  std::string s;  // STR (utf-8) and BYTES share the field
  std::vector<Value> items;                      // LIST / TUPLE
  std::vector<std::pair<Value, Value>> pairs;    // DICT

  Value() = default;
  static Value None() { return Value(); }
  static Value Bool(bool v) { Value x; x.kind = BOOL; x.b = v; return x; }
  static Value Int(int64_t v) { Value x; x.kind = INT; x.i = v; return x; }
  static Value Float(double v) { Value x; x.kind = FLOAT; x.f = v; return x; }
  static Value Str(std::string v) {
    Value x; x.kind = STR; x.s = std::move(v); return x;
  }
  static Value Bytes(std::string v) {
    Value x; x.kind = BYTES; x.s = std::move(v); return x;
  }
  static Value List(std::vector<Value> v = {}) {
    Value x; x.kind = LIST; x.items = std::move(v); return x;
  }
  static Value Tuple(std::vector<Value> v = {}) {
    Value x; x.kind = TUPLE; x.items = std::move(v); return x;
  }
  static Value Dict() { Value x; x.kind = DICT; return x; }

  bool is_none() const { return kind == NONE; }
  bool truthy() const {
    switch (kind) {
      case NONE: return false;
      case BOOL: return b;
      case INT: return i != 0;
      case FLOAT: return f != 0.0;
      case STR: case BYTES: return !s.empty();
      case LIST: case TUPLE: return !items.empty();
      case DICT: return !pairs.empty();
    }
    return false;
  }
  int64_t as_int() const {
    if (kind == INT) return i;
    if (kind == BOOL) return b ? 1 : 0;
    if (kind == FLOAT) return int64_t(f);
    throw CodecError("not an int");
  }
  double as_float() const {
    if (kind == FLOAT) return f;
    if (kind == INT) return double(i);
    throw CodecError("not a float");
  }
  const std::string& as_str() const {
    if (kind != STR && kind != BYTES) throw CodecError("not a str/bytes");
    return s;
  }
  const Value* get(const std::string& key) const {
    if (kind != DICT) return nullptr;
    for (const auto& kv : pairs)
      if (kv.first.kind == STR && kv.first.s == key) return &kv.second;
    return nullptr;
  }
  void set(const std::string& key, Value v) {
    if (kind != DICT) throw CodecError("set() on non-dict");
    for (auto& kv : pairs)
      if (kv.first.kind == STR && kv.first.s == key) {
        kv.second = std::move(v);
        return;
      }
    pairs.emplace_back(Str(key), std::move(v));
  }
};

// -------------------------------------------------------- pickle encode
inline void pickle_encode_into(const Value& v, std::string& out) {
  auto put_u32le = [&out](uint32_t n) {
    char b[4] = {char(n), char(n >> 8), char(n >> 16), char(n >> 24)};
    out.append(b, 4);
  };
  switch (v.kind) {
    case Value::NONE:
      out.push_back('N');
      break;
    case Value::BOOL:
      out.push_back(v.b ? '\x88' : '\x89');
      break;
    case Value::INT:
      if (v.i >= INT32_MIN && v.i <= INT32_MAX) {
        out.push_back('J');
        put_u32le(uint32_t(int32_t(v.i)));
      } else {  // LONG1: n little-endian two's-complement bytes
        out.push_back('\x8a');
        uint64_t u = uint64_t(v.i);
        char tmp[9];
        int n = 0;
        for (; n < 8; n++) tmp[n] = char(u >> (8 * n));
        // trim redundant sign bytes, keep at least 1
        int len = 8;
        while (len > 1) {
          uint8_t top = uint8_t(tmp[len - 1]);
          uint8_t next = uint8_t(tmp[len - 2]);
          if ((top == 0x00 && !(next & 0x80)) ||
              (top == 0xff && (next & 0x80)))
            len--;
          else
            break;
        }
        out.push_back(char(len));
        out.append(tmp, len);
      }
      break;
    case Value::FLOAT: {
      out.push_back('G');
      uint64_t bits;
      std::memcpy(&bits, &v.f, 8);
      for (int k = 7; k >= 0; k--) out.push_back(char(bits >> (8 * k)));
      break;
    }
    case Value::STR:
      out.push_back('X');
      put_u32le(uint32_t(v.s.size()));
      out.append(v.s);
      break;
    case Value::BYTES:
      out.push_back('B');
      put_u32le(uint32_t(v.s.size()));
      out.append(v.s);
      break;
    case Value::LIST:
      out.push_back(']');
      if (!v.items.empty()) {
        out.push_back('(');
        for (const auto& it : v.items) pickle_encode_into(it, out);
        out.push_back('e');
      }
      break;
    case Value::TUPLE:
      out.push_back('(');
      for (const auto& it : v.items) pickle_encode_into(it, out);
      out.push_back('t');
      break;
    case Value::DICT:
      out.push_back('}');
      if (!v.pairs.empty()) {
        out.push_back('(');
        for (const auto& kv : v.pairs) {
          pickle_encode_into(kv.first, out);
          pickle_encode_into(kv.second, out);
        }
        out.push_back('u');
      }
      break;
  }
}

inline std::string pickle_dumps(const Value& v) {
  std::string out;
  out.push_back('\x80');
  out.push_back('\x03');
  pickle_encode_into(v, out);
  out.push_back('.');
  return out;
}

// -------------------------------------------------------- pickle decode
class PickleReader {
 public:
  PickleReader(const uint8_t* data, size_t len) : p_(data), end_(data + len) {}

  Value load() {
    // Stack entries: MARK sentinel is a Value with kind LIST and marker_
    // recorded separately via index stack.
    std::vector<Value> stack;
    std::vector<size_t> marks;
    std::vector<Value> memo;
    while (p_ < end_) {
      uint8_t op = *p_++;
      switch (op) {
        case 0x80:  // PROTO
          need(1);
          p_++;
          break;
        case 0x95:  // FRAME
          need(8);
          p_ += 8;
          break;
        case 0x94:  // MEMOIZE
          if (stack.empty()) throw CodecError("MEMOIZE on empty stack");
          memo.push_back(stack.back());
          break;
        case 'q':  // BINPUT
          need(1);
          setmemo(memo, *p_++, stack);
          break;
        case 'r':  // LONG_BINPUT
          setmemo(memo, u32le(), stack);
          break;
        case 'h': {  // BINGET
          need(1);
          size_t idx = *p_++;
          if (idx >= memo.size()) throw CodecError("BINGET out of range");
          stack.push_back(memo[idx]);
          break;
        }
        case 'j': {  // LONG_BINGET
          size_t idx = u32le();
          if (idx >= memo.size()) throw CodecError("LONG_BINGET range");
          stack.push_back(memo[idx]);
          break;
        }
        case 'N':
          stack.push_back(Value::None());
          break;
        case 0x88:
          stack.push_back(Value::Bool(true));
          break;
        case 0x89:
          stack.push_back(Value::Bool(false));
          break;
        case 'J':
          stack.push_back(Value::Int(int32_t(u32le())));
          break;
        case 'K':
          need(1);
          stack.push_back(Value::Int(*p_++));
          break;
        case 'M': {
          need(2);
          uint16_t n = uint16_t(p_[0]) | (uint16_t(p_[1]) << 8);
          p_ += 2;
          stack.push_back(Value::Int(n));
          break;
        }
        case 0x8a: {  // LONG1
          need(1);
          size_t n = *p_++;
          need(n);
          if (n > 8) throw CodecError("LONG1 too wide for int64");
          uint64_t u = 0;
          for (size_t k = 0; k < n; k++) u |= uint64_t(p_[k]) << (8 * k);
          if (n && n < 8 && (p_[n - 1] & 0x80))  // sign-extend
            u |= ~uint64_t(0) << (8 * n);
          p_ += n;
          stack.push_back(Value::Int(int64_t(u)));
          break;
        }
        case 'G': {  // BINFLOAT, big-endian
          need(8);
          uint64_t bits = 0;
          for (int k = 0; k < 8; k++) bits = (bits << 8) | p_[k];
          p_ += 8;
          double d;
          std::memcpy(&d, &bits, 8);
          stack.push_back(Value::Float(d));
          break;
        }
        case 0x8c: {  // SHORT_BINUNICODE
          need(1);
          size_t n = *p_++;
          stack.push_back(Value::Str(take(n)));
          break;
        }
        case 'X':  // BINUNICODE
          stack.push_back(Value::Str(take(u32le())));
          break;
        case 0x8d:  // BINUNICODE8
          stack.push_back(Value::Str(take(size_t(u64le()))));
          break;
        case 'C': {  // SHORT_BINBYTES
          need(1);
          size_t n = *p_++;
          stack.push_back(Value::Bytes(take(n)));
          break;
        }
        case 'B':  // BINBYTES
          stack.push_back(Value::Bytes(take(u32le())));
          break;
        case 0x8e:  // BINBYTES8
          stack.push_back(Value::Bytes(take(size_t(u64le()))));
          break;
        case 0x96:  // BYTEARRAY8 — surfaces as BYTES
          stack.push_back(Value::Bytes(take(size_t(u64le()))));
          break;
        case ']':
          stack.push_back(Value::List());
          break;
        case '}':
          stack.push_back(Value::Dict());
          break;
        case ')':
          stack.push_back(Value::Tuple());
          break;
        case '(':
          marks.push_back(stack.size());
          break;
        case 'a': {  // APPEND
          if (stack.size() < 2) throw CodecError("APPEND underflow");
          Value item = std::move(stack.back());
          stack.pop_back();
          listref(stack).items.push_back(std::move(item));
          break;
        }
        case 'e': {  // APPENDS
          size_t m = popmark(marks, stack);
          Value& lst = stack[m - 1];
          if (lst.kind != Value::LIST) throw CodecError("APPENDS non-list");
          for (size_t k = m; k < stack.size(); k++)
            lst.items.push_back(std::move(stack[k]));
          stack.resize(m);
          break;
        }
        case 's': {  // SETITEM
          if (stack.size() < 3) throw CodecError("SETITEM underflow");
          Value val = std::move(stack.back());
          stack.pop_back();
          Value key = std::move(stack.back());
          stack.pop_back();
          dictref(stack).pairs.emplace_back(std::move(key), std::move(val));
          break;
        }
        case 'u': {  // SETITEMS
          size_t m = popmark(marks, stack);
          Value& d = stack[m - 1];
          if (d.kind != Value::DICT) throw CodecError("SETITEMS non-dict");
          if ((stack.size() - m) % 2) throw CodecError("odd SETITEMS");
          for (size_t k = m; k < stack.size(); k += 2)
            d.pairs.emplace_back(std::move(stack[k]), std::move(stack[k + 1]));
          stack.resize(m);
          break;
        }
        case 't': {  // TUPLE
          size_t m = popmark(marks, stack);
          Value tup = Value::Tuple();
          for (size_t k = m; k < stack.size(); k++)
            tup.items.push_back(std::move(stack[k]));
          stack.resize(m);
          stack.push_back(std::move(tup));
          break;
        }
        case 0x85:  // TUPLE1
          taken_tuple(stack, 1);
          break;
        case 0x86:  // TUPLE2
          taken_tuple(stack, 2);
          break;
        case 0x87:  // TUPLE3
          taken_tuple(stack, 3);
          break;
        // ---- tolerated object opcodes --------------------------------
        // Error responses carry pickled exception INSTANCES ({"e": exc}).
        // These flatten class/instance machinery to representational
        // strings so the surrounding dict (and its "tb" string) survives.
        case 'c': {  // GLOBAL: module\nname\n
          std::string mod = line(), name = line();
          stack.push_back(Value::Str("<" + mod + "." + name + ">"));
          break;
        }
        case 0x93: {  // STACK_GLOBAL
          if (stack.size() < 2) throw CodecError("STACK_GLOBAL underflow");
          Value name = std::move(stack.back());
          stack.pop_back();
          Value mod = std::move(stack.back());
          stack.pop_back();
          stack.push_back(Value::Str(
              "<" + (mod.kind == Value::STR ? mod.s : "?") + "." +
              (name.kind == Value::STR ? name.s : "?") + ">"));
          break;
        }
        case 'R':      // REDUCE: callable(args) -> opaque marker
        case 0x81: {   // NEWOBJ: cls.__new__(args)
          if (stack.size() < 2) throw CodecError("REDUCE/NEWOBJ underflow");
          Value args = std::move(stack.back());
          stack.pop_back();
          Value callee = std::move(stack.back());
          stack.pop_back();
          std::string desc = callee.kind == Value::STR ? callee.s : "<obj>";
          for (const auto& it : args.items)
            if (it.kind == Value::STR)
              desc += " " + it.s.substr(0, 200);
          stack.push_back(Value::Str(desc));
          break;
        }
        case 0x92: {  // NEWOBJ_EX: cls, args, kwargs
          if (stack.size() < 3) throw CodecError("NEWOBJ_EX underflow");
          stack.pop_back();
          stack.pop_back();  // kwargs, args dropped
          // leave cls marker as the object
          break;
        }
        case 'b': {  // BUILD: apply state to obj — drop the state
          if (stack.size() < 2) throw CodecError("BUILD underflow");
          stack.pop_back();
          break;
        }
        case 0x8f:  // EMPTY_SET — surfaces as list
          stack.push_back(Value::List());
          break;
        case 0x90: {  // ADDITEMS (into set-as-list)
          size_t m = popmark(marks, stack);
          Value& lst = stack[m - 1];
          if (lst.kind != Value::LIST) throw CodecError("ADDITEMS non-list");
          for (size_t k = m; k < stack.size(); k++)
            lst.items.push_back(std::move(stack[k]));
          stack.resize(m);
          break;
        }
        case 0x91: {  // FROZENSET — surfaces as tuple
          size_t m = popmark(marks, stack);
          Value tup = Value::Tuple();
          for (size_t k = m; k < stack.size(); k++)
            tup.items.push_back(std::move(stack[k]));
          stack.resize(m);
          stack.push_back(std::move(tup));
          break;
        }
        case '.':  // STOP
          if (stack.size() != 1) throw CodecError("STOP with deep stack");
          return std::move(stack.back());
        default:
          throw CodecError("unsupported pickle opcode 0x" + hex(op) +
                           " (value outside the cross-language type set?)");
      }
    }
    throw CodecError("pickle stream ended without STOP");
  }

 private:
  const uint8_t* p_;
  const uint8_t* end_;

  static std::string hex(uint8_t b) {
    const char* d = "0123456789abcdef";
    return std::string() + d[b >> 4] + d[b & 15];
  }
  void need(size_t n) const {
    if (size_t(end_ - p_) < n) throw CodecError("truncated pickle");
  }
  uint32_t u32le() {
    need(4);
    uint32_t n = uint32_t(p_[0]) | (uint32_t(p_[1]) << 8) |
                 (uint32_t(p_[2]) << 16) | (uint32_t(p_[3]) << 24);
    p_ += 4;
    return n;
  }
  uint64_t u64le() {
    need(8);
    uint64_t n = 0;
    for (int k = 7; k >= 0; k--) n = (n << 8) | p_[k];
    p_ += 8;
    return n;
  }
  std::string take(size_t n) {
    need(n);
    std::string s(reinterpret_cast<const char*>(p_), n);
    p_ += n;
    return s;
  }
  std::string line() {  // newline-terminated field (GLOBAL operands)
    std::string s;
    while (p_ < end_ && *p_ != '\n') s.push_back(char(*p_++));
    if (p_ >= end_) throw CodecError("unterminated GLOBAL");
    p_++;
    return s;
  }
  static void setmemo(std::vector<Value>& memo, size_t idx,
                      std::vector<Value>& stack) {
    if (stack.empty()) throw CodecError("PUT on empty stack");
    // CPython emits dense consecutive memo indices; a sparse jump means a
    // malformed/hostile frame. Without this cap a 5-byte LONG_BINPUT with
    // idx 0xFFFFFFFF would force a ~4-billion-Value allocation.
    if (idx > memo.size() + 1024) throw CodecError("sparse memo index");
    if (memo.size() <= idx) memo.resize(idx + 1);
    memo[idx] = stack.back();
  }
  static size_t popmark(std::vector<size_t>& marks,
                        std::vector<Value>& stack) {
    if (marks.empty()) throw CodecError("no MARK");
    size_t m = marks.back();
    marks.pop_back();
    if (m > stack.size()) throw CodecError("MARK beyond stack");
    return m;
  }
  static Value& listref(std::vector<Value>& stack) {
    if (stack.empty() || stack.back().kind != Value::LIST)
      throw CodecError("expected list on stack");
    return stack.back();
  }
  static Value& dictref(std::vector<Value>& stack) {
    if (stack.empty() || stack.back().kind != Value::DICT)
      throw CodecError("expected dict on stack");
    return stack.back();
  }
  static void taken_tuple(std::vector<Value>& stack, size_t n) {
    if (stack.size() < n) throw CodecError("TUPLEn underflow");
    Value tup = Value::Tuple();
    for (size_t k = stack.size() - n; k < stack.size(); k++)
      tup.items.push_back(std::move(stack[k]));
    stack.resize(stack.size() - n);
    stack.push_back(std::move(tup));
  }
};

inline Value pickle_loads(const std::string& blob) {
  PickleReader r(reinterpret_cast<const uint8_t*>(blob.data()), blob.size());
  return r.load();
}

// ------------------------------------------- msgpack wire codec (r5)
// The RPC envelope switched from restricted pickle to msgpack
// (ray_tpu/cluster/wire.py). Same closed type set; tuples/sets travel as
// extension types, exceptions as EXT_EXC. The pickle codec above remains
// for object-STORE payloads (user-value plane), which are Python pickle
// by design.

constexpr int8_t kExtTuple = 1;
constexpr int8_t kExtSet = 2;
constexpr int8_t kExtFrozenset = 3;
constexpr int8_t kExtExc = 4;
constexpr int8_t kExtPickle = 127;

inline void msgpack_encode_into(const Value& v, std::string& out);

inline void msgpack_uint_into(uint64_t n, std::string& out) {
  if (n <= 0x7f) {
    out.push_back(char(n));
  } else if (n <= 0xffffffffull) {
    out.push_back('\xce');
    for (int k = 3; k >= 0; k--) out.push_back(char(n >> (8 * k)));
  } else {
    out.push_back('\xcf');
    for (int k = 7; k >= 0; k--) out.push_back(char(n >> (8 * k)));
  }
}

inline void msgpack_str_into(const std::string& s, std::string& out) {
  size_t n = s.size();
  if (n <= 31) {
    out.push_back(char(0xa0 | n));
  } else if (n <= 0xff) {
    out.push_back('\xd9');
    out.push_back(char(n));
  } else if (n <= 0xffff) {
    out.push_back('\xda');
    out.push_back(char(n >> 8));
    out.push_back(char(n));
  } else {
    out.push_back('\xdb');
    for (int k = 3; k >= 0; k--) out.push_back(char(n >> (8 * k)));
  }
  out.append(s);
}

inline void msgpack_ext_into(int8_t type, const std::string& payload,
                             std::string& out) {
  size_t n = payload.size();
  if (n <= 0xff) {
    out.push_back('\xc7');
    out.push_back(char(n));
  } else if (n <= 0xffff) {
    out.push_back('\xc8');
    out.push_back(char(n >> 8));
    out.push_back(char(n));
  } else {
    out.push_back('\xc9');
    for (int k = 3; k >= 0; k--) out.push_back(char(n >> (8 * k)));
  }
  out.push_back(char(type));
  out.append(payload);
}

// Exception extension payload: [module, qualname, args, state, tb] —
// mirrors wire.py's _exc_payload, so the Python peer reconstructs a real
// builtins/ray_tpu exception from a C++ error response.
inline void msgpack_exc_into(const std::string& module,
                             const std::string& qualname,
                             const std::string& msg, const std::string& tb,
                             std::string& out) {
  std::string payload;
  payload.push_back('\x95');  // fixarray(5)
  msgpack_str_into(module, payload);
  msgpack_str_into(qualname, payload);
  payload.push_back('\x91');  // args = [msg]
  msgpack_str_into(msg, payload);
  payload.push_back('\x80');  // state = {}
  msgpack_str_into(tb, payload);
  msgpack_ext_into(kExtExc, payload, out);
}

inline void msgpack_encode_into(const Value& v, std::string& out) {
  switch (v.kind) {
    case Value::NONE:
      out.push_back('\xc0');
      break;
    case Value::BOOL:
      out.push_back(v.b ? '\xc3' : '\xc2');
      break;
    case Value::INT:
      if (v.i >= 0) {
        msgpack_uint_into(uint64_t(v.i), out);
      } else if (v.i >= -32) {
        out.push_back(char(v.i));  // negative fixint
      } else {
        out.push_back('\xd3');
        uint64_t u = uint64_t(v.i);
        for (int k = 7; k >= 0; k--) out.push_back(char(u >> (8 * k)));
      }
      break;
    case Value::FLOAT: {
      out.push_back('\xcb');
      uint64_t bits;
      std::memcpy(&bits, &v.f, 8);
      for (int k = 7; k >= 0; k--) out.push_back(char(bits >> (8 * k)));
      break;
    }
    case Value::STR:
      msgpack_str_into(v.s, out);
      break;
    case Value::BYTES: {
      size_t n = v.s.size();
      if (n <= 0xff) {
        out.push_back('\xc4');
        out.push_back(char(n));
      } else if (n <= 0xffff) {
        out.push_back('\xc5');
        out.push_back(char(n >> 8));
        out.push_back(char(n));
      } else {
        out.push_back('\xc6');
        for (int k = 3; k >= 0; k--) out.push_back(char(n >> (8 * k)));
      }
      out.append(v.s);
      break;
    }
    case Value::LIST: {
      size_t n = v.items.size();
      if (n <= 15) {
        out.push_back(char(0x90 | n));
      } else if (n <= 0xffff) {
        out.push_back('\xdc');
        out.push_back(char(n >> 8));
        out.push_back(char(n));
      } else {
        out.push_back('\xdd');
        for (int k = 3; k >= 0; k--) out.push_back(char(n >> (8 * k)));
      }
      for (const auto& it : v.items) msgpack_encode_into(it, out);
      break;
    }
    case Value::TUPLE: {
      std::string payload;
      Value as_list = Value::List(v.items);
      msgpack_encode_into(as_list, payload);
      msgpack_ext_into(kExtTuple, payload, out);
      break;
    }
    case Value::DICT: {
      size_t n = v.pairs.size();
      if (n <= 15) {
        out.push_back(char(0x80 | n));
      } else if (n <= 0xffff) {
        out.push_back('\xde');
        out.push_back(char(n >> 8));
        out.push_back(char(n));
      } else {
        out.push_back('\xdf');
        for (int k = 3; k >= 0; k--) out.push_back(char(n >> (8 * k)));
      }
      for (const auto& kv : v.pairs) {
        msgpack_encode_into(kv.first, out);
        msgpack_encode_into(kv.second, out);
      }
      break;
    }
  }
}

inline std::string msgpack_dumps(const Value& v) {
  std::string out;
  msgpack_encode_into(v, out);
  return out;
}

class MsgpackReader {
 public:
  MsgpackReader(const uint8_t* data, size_t len, int depth = 0)
      : p_(data), end_(data + len), depth_(depth) {}

  Value load() {
    Value v = item();
    return v;
  }

  // Container recursion bound: item() -> array()/map()/ext() -> item()
  // recurses on the C++ stack, and ext payloads re-enter through a sub-
  // reader that INHERITS the depth — without the cap, ~100k bytes of
  // nested fixarray(1) (well under the frame cap) would overflow the
  // stack and kill the worker instead of raising CodecError.
  static constexpr int kMaxDepth = 64;

 private:
  const uint8_t* p_;
  const uint8_t* end_;
  int depth_;

  struct DepthGuard {
    MsgpackReader* r;
    explicit DepthGuard(MsgpackReader* rd) : r(rd) {
      if (++r->depth_ > kMaxDepth)
        throw CodecError("msgpack nesting too deep");
    }
    ~DepthGuard() { --r->depth_; }
  };

  void need(size_t n) const {
    if (size_t(end_ - p_) < n) throw CodecError("truncated msgpack");
  }
  uint64_t be(size_t n) {
    need(n);
    uint64_t v = 0;
    for (size_t k = 0; k < n; k++) v = (v << 8) | *p_++;
    return v;
  }
  std::string take(size_t n) {
    need(n);
    std::string s(reinterpret_cast<const char*>(p_), n);
    p_ += n;
    return s;
  }
  Value array(size_t n) {
    DepthGuard g(this);
    // Each element is >= 1 byte: a hostile count can't force a huge
    // allocation past what the frame itself could hold.
    if (n > size_t(end_ - p_)) throw CodecError("array count exceeds frame");
    Value v = Value::List();
    v.items.reserve(n);
    for (size_t k = 0; k < n; k++) v.items.push_back(item());
    return v;
  }
  Value map(size_t n) {
    DepthGuard g(this);
    if (n > size_t(end_ - p_)) throw CodecError("map count exceeds frame");
    Value v = Value::Dict();
    v.pairs.reserve(n);
    for (size_t k = 0; k < n; k++) {
      Value key = item();
      Value val = item();
      v.pairs.emplace_back(std::move(key), std::move(val));
    }
    return v;
  }
  Value ext(size_t n) {
    DepthGuard g(this);
    need(1);
    int8_t type = int8_t(*p_++);
    std::string payload = take(n);
    switch (type) {
      case kExtTuple: {
        Value inner = msgpack_sub(payload);
        if (inner.kind != Value::LIST)
          throw CodecError("EXT_TUPLE payload is not an array");
        Value t = Value::Tuple(std::move(inner.items));
        return t;
      }
      case kExtSet: {  // surfaces as list (matches pickle reader)
        Value inner = msgpack_sub(payload);
        if (inner.kind != Value::LIST)
          throw CodecError("EXT_SET payload is not an array");
        return inner;
      }
      case kExtFrozenset: {  // surfaces as tuple
        Value inner = msgpack_sub(payload);
        if (inner.kind != Value::LIST)
          throw CodecError("EXT_FROZENSET payload is not an array");
        return Value::Tuple(std::move(inner.items));
      }
      case kExtExc: {
        // [module, qualname, args, state, tb] -> representational string
        // (same flattening the pickle reader did for exception objects).
        Value inner = msgpack_sub(payload);
        std::string desc = "<";
        if (inner.kind == Value::LIST && inner.items.size() >= 2 &&
            inner.items[0].kind == Value::STR &&
            inner.items[1].kind == Value::STR)
          desc += inner.items[0].s + "." + inner.items[1].s;
        else
          desc += "exception";
        desc += ">";
        if (inner.kind == Value::LIST && inner.items.size() >= 3 &&
            inner.items[2].kind == Value::LIST)
          for (const auto& a : inner.items[2].items)
            if (a.kind == Value::STR) desc += " " + a.s.substr(0, 200);
        return Value::Str(desc);
      }
      default:
        // kExtPickle and unknown exts are refused: the C++ worker never
        // feeds wire bytes to a pickle machine.
        throw CodecError("unsupported msgpack ext type " +
                         std::to_string(int(type)));
    }
  }
  Value msgpack_sub(const std::string& blob) {
    // Sub-reader INHERITS depth: chained ext payloads still recurse on
    // this thread's stack, so a fresh counter would defeat the cap.
    MsgpackReader r(reinterpret_cast<const uint8_t*>(blob.data()),
                    blob.size(), depth_);
    return r.load();
  }

  Value item() {
    need(1);
    uint8_t t = *p_++;
    if (t <= 0x7f) return Value::Int(t);            // positive fixint
    if (t >= 0xe0) return Value::Int(int8_t(t));    // negative fixint
    if ((t & 0xe0) == 0xa0) return Value::Str(take(t & 0x1f));  // fixstr
    if ((t & 0xf0) == 0x90) return array(t & 0x0f);             // fixarray
    if ((t & 0xf0) == 0x80) return map(t & 0x0f);               // fixmap
    switch (t) {
      case 0xc0: return Value::None();
      case 0xc2: return Value::Bool(false);
      case 0xc3: return Value::Bool(true);
      case 0xc4: return Value::Bytes(take(be(1)));
      case 0xc5: return Value::Bytes(take(be(2)));
      case 0xc6: return Value::Bytes(take(be(4)));
      case 0xc7: return ext(be(1));
      case 0xc8: return ext(be(2));
      case 0xc9: return ext(be(4));
      case 0xca: {  // float32
        uint32_t bits = uint32_t(be(4));
        float f;
        std::memcpy(&f, &bits, 4);
        return Value::Float(double(f));
      }
      case 0xcb: {  // float64
        uint64_t bits = be(8);
        double d;
        std::memcpy(&d, &bits, 8);
        return Value::Float(d);
      }
      case 0xcc: return Value::Int(int64_t(be(1)));
      case 0xcd: return Value::Int(int64_t(be(2)));
      case 0xce: return Value::Int(int64_t(be(4)));
      case 0xcf: {
        uint64_t u = be(8);
        if (u > uint64_t(INT64_MAX))
          throw CodecError("uint64 out of int64 range");
        return Value::Int(int64_t(u));
      }
      case 0xd0: return Value::Int(int8_t(be(1)));
      case 0xd1: return Value::Int(int16_t(be(2)));
      case 0xd2: return Value::Int(int32_t(be(4)));
      case 0xd3: return Value::Int(int64_t(be(8)));
      case 0xd4: return ext(1);   // fixext1
      case 0xd5: return ext(2);
      case 0xd6: return ext(4);
      case 0xd7: return ext(8);
      case 0xd8: return ext(16);
      case 0xd9: return Value::Str(take(be(1)));
      case 0xda: return Value::Str(take(be(2)));
      case 0xdb: return Value::Str(take(be(4)));
      case 0xdc: return array(be(2));
      case 0xdd: return array(be(4));
      case 0xde: return map(be(2));
      case 0xdf: return map(be(4));
      default:
        throw CodecError("unsupported msgpack tag 0x" + hex_(t));
    }
  }
  static std::string hex_(uint8_t b) {
    const char* d = "0123456789abcdef";
    return std::string() + d[b >> 4] + d[b & 15];
  }
};

inline Value msgpack_loads(const std::string& blob) {
  MsgpackReader r(reinterpret_cast<const uint8_t*>(blob.data()),
                  blob.size());
  return r.load();
}

// ----------------------------------------------- object meta (msgpack)
// Stored-object metadata is flag byte ('V' value / 'E' error) + msgpack
// {"sizes": [payload_len, buf0_len, ...]} (core/serialization.py). The
// C++ side writes single-part payloads and reads sizes back out.
inline std::string meta_encode(char flag, uint64_t payload_len) {
  std::string m;
  m.push_back(flag);
  m.push_back('\x81');                       // fixmap(1)
  m.push_back('\xa5');                       // fixstr(5)
  m.append("sizes");
  m.push_back('\x91');                       // fixarray(1)
  m.push_back('\xcf');                       // uint64
  for (int k = 7; k >= 0; k--) m.push_back(char(payload_len >> (8 * k)));
  return m;
}

// Returns sizes; flag comes back via *flag. Tolerant of any msgpack int
// widths the Python packer chooses.
inline std::vector<uint64_t> meta_decode(const std::string& meta,
                                         char* flag) {
  if (meta.empty()) throw CodecError("empty object meta");
  *flag = meta[0];
  const uint8_t* p = reinterpret_cast<const uint8_t*>(meta.data()) + 1;
  const uint8_t* end = reinterpret_cast<const uint8_t*>(meta.data()) +
                       meta.size();
  auto need = [&](size_t n) {
    if (size_t(end - p) < n) throw CodecError("truncated meta");
  };
  auto read_uint = [&]() -> uint64_t {
    need(1);
    uint8_t t = *p++;
    if (t <= 0x7f) return t;
    uint64_t v = 0;
    int n = 0;
    if (t == 0xcc) n = 1;
    else if (t == 0xcd) n = 2;
    else if (t == 0xce) n = 4;
    else if (t == 0xcf) n = 8;
    else throw CodecError("unexpected msgpack int tag");
    need(n);
    for (int k = 0; k < n; k++) v = (v << 8) | *p++;
    return v;
  };
  need(1);
  uint8_t t = *p++;
  uint32_t map_n = 0;
  if ((t & 0xf0) == 0x80) map_n = t & 0x0f;
  else if (t == 0xde) { need(2); map_n = (uint32_t(p[0]) << 8) | p[1]; p += 2; }
  else throw CodecError("meta is not a msgpack map");
  std::vector<uint64_t> sizes;
  for (uint32_t m = 0; m < map_n; m++) {
    need(1);
    uint8_t kt = *p++;
    uint32_t klen = 0;
    if ((kt & 0xe0) == 0xa0) klen = kt & 0x1f;
    else if (kt == 0xd9) { need(1); klen = *p++; }
    else throw CodecError("non-str meta key");
    need(klen);
    std::string key(reinterpret_cast<const char*>(p), klen);
    p += klen;
    need(1);
    uint8_t at = *p++;
    uint32_t arr_n = 0;
    if ((at & 0xf0) == 0x90) arr_n = at & 0x0f;
    else if (at == 0xdc) { need(2); arr_n = (uint32_t(p[0]) << 8) | p[1]; p += 2; }
    else throw CodecError("meta value is not an array");
    for (uint32_t k = 0; k < arr_n; k++) {
      uint64_t v = read_uint();
      if (key == "sizes") sizes.push_back(v);
    }
  }
  return sizes;
}

}  // namespace raytpu
