// Multi-threaded stress driver for the shm object store, built under
// ASAN/TSAN by tests (SURVEY §5.2 — the reference runs its C++ core
// under sanitizers in CI; this is the equivalent for our one native
// component). Hammers the API surface — alloc/seal/get/release/pin/
// evict/delete/stats — from many threads sharing one attached handle:
// the production pattern is many processes mapping one segment and
// contending on the process-shared mutex, which the robust-mutex Guard
// serializes identically for threads.
//
// Exit code 0 = no crashes, no sanitizer reports (sanitizers abort), and
// all invariants held.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include <unistd.h>

extern "C" {
void* ts_create(const char* path, uint64_t size, uint64_t num_slots);
void* ts_attach(const char* path);
void ts_detach(void* hp);
int ts_unlink(const char* path);
int64_t ts_alloc(void* hp, const uint8_t* id, uint64_t data_size,
                 uint64_t meta_size);
int ts_seal(void* hp, const uint8_t* id);
int ts_get(void* hp, const uint8_t* id, uint64_t* offset,
           uint64_t* data_size, uint64_t* meta_size);
int ts_release(void* hp, const uint8_t* id);
int64_t ts_release_dead(void* hp, int32_t pid);
int ts_contains(void* hp, const uint8_t* id);
int ts_delete(void* hp, const uint8_t* id);
int ts_abort(void* hp, const uint8_t* id);
int ts_pin(void* hp, const uint8_t* id, int pinned);
int ts_evict(void* hp, const uint8_t* id);
void ts_stats(void* hp, uint64_t* capacity, uint64_t* used,
              uint64_t* num_objects, uint64_t* num_evictions,
              uint64_t* spilled_objects, uint64_t* spilled_bytes);
uint8_t* ts_base_ptr(void* hp);
}

namespace {

constexpr int kIdSize = 20;  // matches shm_store.py ID_SIZE
std::atomic<int> failures{0};

void fill_id(uint8_t* id, int thread, int slot) {
  std::memset(id, 0, kIdSize);
  std::snprintf(reinterpret_cast<char*>(id), kIdSize, "t%02d-o%05d", thread,
                slot);
}

void worker(void* h, int tid, int iters) {
  uint8_t id[kIdSize];
  for (int i = 0; i < iters; i++) {
    fill_id(id, tid, i % 64);
    uint64_t size = 256 + static_cast<uint64_t>(i % 7) * 1024;
    int64_t off = ts_alloc(h, id, size, 8);
    if (off >= 0) {
      // Touch the data region: sanitizers watch these writes.
      std::memset(ts_base_ptr(h) + off, tid & 0xff, size + 8);
      if (ts_seal(h, id) != 0) failures++;
      uint64_t o, ds, ms;
      if (ts_get(h, id, &o, &ds, &ms) == 0) {
        if (ds != size || ms != 8) {
          std::fprintf(stderr, "size mismatch ds=%lu ms=%lu want=%lu\n",
                       static_cast<unsigned long>(ds),
                       static_cast<unsigned long>(ms),
                       static_cast<unsigned long>(size));
          failures++;
        }
        volatile uint8_t sink = ts_base_ptr(h)[o];  // concurrent read
        (void)sink;
        ts_release(h, id);
      }
      switch (i % 5) {
        case 0:
          ts_pin(h, id, 1);
          ts_pin(h, id, 0);
          break;
        case 1:
          ts_evict(h, id);
          break;
        case 2:
          ts_delete(h, id);
          break;
        default:
          ts_contains(h, id);
          break;
      }
    } else if (off == -2) {
      // Another thread owns this id right now: contend on delete.
      ts_delete(h, id);
    }
    if (i % 97 == 0) {
      uint64_t cap, used, n, ev, so, sb;
      ts_stats(h, &cap, &used, &n, &ev, &so, &sb);
      if (used > cap) {
        std::fprintf(stderr, "used %lu > capacity %lu\n",
                     static_cast<unsigned long>(used),
                     static_cast<unsigned long>(cap));
        failures++;
      }
    }
  }
}

}  // namespace

int main(int argc, char** argv) {
  const int n_threads = argc > 1 ? std::atoi(argv[1]) : 8;
  const int iters = argc > 2 ? std::atoi(argv[2]) : 3000;
  std::string path = "/dev/shm/ray_tpu_stress_" + std::to_string(::getpid());
  void* h = ts_create(path.c_str(), 8ull << 20, 4096);
  if (h == nullptr) {
    std::fprintf(stderr, "create failed\n");
    return 2;
  }
  std::vector<std::thread> threads;
  for (int t = 0; t < n_threads; t++) {
    threads.emplace_back(worker, h, t, iters);
  }
  for (auto& th : threads) th.join();
  // Dead-process sweep: whatever pins this pid still holds are
  // reclaimable exactly once, without corrupting the arena.
  ts_release_dead(h, static_cast<int32_t>(::getpid()));
  uint64_t cap, used, n, ev, so, sb;
  ts_stats(h, &cap, &used, &n, &ev, &so, &sb);
  std::fprintf(stderr, "done: %lu objects, %lu/%lu bytes, %lu evictions\n",
               static_cast<unsigned long>(n), static_cast<unsigned long>(used),
               static_cast<unsigned long>(cap),
               static_cast<unsigned long>(ev));
  ts_detach(h);
  ts_unlink(path.c_str());
  return failures.load() == 0 ? 0 : 1;
}
