// C++ peer for the repo's RPC plane (ray_tpu/cluster/rpc.py).
//
// Wire: on accept the server sends "RTPA1" + required-flag + 32-byte
// challenge; when a cluster token is configured the client answers
// HMAC-SHA256(token, challenge) || 32-byte nonce and verifies the
// server's proof over that nonce (mutual auth). After the handshake,
// frames are 4-byte big-endian length || msgpack({"m","a","k"}) with
// responses {"ok": bool, "v": value} / {"ok": false, "e": exc, "tb": str}
// (wire.py codec: tuples/sets/exceptions as msgpack extension types).
//
// The msgpack here is the restricted codec (pyvalue.h); exception
// extensions in error responses flatten to representational strings —
// enough to surface "tb" to the C++ caller — and the pickle extension
// is refused outright: C++ never feeds wire bytes to a pickle machine.
#pragma once

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cstring>
#include <functional>
#include <mutex>
#include <random>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

#include "hashes.h"
#include "pyvalue.h"

namespace raytpu {

struct RpcError : std::runtime_error {
  explicit RpcError(const std::string& m) : std::runtime_error(m) {}
};

inline void send_all(int fd, const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  while (n) {
    ssize_t k = ::send(fd, p, n, MSG_NOSIGNAL);
    if (k <= 0) throw RpcError("send failed");
    p += k;
    n -= size_t(k);
  }
}

inline void recv_exact(int fd, void* data, size_t n) {
  char* p = static_cast<char*>(data);
  while (n) {
    ssize_t k = ::recv(fd, p, n, 0);
    if (k <= 0) throw RpcError("peer closed connection");
    p += k;
    n -= size_t(k);
  }
}

inline void send_frame(int fd, const std::string& blob) {
  uint8_t len[4] = {uint8_t(blob.size() >> 24), uint8_t(blob.size() >> 16),
                    uint8_t(blob.size() >> 8), uint8_t(blob.size())};
  std::string out(reinterpret_cast<char*>(len), 4);
  out += blob;
  send_all(fd, out.data(), out.size());
}

// Mirrors rpc.py MAX_FRAME_BYTES: a corrupt/hostile length prefix must
// not commit us to a multi-GiB allocation.
constexpr uint32_t kMaxFrameBytes = 1u << 30;

inline std::string recv_frame(int fd) {
  uint8_t len[4];
  recv_exact(fd, len, 4);
  uint32_t n = (uint32_t(len[0]) << 24) | (uint32_t(len[1]) << 16) |
               (uint32_t(len[2]) << 8) | uint32_t(len[3]);
  if (n > kMaxFrameBytes) throw RpcError("frame length exceeds cap");
  std::string blob(n, '\0');
  if (n) recv_exact(fd, blob.data(), n);
  return blob;
}

inline void fill_random(uint8_t* out, size_t n) {
  static thread_local std::mt19937_64 rng{std::random_device{}()};
  for (size_t i = 0; i < n; i++) out[i] = uint8_t(rng());
}

// Constant-time 32-byte digest comparison (timing-side-channel hardening
// to match Python's hmac.compare_digest).
inline bool digest_eq32(const uint8_t* a, const uint8_t* b) {
  volatile uint8_t acc = 0;
  for (size_t i = 0; i < 32; i++) acc = uint8_t(acc | (a[i] ^ b[i]));
  return acc == 0;
}

// Client side of the hello/challenge exchange (rpc.py _handshake_server).
inline void handshake_client(int fd, const std::string& token) {
  char hello[38];
  recv_exact(fd, hello, 38);
  if (std::memcmp(hello, "RTPA1", 5) != 0)
    throw RpcError("bad hello magic from peer");
  bool required = hello[5] == '\x01';
  if (!required) {
    // Downgrade guard (mirrors rpc.py AuthError): a token-configured
    // client must never talk to an unauthenticated server — a spoofed
    // listener on a dead peer's port would otherwise feed us frames.
    if (!token.empty())
      throw RpcError("peer does not require the cluster token this "
                     "client is configured with (spoofed server?)");
    return;
  }
  if (token.empty())
    throw RpcError("cluster requires a token but none is configured "
                   "(set RAY_TPU_CLUSTER_TOKEN)");
  uint8_t digest[32], nonce[32];
  hmac_sha256(reinterpret_cast<const uint8_t*>(token.data()), token.size(),
              reinterpret_cast<const uint8_t*>(hello + 6), 32, digest);
  fill_random(nonce, 32);
  uint8_t frame[64];
  std::memcpy(frame, digest, 32);
  std::memcpy(frame + 32, nonce, 32);
  send_all(fd, frame, 64);
  uint8_t verdict[33];
  recv_exact(fd, verdict, 33);
  if (verdict[0] != 1) throw RpcError("cluster token rejected");
  // Proof is bound to challenge || client_nonce so it cannot be harvested
  // by relaying our nonce under a different server challenge.
  uint8_t both[64];
  std::memcpy(both, hello + 6, 32);
  std::memcpy(both + 32, nonce, 32);
  uint8_t proof[32];
  hmac_sha256(reinterpret_cast<const uint8_t*>(token.data()), token.size(),
              both, 64, proof);
  if (!digest_eq32(verdict + 1, proof))
    throw RpcError("server failed mutual auth (spoofed head?)");
}

// Server side (accepting connections from the node agent / head probes).
inline bool handshake_server(int fd, const std::string& token) {
  uint8_t challenge[32];
  fill_random(challenge, 32);
  std::string hello = "RTPA1";
  hello.push_back(token.empty() ? '\x00' : '\x01');
  hello.append(reinterpret_cast<char*>(challenge), 32);
  try {
    send_all(fd, hello.data(), hello.size());
    if (token.empty()) return true;
    uint8_t frame[64];
    recv_exact(fd, frame, 64);
    uint8_t expect[32];
    hmac_sha256(reinterpret_cast<const uint8_t*>(token.data()), token.size(),
                challenge, 32, expect);
    bool ok = digest_eq32(frame, expect);
    uint8_t verdict[33];
    verdict[0] = ok ? 1 : 0;
    // Only a client that proved token knowledge receives a proof, and the
    // proof covers challenge || client_nonce (anti-relay; see rpc.py).
    std::memset(verdict + 1, 0, 32);
    if (ok) {
      uint8_t both[64];
      std::memcpy(both, challenge, 32);
      std::memcpy(both + 32, frame + 32, 32);
      hmac_sha256(reinterpret_cast<const uint8_t*>(token.data()), token.size(),
                  both, 64, verdict + 1);
    }
    send_all(fd, verdict, 33);
    return ok;
  } catch (const RpcError&) {
    return false;
  }
}

inline std::pair<std::string, int> split_address(const std::string& addr) {
  auto pos = addr.rfind(':');
  if (pos == std::string::npos) throw RpcError("bad address: " + addr);
  return {addr.substr(0, pos), std::stoi(addr.substr(pos + 1))};
}

inline int tcp_connect(const std::string& addr) {
  auto [host, port] = split_address(addr);
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) throw RpcError("socket() failed");
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(uint16_t(port));
  if (inet_pton(AF_INET, host.c_str(), &sa.sin_addr) != 1) {
    ::close(fd);
    throw RpcError("bad host: " + host);
  }
  if (::connect(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0) {
    ::close(fd);
    throw RpcError("connect to " + addr + " refused");
  }
  return fd;
}

// One connection; NOT thread-safe — callers hold their own channel or lock
// (matches rpc.py's per-thread connection pooling).
class RpcChannel {
 public:
  RpcChannel(std::string address, std::string token)
      : address_(std::move(address)), token_(std::move(token)) {}
  ~RpcChannel() { close(); }

  Value call(const std::string& method, std::vector<Value> args,
             Value kwargs = Value::Dict()) {
    std::lock_guard<std::mutex> g(mu_);
    ensure_connected();
    Value req = Value::Dict();
    req.set("m", Value::Str(method));
    req.set("a", Value::Tuple(std::move(args)));
    req.set("k", std::move(kwargs));
    std::string resp;
    try {
      send_frame(fd_, msgpack_dumps(req));
      resp = recv_frame(fd_);
    } catch (const RpcError&) {
      close();  // transport failure: reconnect on the next call
      throw;
    }
    try {
      Value r = msgpack_loads(resp);
      const Value* ok = r.get("ok");
      if (ok && ok->truthy()) {
        const Value* v = r.get("v");
        return v ? *v : Value::None();
      }
      const Value* tb = r.get("tb");
      // Peer-raised: the connection stays usable (frame boundary intact).
      throw RpcError("rpc " + method + " raised on peer:\n" +
                     (tb && tb->kind == Value::STR ? tb->s : "<no traceback>"));
    } catch (const CodecError& e) {
      // Response held objects outside the restricted set (possible for
      // exotic handler returns). The connection is still framed
      // correctly, but the value is unusable from C++.
      throw RpcError("rpc " + method + ": undecodable response (" +
                     e.what() + ")");
    }
  }

  void close() {
    if (fd_ >= 0) {
      ::close(fd_);
      fd_ = -1;
    }
  }

 private:
  void ensure_connected() {
    if (fd_ >= 0) return;
    fd_ = tcp_connect(address_);
    try {
      handshake_client(fd_, token_);
    } catch (...) {
      close();
      throw;
    }
  }

  std::string address_;
  std::string token_;
  std::mutex mu_;
  int fd_ = -1;
};

// Serves rpc_<method> handlers; thread per connection like rpc.py.
class RpcServer {
 public:
  using Handler =
      std::function<Value(const std::string&, const Value& /*args tuple*/,
                          const Value& /*kwargs dict*/)>;

  RpcServer(Handler handler, std::string token)
      : handler_(std::move(handler)), token_(std::move(token)) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) throw RpcError("socket() failed");
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    sa.sin_port = 0;
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0)
      throw RpcError("bind failed");
    if (::listen(listen_fd_, 128) != 0) throw RpcError("listen failed");
    socklen_t slen = sizeof(sa);
    getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&sa), &slen);
    address_ = "127.0.0.1:" + std::to_string(ntohs(sa.sin_port));
    accept_thread_ = std::thread([this] { accept_loop(); });
  }

  ~RpcServer() { stop(); }

  const std::string& address() const { return address_; }

  void stop() {
    bool was = stopped_.exchange(true);
    if (!was && listen_fd_ >= 0) ::shutdown(listen_fd_, SHUT_RDWR);
    if (accept_thread_.joinable()) accept_thread_.join();
    if (listen_fd_ >= 0) {
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
  }

 private:
  void accept_loop() {
    while (!stopped_) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) return;
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::thread([this, fd] { serve_conn(fd); }).detach();
    }
  }

  void serve_conn(int fd) {
    try {
      if (!handshake_server(fd, token_)) {
        ::close(fd);
        return;
      }
      while (true) {
        std::string blob = recv_frame(fd);
        Value req = msgpack_loads(blob);
        const Value* m = req.get("m");
        const Value* a = req.get("a");
        const Value* k = req.get("k");
        Value resp = Value::Dict();
        try {
          Value out = handler_(m ? m->as_str() : "",
                               a ? *a : Value::Tuple(),
                               k ? *k : Value::Dict());
          resp.set("ok", Value::Bool(true));
          resp.set("v", std::move(out));
        } catch (const std::exception& e) {
          // Python peers expect "e" to be an exception instance; the
          // msgpack exception extension reconstructs builtins.RuntimeError
          // at the Python call site (wire.py _decode_exc).
          send_error(fd, e.what());
          continue;
        }
        send_frame(fd, msgpack_dumps(resp));
      }
    } catch (const std::exception&) {
      // connection closed or protocol error — drop the connection
    }
    ::close(fd);
  }

  // {"ok": False, "e": <exception ext>, "tb": str} — msgpack is
  // compositional (no memo), so the pre-encoded ext splices in directly.
  void send_error(int fd, const std::string& what) {
    std::string out;
    out.push_back('\x83');  // fixmap(3)
    msgpack_str_into("ok", out);
    out.push_back('\xc2');  // false
    msgpack_str_into("tb", out);
    msgpack_str_into(what, out);
    msgpack_str_into("e", out);
    msgpack_exc_into("builtins", "RuntimeError", what, what, out);
    send_frame(fd, out);
  }

  Handler handler_;
  std::string token_;
  std::string address_;
  int listen_fd_ = -1;
  std::atomic<bool> stopped_{false};
  std::thread accept_thread_;
};

}  // namespace raytpu
