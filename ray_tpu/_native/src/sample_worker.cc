// Example C++ worker binary (reference: ``cpp/src/ray/worker/`` default
// worker + the api.h examples). Registers a handful of cross-language
// functions and hands control to raytpu::WorkerMain. The node agent
// spawns this binary for tasks submitted with lang="cpp"
// (ray_tpu.cross_language.cpp_function / raytpu::Driver::Submit).
//
// Build: ray_tpu._native.build.build_cpp_worker() →
//   g++ -O2 sample_worker.cc raytpu_runtime.cc shm_store.cc
//
// With --driver <head_addr> it instead runs as a C++ DRIVER: submits
// tasks to the cluster (executed by C++ workers of this same binary) and
// prints results — the C++-to-C++ path with no Python in the loop.
#include <cinttypes>
#include <cstdio>
#include <string>

#include "raytpu.h"

using raytpu::Value;

static Value Add(const std::vector<Value>& args) {
  int64_t s = 0;
  for (const auto& a : args) s += a.as_int();
  return Value::Int(s);
}
RAYTPU_FUNC("add", Add);

static Value Concat(const std::vector<Value>& args) {
  std::string out;
  for (const auto& a : args) out += a.as_str();
  return Value::Str(out);
}
RAYTPU_FUNC("concat", Concat);

static Value Fib(const std::vector<Value>& args) {
  int64_t n = args.at(0).as_int();
  int64_t a = 0, b = 1;
  for (int64_t i = 0; i < n; i++) {
    int64_t t = a + b;
    a = b;
    b = t;
  }
  return Value::Int(a);
}
RAYTPU_FUNC("fib", Fib);

// Echoes its (restricted-type) argument back — exercises the full codec
// round trip for nested lists/dicts/bytes.
static Value Echo(const std::vector<Value>& args) {
  return args.empty() ? Value::None() : args[0];
}
RAYTPU_FUNC("echo", Echo);

static Value Boom(const std::vector<Value>&) {
  throw std::runtime_error("intentional C++ task failure");
}
RAYTPU_FUNC("boom", Boom);

int main(int argc, char** argv) {
  if (argc >= 3 && std::string(argv[1]) == "--driver") {
    // C++ driver demo: C++ → scheduler → C++ worker → shm store → C++.
    raytpu::Driver d;
    d.Connect(argv[2]);
    std::string bin = argc >= 4 ? argv[3] : "";
    auto r1 = d.Submit("add", {Value::Int(40), Value::Int(2)}, bin);
    auto r2 = d.Submit("fib", {Value::Int(20)}, bin);
    auto put = d.Put(Value::Str("cpp-put"));
    printf("add=%" PRId64 "\n", d.Get(r1).as_int());
    printf("fib=%" PRId64 "\n", d.Get(r2).as_int());
    printf("put=%s\n", d.Get(put).as_str().c_str());
    d.Shutdown();
    return 0;
  }
  return raytpu::WorkerMain(argc, argv);
}
