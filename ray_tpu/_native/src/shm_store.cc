// shm_store — per-node shared-memory immutable object store.
//
// TPU-native analog of the reference's plasma store
// (reference: src/ray/object_manager/plasma/store.h:55,
//  object_lifecycle_manager.h:101, eviction_policy.h:105).
//
// Design: ONE mmap'd file (under /dev/shm) shared by every process on the
// node (node daemon + workers + driver). All metadata — object table,
// allocator free state, LRU clock — lives INSIDE the segment, guarded by a
// process-shared robust pthread mutex. Clients attach by mmapping the same
// file, so Create/Seal/Get/Release are plain library calls (no store daemon
// round-trip, no fd passing — the fd-passing dance in plasma's fling.cc
// exists because plasma allocates per-object maps; a single fixed segment
// makes offsets process-portable).
//
// Object lifecycle: ALLOC (unsealed, writable by creator) -> SEAL (immutable,
// readable by all) -> refcounted Get/Release -> DELETE or LRU-evict when
// refcount hits zero and space is needed (mirrors plasma eviction_policy).
//
// Allocation: block-header first-fit arena with lazy coalescing of adjacent
// free blocks during the allocation scan (plasma uses dlmalloc; first-fit is
// adequate at the object counts a node sees and is robust in shared memory).

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <new>

namespace {

constexpr uint64_t kMagic = 0x5452415953544f52ULL;  // "TRAYSTOR"
constexpr uint32_t kIdSize = 20;
constexpr uint64_t kAlign = 64;  // cache-line align objects; helps DMA/H2D

enum SlotState : uint32_t {
  SLOT_EMPTY = 0,
  SLOT_USED = 1,
  SLOT_TOMBSTONE = 2,
};

// Read pins are tracked PER PROCESS so the node agent can reclaim the
// pins of a crashed worker (plasma handles the same problem via client
// disconnect cleanup in the store daemon). Up to kPinSlots distinct
// processes are tracked exactly; further pinners fall into an overflow
// count that a crash cannot reclaim (rare: >6 concurrent readers of one
// object on one node).
constexpr int kPinSlots = 6;
struct PinEntry {
  int32_t pid;
  int32_t count;
};

struct Slot {
  uint8_t id[kIdSize];
  uint32_t state;     // SlotState
  uint32_t sealed;    // 0 = created/unsealed, 1 = sealed
  int64_t refcount;   // total cross-process pins (sum of entries+overflow)
  PinEntry pins[kPinSlots];
  int64_t overflow_pins;
  uint32_t creator_pid;  // for aborting creations of crashed processes
  uint64_t offset;    // data offset from segment base
  uint64_t data_size;
  uint64_t meta_size;
  uint64_t lru_tick;  // last-touch clock for eviction
  // Primary-copy pin: set while the cluster ref-counter still references
  // the object. Pinned objects are never LRU-evicted (data would be LOST);
  // they may be SPILLED to disk (data preserved) via ts_evict after the
  // node agent wrote them out (local_object_manager.h:110 analog).
  uint32_t pinned;
  uint32_t _pad;
};

// Arena block header, placed immediately before each block's payload.
struct Block {
  uint64_t size;  // payload bytes (excluding header)
  uint32_t free;  // 1 = free
  uint32_t magic; // 0xB10CB10C guard
};
constexpr uint32_t kBlockMagic = 0xB10CB10C;

struct Header {
  uint64_t magic;
  uint64_t segment_size;
  uint64_t num_slots;
  uint64_t slots_offset;
  uint64_t arena_offset;
  uint64_t arena_size;
  uint64_t lru_clock;
  uint64_t num_objects;
  uint64_t bytes_in_use;   // payload bytes of live objects
  uint64_t num_evictions;
  pthread_mutex_t mutex;
};

struct Handle {
  uint8_t* base;
  uint64_t size;
  int fd;
  Header* hdr() const { return reinterpret_cast<Header*>(base); }
  Slot* slots() const {
    return reinterpret_cast<Slot*>(base + hdr()->slots_offset);
  }
};

uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

uint64_t id_hash(const uint8_t* id) {
  // FNV-1a over the 20-byte id.
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdSize; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

class Guard {
 public:
  explicit Guard(Header* h) : h_(h) {
    int rc = pthread_mutex_lock(&h_->mutex);
    if (rc == EOWNERDEAD) {
      // A process died holding the lock; state is still consistent for our
      // coarse-grained critical sections (each op completes its writes
      // before unlocking the only partially-written thing is an unsealed
      // object, which its dead creator can never seal => abortable).
      pthread_mutex_consistent(&h_->mutex);
    }
  }
  ~Guard() { pthread_mutex_unlock(&h_->mutex); }

 private:
  Header* h_;
};

Slot* find_slot(Handle* h, const uint8_t* id) {
  Header* hdr = h->hdr();
  Slot* slots = h->slots();
  uint64_t n = hdr->num_slots;
  uint64_t i = id_hash(id) % n;
  for (uint64_t probe = 0; probe < n; probe++, i = (i + 1) % n) {
    Slot& s = slots[i];
    if (s.state == SLOT_EMPTY) return nullptr;
    if (s.state == SLOT_USED && memcmp(s.id, id, kIdSize) == 0) return &s;
  }
  return nullptr;
}

Slot* insert_slot(Handle* h, const uint8_t* id) {
  Header* hdr = h->hdr();
  Slot* slots = h->slots();
  uint64_t n = hdr->num_slots;
  uint64_t i = id_hash(id) % n;
  Slot* tomb = nullptr;
  for (uint64_t probe = 0; probe < n; probe++, i = (i + 1) % n) {
    Slot& s = slots[i];
    if (s.state == SLOT_EMPTY) {
      Slot* t = tomb ? tomb : &s;
      memcpy(t->id, id, kIdSize);
      t->state = SLOT_USED;
      return t;
    }
    if (s.state == SLOT_TOMBSTONE && !tomb) tomb = &s;
    if (s.state == SLOT_USED && memcmp(s.id, id, kIdSize) == 0) return nullptr;
  }
  if (tomb) {
    memcpy(tomb->id, id, kIdSize);
    tomb->state = SLOT_USED;
    return tomb;
  }
  return nullptr;  // table full
}

Block* block_at(Handle* h, uint64_t off) {
  return reinterpret_cast<Block*>(h->base + off);
}

// First-fit scan with lazy coalescing. Returns payload offset or 0.
uint64_t arena_alloc(Handle* h, uint64_t want) {
  Header* hdr = h->hdr();
  want = align_up(want, kAlign);
  uint64_t off = hdr->arena_offset;
  uint64_t end = hdr->arena_offset + hdr->arena_size;
  while (off < end) {
    Block* b = block_at(h, off);
    if (b->magic != kBlockMagic) return 0;  // corruption; bail
    if (b->free) {
      // Coalesce following free blocks.
      uint64_t next = off + sizeof(Block) + b->size;
      while (next < end) {
        Block* nb = block_at(h, next);
        if (nb->magic != kBlockMagic || !nb->free) break;
        b->size += sizeof(Block) + nb->size;
        nb->magic = 0;
        next = off + sizeof(Block) + b->size;
      }
      if (b->size >= want) {
        // Split if the tail is big enough to hold a header + one line.
        if (b->size >= want + sizeof(Block) + kAlign) {
          uint64_t tail_off = off + sizeof(Block) + want;
          Block* tail = block_at(h, tail_off);
          tail->size = b->size - want - sizeof(Block);
          tail->free = 1;
          tail->magic = kBlockMagic;
          b->size = want;
        }
        b->free = 0;
        return off + sizeof(Block);
      }
    }
    off += sizeof(Block) + b->size;
  }
  return 0;
}

void arena_free(Handle* h, uint64_t payload_off) {
  Block* b = block_at(h, payload_off - sizeof(Block));
  if (b->magic != kBlockMagic) return;
  b->free = 1;
}

void pin_add(Slot* s, int32_t pid) {
  s->refcount++;
  for (int i = 0; i < kPinSlots; i++) {
    if (s->pins[i].count > 0 && s->pins[i].pid == pid) {
      s->pins[i].count++;
      return;
    }
  }
  for (int i = 0; i < kPinSlots; i++) {
    if (s->pins[i].count == 0) {
      s->pins[i].pid = pid;
      s->pins[i].count = 1;
      return;
    }
  }
  s->overflow_pins++;
}

void pin_sub(Slot* s, int32_t pid) {
  if (s->refcount > 0) s->refcount--;
  for (int i = 0; i < kPinSlots; i++) {
    if (s->pins[i].count > 0 && s->pins[i].pid == pid) {
      s->pins[i].count--;
      return;
    }
  }
  if (s->overflow_pins > 0) s->overflow_pins--;
}

void delete_slot(Handle* h, Slot* s) {
  Header* hdr = h->hdr();
  arena_free(h, s->offset);
  hdr->bytes_in_use -= align_up(s->data_size + s->meta_size, kAlign);
  hdr->num_objects--;
  s->state = SLOT_TOMBSTONE;
  s->sealed = 0;
  s->refcount = 0;
  memset(s->pins, 0, sizeof(s->pins));
  s->overflow_pins = 0;
  s->creator_pid = 0;
  s->pinned = 0;
}

// Evict the single least-recently-used sealed, unreferenced, UNPINNED
// object. Returns true if a victim was evicted (caller retries allocation).
// Pinned (primary) copies are spill-only — losing them would drop the only
// copy of a still-referenced object.
bool evict_one(Handle* h) {
  Header* hdr = h->hdr();
  Slot* victim = nullptr;
  Slot* slots = h->slots();
  for (uint64_t i = 0; i < hdr->num_slots; i++) {
    Slot& s = slots[i];
    if (s.state == SLOT_USED && s.sealed && s.refcount == 0 && !s.pinned) {
      if (!victim || s.lru_tick < victim->lru_tick) victim = &s;
    }
  }
  if (!victim) return false;
  delete_slot(h, victim);
  hdr->num_evictions++;
  return true;
}

}  // namespace

extern "C" {

void* ts_create(const char* path, uint64_t size, uint64_t num_slots) {
  if (size < (1u << 20)) size = 1u << 20;
  if (num_slots == 0) {
    // Size the table so it stays well under the segment: one slot per 4KB
    // of capacity, clamped to [1024, 65536].
    num_slots = size / 4096;
    if (num_slots > (1 << 16)) num_slots = 1 << 16;
    if (num_slots < 1024) num_slots = 1024;
  }
  // The slot table + header must leave a usable arena.
  {
    uint64_t meta_bytes = align_up(sizeof(Header), kAlign) +
                          align_up(num_slots * sizeof(Slot), kAlign);
    if (meta_bytes + (1u << 16) > size) return nullptr;
  }
  int fd = open(path, O_RDWR | O_CREAT | O_EXCL, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)size) != 0) {
    close(fd);
    unlink(path);
    return nullptr;
  }
  // MAP_POPULATE prefaults the tmpfs pages at creation (node start, off
  // the hot path) so a first big put pays minor faults, not page zeroing
  // — first-touch was costing ~5x on a cold 256 MiB put.
  void* base = mmap(nullptr, size, PROT_READ | PROT_WRITE,
                    MAP_SHARED | MAP_POPULATE, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    unlink(path);
    return nullptr;
  }
  Handle* h = new Handle{reinterpret_cast<uint8_t*>(base), size, fd};
  Header* hdr = h->hdr();
  memset(hdr, 0, sizeof(Header));
  hdr->segment_size = size;
  hdr->num_slots = num_slots;
  hdr->slots_offset = align_up(sizeof(Header), kAlign);
  uint64_t slots_bytes = align_up(num_slots * sizeof(Slot), kAlign);
  hdr->arena_offset = hdr->slots_offset + slots_bytes;
  hdr->arena_size = size - hdr->arena_offset;
  memset(h->base + hdr->slots_offset, 0, slots_bytes);
  // One giant free block spanning the arena.
  Block* b = block_at(h, hdr->arena_offset);
  b->size = hdr->arena_size - sizeof(Block);
  b->free = 1;
  b->magic = kBlockMagic;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&hdr->mutex, &attr);
  pthread_mutexattr_destroy(&attr);

  __sync_synchronize();
  hdr->magic = kMagic;  // publish last
  return h;
}

void* ts_attach(const char* path) {
  int fd = open(path, O_RDWR);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < (off_t)sizeof(Header)) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Handle* h =
      new Handle{reinterpret_cast<uint8_t*>(base), (uint64_t)st.st_size, fd};
  // Wait (bounded) for the creator to publish the magic.
  for (int i = 0; i < 1000 && h->hdr()->magic != kMagic; i++) usleep(1000);
  if (h->hdr()->magic != kMagic) {
    munmap(base, (size_t)st.st_size);
    close(fd);
    delete h;
    return nullptr;
  }
  return h;
}

void ts_detach(void* hp) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  munmap(h->base, h->size);
  close(h->fd);
  delete h;
}

int ts_unlink(const char* path) { return unlink(path); }

// Allocate space for an object. Returns payload offset (>0), or:
//   -1 out of memory (even after eviction)   -2 already exists
//   -3 table full                            -4 too large for segment
int64_t ts_alloc(void* hp, const uint8_t* id, uint64_t data_size,
                 uint64_t meta_size) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  Header* hdr = h->hdr();
  uint64_t want = data_size + meta_size;
  if (want == 0) want = 1;
  if (align_up(want, kAlign) + sizeof(Block) > hdr->arena_size) return -4;
  Guard g(hdr);
  if (find_slot(h, id)) return -2;
  uint64_t off = arena_alloc(h, want);
  while (!off) {
    if (!evict_one(h)) return -1;
    off = arena_alloc(h, want);
  }
  Slot* s = insert_slot(h, id);
  if (!s) {
    arena_free(h, off);
    return -3;
  }
  s->sealed = 0;
  s->refcount = 0;
  memset(s->pins, 0, sizeof(s->pins));
  s->overflow_pins = 0;
  pin_add(s, (int32_t)getpid());  // creator holds a pin until seal/abort
  s->creator_pid = (uint32_t)getpid();
  s->pinned = 0;
  s->offset = off;
  s->data_size = data_size;
  s->meta_size = meta_size;
  s->lru_tick = ++hdr->lru_clock;
  hdr->num_objects++;
  hdr->bytes_in_use += align_up(want, kAlign);
  return (int64_t)off;
}

int ts_seal(void* hp, const uint8_t* id) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  Guard g(h->hdr());
  Slot* s = find_slot(h, id);
  if (!s) return -1;
  if (s->sealed) return -2;
  s->sealed = 1;
  pin_sub(s, (int32_t)getpid());  // drop creator pin
  s->lru_tick = ++h->hdr()->lru_clock;
  return 0;
}

// Look up a sealed object, pinning it. 0 on success.
int ts_get(void* hp, const uint8_t* id, uint64_t* offset, uint64_t* data_size,
           uint64_t* meta_size) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  Guard g(h->hdr());
  Slot* s = find_slot(h, id);
  if (!s || !s->sealed) return -1;
  pin_add(s, (int32_t)getpid());
  s->lru_tick = ++h->hdr()->lru_clock;
  *offset = s->offset;
  *data_size = s->data_size;
  *meta_size = s->meta_size;
  return 0;
}

int ts_release(void* hp, const uint8_t* id) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  Guard g(h->hdr());
  Slot* s = find_slot(h, id);
  if (!s) return -1;
  pin_sub(s, (int32_t)getpid());
  return 0;
}

// Reclaim every pin held by a (dead) process and abort its unsealed
// creations. Returns the number of slots touched. The node agent calls
// this when it reaps a worker so crashed readers can't leak refcounts
// (plasma client-disconnect cleanup analog).
int64_t ts_release_dead(void* hp, int32_t pid) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  Guard g(h->hdr());
  Header* hdr = h->hdr();
  Slot* slots = h->slots();
  int64_t touched = 0;
  for (uint64_t i = 0; i < hdr->num_slots; i++) {
    Slot& s = slots[i];
    if (s.state != SLOT_USED) continue;
    bool hit = false;
    for (int p = 0; p < kPinSlots; p++) {
      if (s.pins[p].count > 0 && s.pins[p].pid == pid) {
        s.refcount -= s.pins[p].count;
        if (s.refcount < 0) s.refcount = 0;
        s.pins[p].count = 0;
        hit = true;
      }
    }
    if (!s.sealed && s.creator_pid == (uint32_t)pid) {
      delete_slot(h, &s);
      hit = true;
    }
    if (hit) touched++;
  }
  return touched;
}

int ts_contains(void* hp, const uint8_t* id) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  Guard g(h->hdr());
  Slot* s = find_slot(h, id);
  return (s && s->sealed) ? 1 : 0;
}

// Delete a sealed object (refcount must be 0) or abort an unsealed one.
int ts_delete(void* hp, const uint8_t* id) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  Guard g(h->hdr());
  Slot* s = find_slot(h, id);
  if (!s) return -1;
  if (s->sealed && s->refcount > 0) return -2;  // pinned
  delete_slot(h, s);
  return 0;
}

// Abort an in-progress (unsealed) creation by the creator.
int ts_abort(void* hp, const uint8_t* id) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  Guard g(h->hdr());
  Slot* s = find_slot(h, id);
  if (!s || s->sealed) return -1;
  delete_slot(h, s);
  return 0;
}

// Set/clear the primary-copy pin (cluster ref-counter protection).
int ts_pin(void* hp, const uint8_t* id, int pinned) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  Guard g(h->hdr());
  Slot* s = find_slot(h, id);
  if (!s) return -1;
  s->pinned = pinned ? 1 : 0;
  return 0;
}

// Per-object metadata for spill-candidate selection.
int ts_info(void* hp, const uint8_t* id, uint64_t* data_size,
            uint64_t* meta_size, int64_t* refcount, uint32_t* pinned,
            uint64_t* lru_tick) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  Guard g(h->hdr());
  Slot* s = find_slot(h, id);
  if (!s || !s->sealed) return -1;
  *data_size = s->data_size;
  *meta_size = s->meta_size;
  *refcount = s->refcount;
  *pinned = s->pinned;
  *lru_tick = s->lru_tick;
  return 0;
}

// Remove a sealed object regardless of its pin (the caller has preserved
// the data elsewhere, e.g. spilled it to disk). Still refuses if actively
// read (refcount > 0).
int ts_evict(void* hp, const uint8_t* id) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  Guard g(h->hdr());
  Slot* s = find_slot(h, id);
  if (!s || !s->sealed) return -1;
  if (s->refcount > 0) return -2;
  delete_slot(h, s);
  return 0;
}

void ts_stats(void* hp, uint64_t* capacity, uint64_t* used,
              uint64_t* num_objects, uint64_t* num_evictions) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  Guard g(h->hdr());
  Header* hdr = h->hdr();
  *capacity = hdr->arena_size;
  *used = hdr->bytes_in_use;
  *num_objects = hdr->num_objects;
  *num_evictions = hdr->num_evictions;
}

// Copy up to max_ids sealed object ids into out (max_ids * 20 bytes).
uint64_t ts_list(void* hp, uint8_t* out, uint64_t max_ids) {
  Handle* h = reinterpret_cast<Handle*>(hp);
  Guard g(h->hdr());
  Header* hdr = h->hdr();
  Slot* slots = h->slots();
  uint64_t n = 0;
  for (uint64_t i = 0; i < hdr->num_slots && n < max_ids; i++) {
    if (slots[i].state == SLOT_USED && slots[i].sealed) {
      memcpy(out + n * kIdSize, slots[i].id, kIdSize);
      n++;
    }
  }
  return n;
}

uint8_t* ts_base_ptr(void* hp) {
  return reinterpret_cast<Handle*>(hp)->base;
}

}  // extern "C"
