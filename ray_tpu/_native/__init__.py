"""Native (C++) components: shm object store, scheduling policy.

Built on demand by ``ray_tpu._native.build`` (reference analog: the bazel
targets under ``src/ray/``)."""
