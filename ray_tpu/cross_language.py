"""Cross-language task invocation (reference: ``python/ray/cross_language.py``).

``cpp_function(name)`` returns a handle whose ``.remote(*args)`` submits a
task executed by a native C++ worker (``_native/src/raytpu.h`` /
``raytpu_runtime.cc``) — the node agent spawns the configured worker
binary (``config.cpp_worker_bin`` / ``RAY_TPU_CPP_WORKER_BIN`` or the
``worker_bin=`` override) and the result lands in the shm object store
like any other object; ``ray_tpu.get`` reads it as a plain Python value.

Values crossing the language boundary are restricted to
{None, bool, int, float, str, bytes, list, tuple, dict} — the same
restriction the reference places on cross-language calls (its args must
be msgpack-able); anything else raises ``TypeError`` at submission.
"""

from __future__ import annotations

import pickle

from ray_tpu._private import worker as _worker

_ALLOWED_SCALARS = (type(None), bool, int, float, str, bytes)


def _check_value(v, path="arg"):
    if isinstance(v, _ALLOWED_SCALARS):
        return
    if isinstance(v, (list, tuple)):
        for i, item in enumerate(v):
            _check_value(item, f"{path}[{i}]")
        return
    if isinstance(v, dict):
        for k, item in v.items():
            if not isinstance(k, str):
                raise TypeError(
                    f"cross-language dict keys must be str, got "
                    f"{type(k).__name__} at {path}"
                )
            _check_value(item, f"{path}[{k!r}]")
        return
    raise TypeError(
        f"cross-language values are restricted to None/bool/int/float/"
        f"str/bytes/list/tuple/dict; got {type(v).__name__} at {path}"
    )


def pack_args(args: tuple) -> bytes:
    """Restricted-pickle the arg list for the native codec
    (``pyvalue.h`` decodes protocol ≤3 streams of these types)."""
    for i, a in enumerate(args):
        _check_value(a, f"arg{i}")
    return pickle.dumps(list(args), protocol=3)


class CppFunction:
    """Handle to a named function in a C++ worker binary."""

    def __init__(self, name: str, worker_bin: str | None = None,
                 num_cpus: float = 1.0, num_returns: int = 1):
        self._name = name
        self._worker_bin = worker_bin
        self._num_cpus = num_cpus
        self._num_returns = num_returns

    def options(self, *, worker_bin: str | None = None,
                num_cpus: float | None = None,
                num_returns: int | None = None) -> "CppFunction":
        return CppFunction(
            self._name,
            worker_bin if worker_bin is not None else self._worker_bin,
            num_cpus if num_cpus is not None else self._num_cpus,
            num_returns if num_returns is not None else self._num_returns,
        )

    def remote(self, *args):
        backend = _worker.backend()
        if not hasattr(backend, "submit_cpp_task"):
            raise RuntimeError(
                "cpp_function requires the cluster backend "
                "(ray_tpu.init(address=...)); local mode has no native "
                "worker pool"
            )
        refs = backend.submit_cpp_task(
            self._name,
            pack_args(args),
            worker_bin=self._worker_bin,
            num_cpus=self._num_cpus,
            num_returns=self._num_returns,
        )
        return refs[0] if self._num_returns == 1 else refs


def cpp_function(name: str, worker_bin: str | None = None) -> CppFunction:
    """Reference-parity entry point (``ray.cross_language.cpp_function``)."""
    return CppFunction(name, worker_bin)
