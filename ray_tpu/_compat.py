"""Version compatibility shims for the jax surface this repo uses.

The codebase targets current jax (``jax.shard_map`` with ``check_vma``),
but must import — and run its CPU test harness — on older installs
where shard_map still lives in ``jax.experimental.shard_map`` and the
replication check is spelled ``check_rep``. Keep every such difference
HERE so feature modules import one stable name.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.5: top-level export
    from jax import shard_map as _shard_map
except ImportError:  # older jax: experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

try:
    _ACCEPTS_CHECK_VMA = (
        "check_vma" in inspect.signature(_shard_map).parameters)
except (TypeError, ValueError):  # C-accelerated / wrapped callable
    _ACCEPTS_CHECK_VMA = True


def shard_map(f, *args, **kwargs):
    """``jax.shard_map`` with ``check_vma`` translated to the old
    ``check_rep`` spelling where needed."""
    if not _ACCEPTS_CHECK_VMA and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(f, *args, **kwargs)


try:  # jax >= 0.5: explicit-sharding axis types
    from jax.sharding import AxisType
except ImportError:
    import enum

    class AxisType(enum.Enum):  # type: ignore[no-redef]
        """Stub of jax.sharding.AxisType for older jax, where every mesh
        axis is implicitly Auto (GSPMD propagation) — exactly what the
        stub degrades to (make_mesh below drops the argument)."""

        Auto = "auto"
        Explicit = "explicit"
        Manual = "manual"


def set_num_cpu_devices(n: int) -> None:
    """Configure N virtual XLA CPU devices. New jax has a config option
    (and REJECTS also having the XLA flag set); older jax only honors
    the XLA flag, which must land in the environment BEFORE the backend
    initializes (callers here all run pre-first-backend-touch: worker
    setup_jax, bench harness entry). So: config first, flag only as the
    old-jax fallback — never both."""
    import os

    import jax

    try:
        jax.config.update("jax_num_cpu_devices", n)
        return
    except AttributeError:
        pass  # pre-0.5 jax: only the XLA flag exists
    if "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n}").strip()


def mesh(device_array, axis_names, *, axis_types=None):
    """``jax.sharding.Mesh`` from an explicit device array, dropping
    ``axis_types`` on jax versions whose Mesh doesn't accept it."""
    from jax.sharding import Mesh as _Mesh

    if axis_types is not None:
        try:
            return _Mesh(device_array, axis_names, axis_types=axis_types)
        except (TypeError, AttributeError, ValueError):
            # Older Mesh spells axis_types differently (dict keyed by
            # AxisTypes) or not at all; Auto propagation is its default.
            pass
    return _Mesh(device_array, axis_names)


def pallas_tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams(**kwargs)`` across pallas spellings: older
    pallas names the class ``TPUCompilerParams`` (same fields). Shared by
    every Pallas kernel module (flash_attention, fused_norm) so the alias
    probe lives in exactly one place."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None)
    if cls is None:
        cls = pltpu.TPUCompilerParams
    return cls(**kwargs)


def make_mesh(axis_shapes, axis_names, *, axis_types=None, devices=None):
    """``jax.make_mesh`` that tolerates older signatures without
    ``axis_types`` (where Auto is the only behavior anyway)."""
    import inspect as _inspect

    import jax as _jax

    kwargs = {"devices": devices}
    try:
        if axis_types is not None and "axis_types" in _inspect.signature(
                _jax.make_mesh).parameters:
            kwargs["axis_types"] = axis_types
    except (TypeError, ValueError):
        kwargs["axis_types"] = axis_types
    return _jax.make_mesh(tuple(axis_shapes), tuple(axis_names), **kwargs)
