"""Typed GCS client: accessor objects over the head's RPC surface.

Reference: ``src/ray/gcs/gcs_client/accessor.h`` + the Python
``GcsClient`` — instead of stringly-typed ``head.call("...")`` scattered
through call sites, a ``GcsClient`` exposes typed accessors per table
(nodes, actors, objects, placement groups, internal KV, pubsub, spans).
Library code and tools (dashboard, CLI, state API) can depend on this
stable surface while the wire protocol underneath evolves.

    gcs = GcsClient(head_address)
    gcs.nodes.all()                  # [{"NodeID": ..., "Alive": ...}]
    gcs.actors.get(actor_id)
    gcs.kv.put("k", b"v"); gcs.kv.get("k")
    gcs.placement_groups.table()
"""

from __future__ import annotations

from typing import Any, Optional

from ray_tpu.cluster.rpc import RpcClient


def drain_rpc_timeout(deadline_s: Optional[float]) -> float:
    """Client RPC timeout for a blocking drain_node call: the effective
    server-side deadline (mirroring the head's config fallback) plus
    margin covering the coordinator's own evt.wait slack — so the RPC
    always outlives the drain it is waiting on."""
    from ray_tpu.core.config import config

    effective = (config.drain_deadline_s if deadline_s is None
                 else float(deadline_s))
    return effective + 45.0


class _Accessor:
    def __init__(self, rpc: RpcClient):
        self._rpc = rpc


class NodeInfoAccessor(_Accessor):
    def all(self) -> list[dict]:
        return self._rpc.call("nodes")

    def alive(self) -> list[dict]:
        return [n for n in self.all() if n["Alive"]]

    def resources_total(self) -> dict:
        return self._rpc.call("cluster_resources")

    def resources_available(self) -> dict:
        return self._rpc.call("available_resources")

    def drain(self, node_id: str, reason: str = "requested",
              deadline_s: Optional[float] = None,
              wait: bool = True) -> dict:
        """Graceful, deadline-bounded drain through the head's drain
        protocol (DRAINING -> migrate actors -> quiesce -> DEAD)."""
        return self._rpc.call(
            "drain_node", node_id, reason, deadline_s, wait,
            timeout=drain_rpc_timeout(deadline_s))


class ActorInfoAccessor(_Accessor):
    def all(self) -> list[dict]:
        return self._rpc.call("list_actors")

    def get(self, actor_id: str, timeout: float = 10.0) -> Optional[dict]:
        return self._rpc.call("get_actor", actor_id, timeout,
                              timeout=timeout + 5.0)

    def by_name(self, name: str) -> Optional[dict]:
        return self._rpc.call("get_named_actor", name)

    def kill(self, actor_id: str, reason: str = "gcs_client.kill") -> None:
        self._rpc.call("mark_actor_dead", actor_id, reason, False)


class ObjectInfoAccessor(_Accessor):
    def all(self, limit: int = 1000) -> dict:
        """{"objects": [...size-descending...], "truncated", "total"}."""
        return self._rpc.call("list_objects", limit)

    def locations(self, object_id: str) -> Optional[dict]:
        return self._rpc.call("locations", object_id)

    def on_node(self, node_id: str) -> list[str]:
        return self._rpc.call("objects_on_node", node_id)

    def store_stats(self, node_id: Optional[str] = None,
                    include_objects: bool = True) -> list[dict]:
        """Per-node shm store stats with the per-key attribution join."""
        return self._rpc.call("object_store_stats", node_id,
                              include_objects, timeout=30.0)

    def memory_summary(self, top_k: int = 20,
                       group_by: str = "callsite") -> dict:
        """Cluster memory rollup (totals / per-node occupancy / top-K /
        grouped attribution)."""
        return self._rpc.call("memory_summary", top_k, group_by,
                              timeout=30.0)

    def leaks(self) -> list[dict]:
        """Objects the head's leak sweeper currently flags."""
        return self._rpc.call("memory_leaks", timeout=15.0)


class PlacementGroupAccessor(_Accessor):
    def table(self, pg_id: Optional[str] = None):
        return self._rpc.call("placement_group_table", pg_id)

    def remove(self, pg_id: str) -> None:
        self._rpc.call("remove_placement_group", pg_id)


class InternalKvAccessor(_Accessor):
    def put(self, key: str, value: Any, overwrite: bool = True) -> bool:
        return self._rpc.call("kv_put", key, value, overwrite)

    def get(self, key: str) -> Any:
        return self._rpc.call("kv_get", key)

    def delete(self, key: str) -> bool:
        return self._rpc.call("kv_del", key)

    def keys(self, prefix: str = "") -> list[str]:
        return self._rpc.call("kv_keys", prefix)


class PubsubAccessor(_Accessor):
    def subscribe(self, sub_id: str, channel: str, keys=None) -> bool:
        return self._rpc.call("pubsub_subscribe", sub_id, channel, keys)

    def poll(self, sub_id: str, timeout: float = 10.0, max_msgs: int = 1000):
        return self._rpc.call("pubsub_poll", sub_id, timeout, max_msgs,
                              timeout=timeout + 10.0)

    def unsubscribe(self, sub_id: str, channel=None) -> bool:
        return self._rpc.call("pubsub_unsubscribe", sub_id, channel)

    def publish(self, channel: str, key: str, message) -> int:
        return self._rpc.call("publish", channel, key, message)


class TaskInfoAccessor(_Accessor):
    def all(self, limit: int = 1000) -> list[dict]:
        return self._rpc.call("list_tasks", limit)

    def spans(self, trace_id: Optional[str] = None,
              limit: int = 10_000) -> list[dict]:
        return self._rpc.call("list_spans", trace_id, limit)


class MetricsAccessor(_Accessor):
    """Cluster-wide observability exports: the federated Prometheus
    scrape, its HTTP endpoint, and device telemetry snapshots."""

    def cluster_text(self) -> str:
        """Federated exposition body (what ``/metrics/cluster`` serves):
        the head's registry merged with every alive agent's."""
        return self._rpc.call("cluster_metrics_text", timeout=30.0)

    def endpoint(self) -> Optional[dict]:
        """The head's scrape endpoint {address, cluster_path,
        targets_path}, or None when the HTTP exposition is disabled."""
        return self._rpc.call("metrics_endpoint")

    def device_stats(self, fresh: bool = False) -> list[dict]:
        """Per-worker JAX/XLA device snapshots across the cluster."""
        return self._rpc.call("device_stats", fresh, timeout=20.0)


class SignalsAccessor(_Accessor):
    """The head's signal plane: windowed queries over the metrics
    history ring and the declarative SLO registry. Every call is a pure
    ring read on the head — zero sleeps anywhere in the path."""

    def query(self, spec: dict) -> dict:
        """One windowed query: ``{"op": "rate"|"delta"|"gauge_avg"|
        "gauge_max"|"gauge_last"|"trend"|"quantile"|"series_delta",
        "name": family, "window_s": s, "q"?, "match"?, "group_by"?}``."""
        return self._rpc.call("query_metrics", spec, timeout=15.0)

    def slo_status(self) -> dict:
        return self._rpc.call("slo_status", timeout=15.0)

    def register_slo(self, name: str, expr: str) -> dict:
        """e.g. ``signals.register_slo("serve-ttft",
        'ttft_p50{deployment="d"} < 2s over 60s')``."""
        return self._rpc.call("register_slo", name, expr, timeout=15.0)

    def remove_slo(self, name: str) -> dict:
        return self._rpc.call("remove_slo", name, timeout=15.0)

    def top(self, window_s: float = 60.0) -> dict:
        """The ``ray-tpu top`` rollup (nodes/serve/train/slos)."""
        return self._rpc.call("signal_top", window_s, timeout=15.0)


class ChaosAccessor(_Accessor):
    """Cluster-wide deterministic fault injection: failpoints (named
    sites, armed head -> agents -> workers) and network chaos on the RPC
    plane (delay / drop / duplicate / sever rules; partitions)."""

    def set_failpoints(self, specs: dict,
                       include_workers: bool = True) -> dict:
        """``{site: "action[:arg][,selector...]"}``; falsy spec disarms."""
        return self._rpc.call("set_failpoints", specs, include_workers,
                              timeout=30.0)

    def arm(self, site: str, spec: str) -> dict:
        return self.set_failpoints({site: spec})

    def disarm(self, site: str) -> dict:
        return self.set_failpoints({site: None})

    def list(self) -> dict:
        return self._rpc.call("list_failpoints", timeout=30.0)

    def set_channel_chaos(self, rules: list, label: str = "") -> dict:
        return self._rpc.call("set_channel_chaos", rules, label,
                              timeout=30.0)

    def clear_channel_chaos(self, label: Optional[str] = None) -> dict:
        return self._rpc.call("clear_channel_chaos", label, timeout=30.0)

    def list_channel_chaos(self) -> dict:
        return self._rpc.call("list_channel_chaos", timeout=30.0)

    def partition(self, groups: list) -> dict:
        """Symmetric drop rules between groups of node ids (or "head")."""
        return self._rpc.call("partition", groups, timeout=30.0)

    def heal(self) -> dict:
        return self._rpc.call("heal", timeout=30.0)


class GcsClient:
    def __init__(self, address: str, reconnect_window: float = 15.0):
        self.address = address
        self._rpc = RpcClient(address, reconnect_window=reconnect_window)
        self.nodes = NodeInfoAccessor(self._rpc)
        self.actors = ActorInfoAccessor(self._rpc)
        self.objects = ObjectInfoAccessor(self._rpc)
        self.placement_groups = PlacementGroupAccessor(self._rpc)
        self.kv = InternalKvAccessor(self._rpc)
        self.pubsub = PubsubAccessor(self._rpc)
        self.tasks = TaskInfoAccessor(self._rpc)
        self.metrics = MetricsAccessor(self._rpc)
        self.signals = SignalsAccessor(self._rpc)
        self.chaos = ChaosAccessor(self._rpc)

    def ping(self) -> bool:
        return self._rpc.call("ping") == "pong"

    def event_stats(self) -> dict:
        """Head per-RPC-handler timing stats (event_stats.h analog)."""
        return self._rpc.call("event_stats")

    def close(self) -> None:
        self._rpc.close()
