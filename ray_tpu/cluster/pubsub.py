"""Generalized pub/sub plane (reference: ``src/ray/pubsub/README.md``).

The reference's GCS publisher fans object/actor/node/log/error feeds out
to subscribers over long-poll batches with per-subscriber bounded buffers
(``pubsub/publisher.h``: one outstanding poll per subscriber, messages
buffered between polls, slow subscribers lose oldest messages rather than
stalling the publisher). Same protocol here, hosted in the head:

* ``subscribe(sub_id, channel, keys)`` — keys=None means the whole
  channel; a key list narrows delivery (per-entity subscription).
* ``poll(sub_id, timeout)`` — long-poll: returns buffered messages
  immediately or blocks until one arrives / timeout. Also reports how
  many messages were dropped on overflow since the last poll.
* ``publish(channel, key, message)`` — fan out to matching subscribers.

Channels in use: ``LOGS`` (worker stdout/stderr), ``ACTORS`` (lifecycle
state changes), ``NODES`` (membership), ``ERRORS`` (pushed task errors).
"""

from __future__ import annotations

import collections
import threading
import time

from ray_tpu.core.config import config

CHANNELS = ("LOGS", "ACTORS", "NODES", "ERRORS")


class _Subscriber:
    __slots__ = ("queue", "dropped", "channels", "last_seen")

    def __init__(self):
        self.queue: collections.deque = collections.deque()
        self.dropped = 0
        # channel -> None (all keys) | set of keys
        self.channels: dict[str, set | None] = {}
        self.last_seen = time.monotonic()


class Publisher:
    def __init__(self, max_buffer: int | None = None,
                 subscriber_ttl_s: float | None = None):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._subs: dict[str, _Subscriber] = {}
        # Config read at construction (not import) so overrides apply.
        self._max_buffer = (config.pubsub_max_buffer
                            if max_buffer is None else max_buffer)
        self._ttl = (config.pubsub_subscriber_ttl_s
                     if subscriber_ttl_s is None else subscriber_ttl_s)

    def subscribe(self, sub_id: str, channel: str,
                  keys: list | None = None) -> bool:
        if channel not in CHANNELS:
            raise ValueError(f"unknown channel {channel!r}")
        with self._lock:
            sub = self._subs.get(sub_id)
            if sub is None:
                sub = self._subs[sub_id] = _Subscriber()
            if keys is None:
                sub.channels[channel] = None
            else:
                have = sub.channels.get(channel)
                if have is None and channel in sub.channels:
                    pass  # already subscribed to ALL keys: keep that
                else:
                    sub.channels[channel] = (have or set()) | set(keys)
        return True

    def unsubscribe(self, sub_id: str, channel: str | None = None) -> bool:
        with self._lock:
            sub = self._subs.get(sub_id)
            if sub is None:
                return False
            if channel is None:
                del self._subs[sub_id]
            else:
                sub.channels.pop(channel, None)
                if not sub.channels:
                    del self._subs[sub_id]
        return True

    def publish(self, channel: str, key: str, message) -> int:
        """Returns the number of subscribers the message was queued to."""
        delivered = 0
        now = time.monotonic()
        with self._cv:
            dead = []
            for sub_id, sub in self._subs.items():
                keys = sub.channels.get(channel, "absent")
                if keys == "absent" or (keys is not None and key not in keys):
                    continue
                if now - sub.last_seen > self._ttl:
                    dead.append(sub_id)  # poller gone: stop buffering
                    continue
                sub.queue.append(
                    {"channel": channel, "key": key, "data": message})
                if len(sub.queue) > self._max_buffer:
                    sub.queue.popleft()
                    sub.dropped += 1
                delivered += 1
            for sub_id in dead:
                del self._subs[sub_id]
            if delivered:
                self._cv.notify_all()
        return delivered

    def poll(self, sub_id: str, timeout: float = 10.0,
             max_msgs: int = 1000):
        """Long-poll: (messages, dropped_since_last_poll). An unknown
        sub_id returns immediately (the caller should re-subscribe — the
        head may have restarted, pubsub state is not persisted)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                sub = self._subs.get(sub_id)
                if sub is None:
                    return None  # not subscribed (anymore)
                sub.last_seen = time.monotonic()
                if sub.queue:
                    out = []
                    while sub.queue and len(out) < max_msgs:
                        out.append(sub.queue.popleft())
                    dropped, sub.dropped = sub.dropped, 0
                    return out, dropped
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return [], 0
                self._cv.wait(remaining)

    def stats(self) -> dict:
        with self._lock:
            return {
                "subscribers": len(self._subs),
                "buffered": sum(len(s.queue) for s in self._subs.values()),
            }
