"""Generalized pub/sub plane (reference: ``src/ray/pubsub/README.md``).

The reference's GCS publisher fans object/actor/node/log/error feeds out
to subscribers over long-poll batches with per-subscriber bounded buffers
(``pubsub/publisher.h``: one outstanding poll per subscriber, messages
buffered between polls, slow subscribers lose oldest messages rather than
stalling the publisher). Same protocol here, hosted in the head:

* ``subscribe(sub_id, channel, keys)`` — keys=None means the whole
  channel; a key list narrows delivery (per-entity subscription).
* ``poll(sub_id, timeout)`` — long-poll: returns buffered messages
  immediately or blocks until one arrives / timeout. Also reports how
  many messages were dropped on overflow since the last poll.
* ``publish(channel, key, message)`` — fan out to matching subscribers.

Channels in use: ``LOGS`` (worker stdout/stderr), ``ACTORS`` (lifecycle
state changes), ``NODES`` (membership), ``ERRORS`` (pushed task errors).

Round 6 (head at scale) restructured the hot path twice over:

* **Key-indexed matching.** ``publish`` used to scan every subscriber
  per message — O(subscribers) even when none matched. The publisher now
  keeps a ``channel -> key -> {sub_id}`` index (plus a channel-wide
  set for keys=None subscriptions), so a publish touches exactly the
  subscribers it delivers to. 1k actor FSM updates against hundreds of
  log pollers no longer pay for each other.
* **Per-(subscriber, channel, key) coalescing.** ``ACTORS`` and
  ``NODES`` messages carry the entity's FULL latest state, so a slow
  subscriber doesn't need history — it needs the newest value. For
  those channels, a publish whose (channel, key) is already buffered
  for a subscriber REPLACES the buffered payload in place instead of
  appending; the message keeps its queue position (delivery order of
  first occurrence) and counts into ``coalesced``. Append-only feeds
  (``LOGS``, ``ERRORS``) never coalesce — every line matters.

Slow subscribers still lose oldest on buffer overflow (drop counter per
subscriber, surfaced in ``poll`` and ``stats``), and a subscriber that
stops polling past the TTL is reaped — on publish, and on the periodic
``stats`` scrape, so idle-channel ghosts can't pin buffers forever.
"""

from __future__ import annotations

import collections
import threading
import time

from ray_tpu.core.config import config
from ray_tpu.util.metrics import (
    PUBSUB_COALESCED as _PUBSUB_COALESCED,
    PUBSUB_DROPPED as _PUBSUB_DROPPED,
)

CHANNELS = ("LOGS", "ACTORS", "NODES", "ERRORS", "PLACEMENT_GROUPS",
            "SLO")

# State-update channels: each message is the entity's complete latest
# state keyed by entity id, so replacing a buffered message with a newer
# one loses nothing a subscriber could act on. Event/stream channels
# (LOGS, ERRORS) are deliberately absent. PLACEMENT_GROUPS carries each
# group's full latest lifecycle state (CREATED/RESCHEDULING/...) keyed
# by pg id — the feed gang holders watch to learn their bundles moved.
# SLO is an edge-event channel: a burning event and the recovery that
# follows it share the slo-name key, so coalescing would swallow one
# edge — both must deliver.
COALESCE_CHANNELS = frozenset(("ACTORS", "NODES", "PLACEMENT_GROUPS"))


class _Subscriber:
    __slots__ = ("sub_id", "queue", "dropped", "coalesced", "channels",
                 "last_seen", "pending")

    def __init__(self, sub_id: str):
        self.sub_id = sub_id
        self.queue: collections.deque = collections.deque()
        self.dropped = 0
        self.coalesced = 0
        # channel -> None (all keys) | set of keys
        self.channels: dict[str, set | None] = {}
        self.last_seen = time.monotonic()
        # (channel, key) -> the buffered message dict for coalescible
        # channels, so a newer publish can swap the payload in place.
        self.pending: dict[tuple, dict] = {}


class Publisher:
    def __init__(self, max_buffer: int | None = None,
                 subscriber_ttl_s: float | None = None):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._subs: dict[str, _Subscriber] = {}
        # Delivery index: channel -> {"*": {sub_id}, key: {sub_id}}.
        # Publish unions the channel-wide set with the exact-key set —
        # O(matching subscribers), not O(all subscribers).
        self._index: dict[str, dict[str, set]] = {
            ch: {"*": set()} for ch in CHANNELS
        }
        # Config read at construction (not import) so overrides apply.
        self._max_buffer = (config.pubsub_max_buffer
                            if max_buffer is None else max_buffer)
        self._ttl = (config.pubsub_subscriber_ttl_s
                     if subscriber_ttl_s is None else subscriber_ttl_s)
        # Cumulative totals survive subscriber reap/unsubscribe so
        # rpc_pubsub_stats can expose lifetime drop/coalesce counts.
        self._total_dropped = 0
        self._total_coalesced = 0
        self._total_published = 0

    # -- membership --------------------------------------------------------

    def subscribe(self, sub_id: str, channel: str,
                  keys: list | None = None) -> bool:
        if channel not in CHANNELS:
            raise ValueError(f"unknown channel {channel!r}")
        with self._lock:
            sub = self._subs.get(sub_id)
            if sub is None:
                sub = self._subs[sub_id] = _Subscriber(sub_id)
            sub.last_seen = time.monotonic()
            idx = self._index[channel]
            if keys is None:
                # Widening to all-keys supersedes any per-key entries.
                have = sub.channels.get(channel)
                if have:
                    for k in have:
                        self._index_discard(channel, k, sub_id)
                sub.channels[channel] = None
                idx["*"].add(sub_id)
            else:
                have = sub.channels.get(channel)
                if have is None and channel in sub.channels:
                    pass  # already subscribed to ALL keys: keep that
                else:
                    merged = (have or set()) | set(keys)
                    sub.channels[channel] = merged
                    for k in keys:
                        idx.setdefault(k, set()).add(sub_id)
        return True

    def unsubscribe(self, sub_id: str, channel: str | None = None) -> bool:
        with self._lock:
            sub = self._subs.get(sub_id)
            if sub is None:
                return False
            if channel is None:
                self._drop_subscriber(sub)
            else:
                self._unindex_channel(sub, channel)
                sub.channels.pop(channel, None)
                sub.pending = {
                    pk: m for pk, m in sub.pending.items()
                    if pk[0] != channel
                }
                if not sub.channels:
                    self._drop_subscriber(sub)
        return True

    def _index_discard(self, channel: str, key: str, sub_id: str) -> None:
        entry = self._index[channel].get(key)
        if entry is not None:
            entry.discard(sub_id)
            if not entry and key != "*":
                del self._index[channel][key]

    def _unindex_channel(self, sub: _Subscriber, channel: str) -> None:
        keys = sub.channels.get(channel, ())
        if keys is None:
            self._index[channel]["*"].discard(sub.sub_id)
        else:
            for k in keys:
                self._index_discard(channel, k, sub.sub_id)

    def _drop_subscriber(self, sub: _Subscriber) -> None:
        """Caller holds the lock: remove the subscriber and every index
        entry pointing at it. Lifetime drop totals keep its count."""
        for channel in list(sub.channels):
            self._unindex_channel(sub, channel)
        # Overflow drops already landed in _total_dropped at publish
        # time; only the never-delivered buffered tail is new loss.
        self._total_dropped += len(sub.queue)
        self._subs.pop(sub.sub_id, None)

    def _reap_stale(self, now: float) -> None:
        """Caller holds the lock: drop every subscriber whose last poll
        is older than the TTL (poller gone: stop buffering for it)."""
        stale = [s for s in self._subs.values()
                 if now - s.last_seen > self._ttl]
        for sub in stale:
            self._drop_subscriber(sub)

    # -- hot path ----------------------------------------------------------

    def publish(self, channel: str, key: str, message) -> int:
        """Returns the number of subscribers the message was queued to
        (coalesced replacements count — the subscriber WILL see it)."""
        delivered = 0
        now = time.monotonic()
        coalesce = channel in COALESCE_CHANNELS
        idx = self._index.get(channel)
        if idx is None:
            raise ValueError(f"unknown channel {channel!r}")
        with self._cv:
            self._total_published += 1
            targets = idx["*"] | idx.get(key, set())
            if not targets:
                return 0
            stale = []
            for sub_id in targets:
                sub = self._subs.get(sub_id)
                if sub is None:
                    continue
                if now - sub.last_seen > self._ttl:
                    stale.append(sub)  # poller gone: stop buffering
                    continue
                if coalesce:
                    buffered = sub.pending.get((channel, key))
                    if buffered is not None:
                        # Latest-state-wins: swap the payload in place;
                        # the subscriber sees ONE message with the
                        # newest data at the old queue position.
                        buffered["data"] = message
                        sub.coalesced += 1
                        self._total_coalesced += 1
                        _PUBSUB_COALESCED.inc()
                        delivered += 1
                        continue
                entry = {"channel": channel, "key": key, "data": message}
                sub.queue.append(entry)
                if coalesce:
                    sub.pending[(channel, key)] = entry
                if len(sub.queue) > self._max_buffer:
                    lost = sub.queue.popleft()
                    sub.dropped += 1
                    self._total_dropped += 1
                    _PUBSUB_DROPPED.inc()
                    pk = (lost["channel"], lost["key"])
                    if sub.pending.get(pk) is lost:
                        del sub.pending[pk]
                delivered += 1
            for sub in stale:
                self._drop_subscriber(sub)
            if delivered:
                self._cv.notify_all()
        return delivered

    def poll(self, sub_id: str, timeout: float = 10.0,
             max_msgs: int = 1000):
        """Long-poll: (messages, dropped_since_last_poll). An unknown
        sub_id returns immediately (the caller should re-subscribe — the
        head may have restarted, pubsub state is not persisted)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while True:
                sub = self._subs.get(sub_id)
                if sub is None:
                    return None  # not subscribed (anymore)
                sub.last_seen = time.monotonic()
                if sub.queue:
                    out = []
                    while sub.queue and len(out) < max_msgs:
                        msg = sub.queue.popleft()
                        pk = (msg["channel"], msg["key"])
                        if sub.pending.get(pk) is msg:
                            del sub.pending[pk]
                        out.append(msg)
                    dropped, sub.dropped = sub.dropped, 0
                    return out, dropped
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return [], 0
                self._cv.wait(remaining)

    def stats(self) -> dict:
        """Pubsub health counters (fed into ``rpc_pubsub_stats``). The
        scrape doubles as the idle-channel reaper: a subscriber past the
        TTL is dropped here even if nothing publishes to its channels."""
        with self._lock:
            self._reap_stale(time.monotonic())
            return {
                "subscribers": len(self._subs),
                "buffered": sum(len(s.queue) for s in self._subs.values()),
                "published": self._total_published,
                "dropped": self._total_dropped,
                "coalesced": self._total_coalesced,
                "indexed_keys": {
                    ch: len(idx) - 1 for ch, idx in self._index.items()
                },
            }
