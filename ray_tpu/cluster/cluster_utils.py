"""Cluster: N simulated nodes on one host, for tests and local multi-node.

Reference parity: ``python/ray/cluster_utils.py:99`` — ``Cluster`` /
``add_node`` start real node agents (with their own node ids, resource
views, and shm store segments) as in-process servers + worker subprocesses,
which is exactly how the reference tests distributed behavior without
machines (SURVEY.md §4.3).
"""

from __future__ import annotations

import os
import time

from ray_tpu.cluster.head import HeadServer
from ray_tpu.cluster.node_agent import NodeAgent


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: dict | None = None,
                 persist_path: str | None = None):
        self.head: HeadServer | None = None
        self.nodes: list[NodeAgent] = []
        self.session = f"c{os.getpid()}_{os.urandom(3).hex()}"
        self.persist_path = persist_path
        self._partitioned = False
        # Auth-on by default (round 5): generate a per-cluster token
        # unless one is configured or auth was explicitly disabled with
        # RAY_TPU_CLUSTER_TOKEN="" — see rpc.ensure_cluster_token.
        from ray_tpu.cluster.rpc import ensure_cluster_token

        ensure_cluster_token()
        # Reclaim dead runs' leaked shm segments before this cluster
        # allocates its own (a SIGKILLed soak can leave 100+ GB in
        # /dev/shm and OOM every later run on the box).
        from ray_tpu.util.shm_sweep import sweep_stale_shm

        sweep_stale_shm()
        if initialize_head:
            self.head = HeadServer(persist_path=persist_path)
            if head_node_args is not None:
                self.add_node(**head_node_args)

    def kill_head(self) -> str:
        """Crash the head ungracefully (no final snapshot/close): the GCS
        fault-tolerance chaos path. Returns the address to restart on."""
        assert self.head is not None
        address = self.head.address
        self.head._stop.set()
        self.head._server.stop()
        if self.head._store is not None:
            # A real crash loses the write-behind dirty queue (whole
            # batches, never torn rows) and must not leave a zombie
            # flusher writing under the restarted head.
            self.head._store.abandon()
        self.head = None
        return address

    def restart_head(self, address: str, timeout: float = 10.0) -> None:
        """Start a fresh head on the SAME address, reloading state from
        ``persist_path`` (gcs fault tolerance: agents keep heartbeating
        through their reconnect window and resume against the new head).
        The bind is retried briefly — sockets of the killed head can
        linger for a moment."""
        assert self.head is None and self.persist_path is not None
        host, port = address.rsplit(":", 1)
        deadline = time.monotonic() + timeout
        while True:
            try:
                self.head = HeadServer(host, int(port),
                                       persist_path=self.persist_path)
                return
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)

    @property
    def address(self) -> str:
        assert self.head is not None
        return self.head.address

    def add_node(self, *, num_cpus: float | None = None,
                 resources: dict | None = None,
                 store_capacity: int | None = None,
                 **agent_kwargs) -> NodeAgent:
        assert self.head is not None, "head not initialized"
        kwargs = dict(agent_kwargs)
        if store_capacity is not None:
            kwargs["store_capacity"] = store_capacity
        node = NodeAgent(
            self.head.address,
            num_cpus=num_cpus,
            resources=resources,
            session=self.session,
            **kwargs,
        )
        self.nodes.append(node)
        return node

    def remove_node(self, node: NodeAgent, graceful: bool = True, *,
                    reason: str = "removed",
                    deadline_s: float | None = None):
        """Remove a node. ``graceful`` routes through the head's drain
        protocol (deadline-bounded: in-flight tasks finish, restartable
        actors migrate first, owners get the retry exemption); the
        ungraceful path stays an instant removal for chaos tests."""
        if graceful and self.head is not None:
            try:
                self.head.rpc_drain_node(
                    node.node_id, reason, deadline_s, wait=True)
            except Exception:
                pass
        node.stop()
        if node in self.nodes:
            self.nodes.remove(node)

    def kill_node(self, node: NodeAgent):
        """Ungraceful: stop heartbeats + kill workers; the head discovers
        the death via heartbeat timeout (chaos-test path)."""
        node._shutdown.set()
        for w in list(node._workers.values()):
            if w.proc.poll() is None:
                w.proc.kill()
        node._server.stop()
        # Break the corpse's outbound clients too: a killed node's
        # heartbeat/gossip thread mid-call in a reconnect window (or
        # spinning against an armed partition rule) would otherwise keep
        # retrying for up to the window — stray threads bleeding into
        # whatever the chaos run does next.
        node.close_outbound_clients()
        if node in self.nodes:
            self.nodes.remove(node)

    # -- network chaos ------------------------------------------------------

    def partition(self, groups) -> dict:
        """Partition the cluster's RPC plane between endpoint groups.
        Each group is a list whose members are ``NodeAgent`` instances,
        node ids, or the string ``"head"``. Delegates to the head's
        ``rpc_partition`` (the one implementation the control plane/CLI
        also uses): symmetric drop rules for every cross-group pair —
        both directions, fanned to every agent and its live workers —
        so heartbeats, gossip, head fan-outs, and object traffic all
        genuinely observe the cut; every affected call surfaces as
        ``ConnectionLost`` (never silent corruption) and retry-windowed
        callers keep probing until ``heal()``. Endpoints not named in
        any group (e.g. the driver) are unaffected."""
        assert self.head is not None
        id_groups = [
            [m.node_id if isinstance(m, NodeAgent) else m for m in group]
            for group in groups
        ]
        self._partitioned = True
        return self.head.rpc_partition(id_groups)

    def heal(self) -> dict | int:
        """Remove every partition rule this cluster armed."""
        if self.head is not None:
            out = self.head.rpc_heal()
        else:
            from ray_tpu.cluster.rpc import channel_chaos

            out = channel_chaos.clear("partition")
        # Only after the heal actually landed: a raised heal must leave
        # the flag set so shutdown()'s auto-heal still fires instead of
        # leaking armed drop rules into the next test's cluster.
        self._partitioned = False
        return out

    def wait_for_nodes(self, timeout: float = 10.0) -> None:
        assert self.head is not None
        # Load-scaled deadline: on a saturated box (parallel suites,
        # worker jax imports) node registration+heartbeats legitimately
        # take several times longer; a fixed 10s produces the classic
        # fixture-TimeoutError flake.
        try:
            load = os.getloadavg()[0] / max(os.cpu_count() or 1, 1)
        except OSError:
            load = 0.0
        deadline = time.monotonic() + timeout * min(4.0, max(1.0, load))
        want = len(self.nodes)
        while time.monotonic() < deadline:
            alive = [n for n in self.head.rpc_nodes() if n["Alive"]]
            if len(alive) >= want:
                return
            time.sleep(0.02)
        states = [(n["NodeID"][-8:], n["Alive"]) for n in self.head.rpc_nodes()]
        try:
            load_s = f"{os.getloadavg()[0]:.1f}"
        except OSError:
            load_s = "?"
        raise TimeoutError(
            f"cluster did not reach {want} nodes; registered={states}, "
            f"load={load_s}/{os.cpu_count()}cpu")

    def shutdown(self):
        # Chaos rules must never outlive the cluster that armed them
        # (the table is process-global; a forgotten partition would drop
        # the NEXT test's RPCs).
        if self._partitioned:
            try:
                self.heal()
            except Exception:
                from ray_tpu.cluster.rpc import channel_chaos

                channel_chaos.clear("partition")
        for node in list(self.nodes):
            try:
                node.stop()
            except Exception:
                pass
        self.nodes.clear()
        if self.head is not None:
            self.head.stop()
            self.head = None
