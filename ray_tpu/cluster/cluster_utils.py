"""Cluster: N simulated nodes on one host, for tests and local multi-node.

Reference parity: ``python/ray/cluster_utils.py:99`` — ``Cluster`` /
``add_node`` start real node agents (with their own node ids, resource
views, and shm store segments) as in-process servers + worker subprocesses,
which is exactly how the reference tests distributed behavior without
machines (SURVEY.md §4.3).
"""

from __future__ import annotations

import os
import time

from ray_tpu.cluster.head import HeadServer
from ray_tpu.cluster.node_agent import NodeAgent


class Cluster:
    def __init__(self, initialize_head: bool = True, head_node_args: dict | None = None):
        self.head: HeadServer | None = None
        self.nodes: list[NodeAgent] = []
        self.session = f"c{os.getpid()}_{os.urandom(3).hex()}"
        if initialize_head:
            self.head = HeadServer()
            if head_node_args is not None:
                self.add_node(**head_node_args)

    @property
    def address(self) -> str:
        assert self.head is not None
        return self.head.address

    def add_node(self, *, num_cpus: float | None = None,
                 resources: dict | None = None,
                 store_capacity: int | None = None) -> NodeAgent:
        assert self.head is not None, "head not initialized"
        kwargs = {}
        if store_capacity is not None:
            kwargs["store_capacity"] = store_capacity
        node = NodeAgent(
            self.head.address,
            num_cpus=num_cpus,
            resources=resources,
            session=self.session,
            **kwargs,
        )
        self.nodes.append(node)
        return node

    def remove_node(self, node: NodeAgent, graceful: bool = True):
        if graceful and self.head is not None:
            try:
                self.head._mark_dead(node.node_id, "removed")
            except Exception:
                pass
        node.stop()
        if node in self.nodes:
            self.nodes.remove(node)

    def kill_node(self, node: NodeAgent):
        """Ungraceful: stop heartbeats + kill workers; the head discovers
        the death via heartbeat timeout (chaos-test path)."""
        node._shutdown.set()
        for w in list(node._workers.values()):
            if w.proc.poll() is None:
                w.proc.kill()
        node._server.stop()
        if node in self.nodes:
            self.nodes.remove(node)

    def wait_for_nodes(self, timeout: float = 10.0) -> None:
        assert self.head is not None
        deadline = time.monotonic() + timeout
        want = len(self.nodes)
        while time.monotonic() < deadline:
            alive = [n for n in self.head.rpc_nodes() if n["Alive"]]
            if len(alive) >= want:
                return
            time.sleep(0.02)
        raise TimeoutError(f"cluster did not reach {want} nodes")

    def shutdown(self):
        for node in list(self.nodes):
            try:
                node.stop()
            except Exception:
                pass
        self.nodes.clear()
        if self.head is not None:
            self.head.stop()
            self.head = None
