"""Worker process: executes tasks and hosts actors (CoreWorker equivalent).

The execution side of ``src/ray/core_worker``: receives pushed tasks over
RPC (``core_worker.proto:382`` PushTask), deserializes with cloudpickle,
resolves ObjectRef args through the object plane, runs the function, and
stores returns in the node's shm store + registers locations with the head
(the task-execution callback path, ``_raylet.pyx:956``).

A worker executes ONE task at a time on its executor thread; actor workers
are dedicated: the actor constructor is the first queued item and method
calls execute in arrival order (sequence-numbered actor queue analog).
Nested ``ray_tpu.*`` calls inside user code work because the worker installs
a full ClusterBackend as the process-wide backend.
"""

from __future__ import annotations

import argparse
import io
import os
import queue
import sys
import threading
import time
import traceback

from ray_tpu.cluster.rpc import RpcClient, RpcServer
from ray_tpu.core import attribution
from ray_tpu.util import failpoints
from ray_tpu.util import metrics as _wp_metrics
from ray_tpu.core import serialization as ser
from ray_tpu.core.cancellation import CancelRegistry
from ray_tpu.core.object_ref import (
    ActorError,
    ObjectRef,
    TaskCancelledError,
    TaskError,
)


class _TeeStream(io.TextIOBase):
    """Write-through stdout/stderr wrapper that also line-buffers into a
    shared list for the log forwarder (reference: per-worker log files
    tailed by ``_private/log_monitor.py`` and pushed to the driver)."""

    def __init__(self, inner, sink: list, lock: threading.Lock):
        self._inner = inner
        self._sink = sink
        self._lock = lock
        self._partial = ""

    def write(self, s):
        self._inner.write(s)
        self._partial += s
        if "\n" in self._partial:
            *lines, self._partial = self._partial.split("\n")
            with self._lock:
                self._sink.extend(lines)
        return len(s)

    def flush(self):
        self._inner.flush()

    def isatty(self):
        return False


class _PhaseClock:
    """Wall-ns accumulator for the per-task phase breakdown
    (``get_args`` = arg fetch + deserialize, ``execute`` = user code,
    ``put_outputs`` = result serialize + object-store put). ``lap``
    closes the current phase; phases ride the task-event record to the
    agent and surface in ``state.summarize_tasks()``/``timeline()``."""

    __slots__ = ("_phases", "_t")

    def __init__(self, phases: dict):
        self._phases = phases
        self._t = time.monotonic_ns()

    def lap(self, name: str) -> None:
        now = time.monotonic_ns()
        self._phases[name] = self._phases.get(name, 0) + (now - self._t)
        self._t = now


class WorkerHandler:
    def __init__(self, head_address, agent_address, node_id, store_path, worker_id):
        from ray_tpu.cluster.client import ClusterBackend

        self.worker_id = worker_id
        self.agent = RpcClient(agent_address)
        self.backend = ClusterBackend(
            head_address, node_id=node_id, store_path=store_path,
            agent_address=agent_address, process_kind="w",
        )
        from ray_tpu._private import worker as worker_mod

        worker_mod._backend = self.backend  # nested API calls inside tasks
        from ray_tpu.core.config import config

        self._hooks = (
            lambda: self.agent.call("task_blocked", self.worker_id),
            # Unblock re-acquires the CPU slot and the agent-side
            # acquire may legitimately wait up to its full re-acquire
            # budget when the node is saturated (many tasks cycling few
            # slots under memory pressure) — the RPC timeout must
            # outlast it or the worker kills a healthy task with
            # ConnectionLost. Derived from the budget knob so the two
            # can't drift; the analyzer checks the declared relation.
            lambda: self.agent.call(
                "task_unblocked", self.worker_id,
                # timeout-budget: outlasts config.cpu_reacquire_budget_s
                timeout=config.cpu_reacquire_budget_s + 30.0),
        )
        self._q: queue.Queue = queue.Queue()
        # Named concurrency groups: each gets its own queue + executor
        # threads (reference actor concurrency groups — a long call in
        # one group never blocks another group's methods).
        self._group_queues: dict[str, queue.Queue] = {}
        self._actor_instance = None
        self._actor_dead_cause: str | None = None
        self._actor_id: str | None = None
        # Threaded actors (max_concurrency > 1): method calls may not run
        # before the constructor finishes, and extra executor threads are
        # only spawned after it (so the ctor itself is never raced).
        self._actor_ready = threading.Event()
        # Observability buffers, shipped to the agent in batches by the
        # event flusher (keeps the task hot path free of extra RPCs).
        self._ev_lock = threading.Lock()
        self._log_lines: list = []
        self._task_events: list = []
        # Cancellation registry: ids cancelled before they ran, and the
        # executor-thread ident of each currently running task (so a
        # cooperative cancel can target the right thread).
        self._cancels = CancelRegistry(threading.Lock())
        # Async actors (reference: asyncio event loop per actor,
        # _raylet.pyx:1023): one loop thread, created on first coroutine
        # method; in-flight coroutine futures by task id for cancel.
        self._aio_loop = None
        self._aio_lock = threading.Lock()
        self._async_futs: dict[str, object] = {}
        # Whether this worker hosts an ASYNC actor (any coroutine method):
        # set after the ctor; async actors route every call via the loop.
        self._actor_is_async = False
        # Completion bookkeeping for async tasks runs here, off the loop.
        self._async_done_q: queue.Queue = queue.Queue()
        threading.Thread(target=self._async_done_loop, daemon=True).start()
        # Function-table cache: content hash -> deserialized function
        # (bounded LRU — long-lived workers must not accumulate every
        # function a driver ever exported).
        import collections

        self._fn_cache: "collections.OrderedDict[str, object]" = (
            collections.OrderedDict())
        # Duplicate-delivery suppression for pushed calls (bounded,
        # insertion-ordered): a caller that loses the REPLY to a push
        # (sever-after-send chaos, network blip) retries the same spec —
        # same task id — against this incarnation; accepting it twice
        # would double user-visible side effects. An actor RESTART is a
        # fresh process (empty set), so legitimate replay still runs.
        self._seen_pushes: "collections.OrderedDict[str, bool]" = (
            collections.OrderedDict())
        sys.stdout = _TeeStream(sys.stdout, self._log_lines, self._ev_lock)
        sys.stderr = _TeeStream(sys.stderr, self._log_lines, self._ev_lock)
        threading.Thread(target=self._event_flush_loop, daemon=True).start()
        threading.Thread(target=self._exec_loop, daemon=True).start()

    # -- observability -----------------------------------------------------

    def _record(self, spec, kind: str):
        rec = {
            "task_id": spec.get("task_id") or spec.get("oids", ["?"])[0],
            "name": spec.get("fname") or spec.get("method")
            or spec.get("class_name", "task"),
            "type": kind,
            "state": "RUNNING",
            "submitted_at": spec.get("submitted_at"),
            "start_time": time.time(),
            "end_time": None,
            "error": None,
            # Wall-ns per execution phase (get_args/execute/put_outputs),
            # filled by a _PhaseClock as the task advances.
            "phases": {},
        }
        return rec

    def _finish(self, rec, error: str | None):
        rec["state"] = "FAILED" if error else "FINISHED"
        rec["end_time"] = time.time()
        rec["error"] = error
        with self._ev_lock:
            self._task_events.append(rec)

    def _event_flush_loop(self):
        import collections

        from ray_tpu.util import device_telemetry, tracing
        from ray_tpu.util import metrics as _metrics

        pid = os.getpid()
        last_dev_ship = 0.0
        # Agent-liveness watchdog (reference: a worker whose raylet dies
        # exits with it, core_worker shutdown-on-raylet-death). Workers
        # are killed by the agent on clean shutdown; when the agent dies
        # ABRUPTLY (node crash, chaos kill, aborted test fixture) nothing
        # would reap us — a jax-loaded orphan per worker piles real load
        # onto the box. The flusher doubles as the probe: consecutive
        # failed agent calls over ~3s mean the agent is gone.
        consecutive_fail = 0
        idle_rounds = 0
        # Failed uploads are RESENT as-is under their ORIGINAL sequence
        # number, and the agent's rpc_worker_events dedups on (worker,
        # pid, seq): a reply lost after the agent applied the batch
        # (maybe_executed) makes the resend an ack instead of a
        # double-count — the serve/goodput planes promise exact counts,
        # and the old requeue-into-the-buffer path re-shipped the same
        # observations under what was effectively a new identity.
        unacked: "collections.deque" = collections.deque()
        ship_seq = 0
        while True:
            time.sleep(0.25)
            # Attach jax compile-counter listeners the moment a task's
            # import makes jax available (idempotent; narrows the
            # uncounted window to compiles racing this tick).
            try:
                device_telemetry.ensure_listeners()
            except Exception:
                _metrics.count_loop_restart("worker.event_flush")
            with self._ev_lock:
                # Drain in place: the tee streams hold a reference to
                # THESE list objects — rebinding would orphan them.
                lines = self._log_lines[:]
                del self._log_lines[:]
                events = self._task_events[:]
                del self._task_events[:]
            spans = tracing.drain() if tracing.is_enabled() else []
            # Span-buffer truncation count rides the batch (no-silent-caps:
            # a worker clipping spans must show up in the head's scrape,
            # and worker registries are never scraped directly).
            span_drops = tracing.drain_dropped() if tracing.is_enabled() \
                else 0
            # Serve request-path observations (phase histograms, shed
            # counters, replica gauges) ride the same batch; the module
            # is only consulted if something in this process imported
            # serve (a worker that never served ships nothing).
            serve_events = []
            so = sys.modules.get("ray_tpu.serve._observability")
            if so is not None:
                try:
                    serve_events = so.drain_events()
                except Exception:
                    serve_events = []
                    _metrics.count_loop_restart("worker.event_flush")
            # Training goodput observations (dataset stage/iterator
            # samples, step phases, downtime) ride the same batch; the
            # module is only consulted if something in this process
            # imported the data/train path.
            train_events = []
            go = sys.modules.get("ray_tpu.util.goodput")
            if go is not None:
                try:
                    train_events = go.drain_events()
                except Exception:
                    train_events = []
                    _metrics.count_loop_restart("worker.event_flush")
            if not lines and not events and not spans and not span_drops \
                    and not serve_events and not train_events \
                    and not unacked:
                idle_rounds += 1
                # Probe liveness every ~2s when idle; every round while
                # failures are accumulating (fast exit once the agent
                # actually died).
                if idle_rounds < 8 and consecutive_fail == 0:
                    continue
            idle_rounds = 0
            # Device telemetry rides the same batch, throttled to ~1/s;
            # None until something in this process imports jax (the
            # snapshot itself never triggers the import).
            device = None
            now = time.monotonic()
            if device_telemetry.jax_loaded() and now - last_dev_ship >= 1.0:
                try:
                    device = device_telemetry.snapshot()
                    last_dev_ship = now
                except Exception:
                    device = None
                    _metrics.count_loop_restart("worker.event_flush")
            if lines or events or spans or span_drops or serve_events \
                    or train_events or device is not None or not unacked:
                # New content — or an empty liveness probe when nothing
                # is pending resend (the resend IS the probe otherwise).
                ship_seq += 1
                unacked.append((ship_seq, events, lines, spans, device,
                                serve_events or None,
                                train_events or None,
                                span_drops or None))
            while len(unacked) > 8:
                # Bounded resend queue: give the oldest batch's
                # exact-count planes back to their buffers (they count
                # their own overflow drops). Re-shipping under a new
                # seq can double-apply only if one of its 8+ failed
                # sends secretly landed — the narrow corner the bound
                # trades for bounded memory.
                (_, _, _, _, _, drop_serve, drop_train,
                 drop_spans) = unacked.popleft()
                # The evicted batch's truncation count folds back into
                # the buffer — losing the loss-counter is the one drop
                # this plane can never absorb silently.
                try:
                    if drop_spans:
                        tracing.requeue_dropped(drop_spans)
                except Exception:
                    _metrics.count_loop_restart("worker.event_flush")
                # Independent requeues: a failing serve requeue must
                # not also cost the batch's goodput observations.
                try:
                    if drop_serve and so is not None:
                        so.requeue_events(drop_serve)
                except Exception:
                    _metrics.count_loop_restart("worker.event_flush")
                try:
                    if drop_train and go is not None:
                        go.requeue_events(drop_train)
                except Exception:
                    _metrics.count_loop_restart("worker.event_flush")
            while unacked:
                (seq, b_events, b_lines, b_spans, b_device, b_serve,
                 b_train, b_drops) = unacked[0]
                try:
                    self.agent.call(
                        "worker_events", self.worker_id, pid, b_events,
                        b_lines, b_spans, b_device, b_serve, b_train,
                        seq=seq, dropped=b_drops)
                    unacked.popleft()
                    consecutive_fail = 0
                except Exception:
                    _metrics.count_loop_restart("worker.event_flush")
                    consecutive_fail += 1
                    if consecutive_fail >= 12:
                        os._exit(1)  # agent is gone: die with the node
                    break  # keep the batch; resend same seq next round

    # -- rpc surface (called by agent and by remote callers) ---------------

    def _is_duplicate_push(self, spec: dict) -> bool:
        """Record-and-test the spec's task id against pushes this process
        already accepted (at-most-once admission per incarnation)."""
        task_id = spec.get("task_id")
        if not task_id:
            return False
        with self._ev_lock:
            if task_id in self._seen_pushes:
                return True
            self._seen_pushes[task_id] = True
            while len(self._seen_pushes) > 4096:
                self._seen_pushes.popitem(last=False)
        return False

    def rpc_push_task(self, spec: dict):  # idempotent
        if self._is_duplicate_push(spec):
            # Refused (False): the agent releases this dispatch's lease;
            # the first delivery owns the task's fate.
            return False
        self._q.put(("task", spec))
        return True

    def rpc_create_actor(self, spec: dict):
        self._actor_id = spec["actor_id"]
        # Group queues exist from the start so calls routed to a group
        # can never race the constructor (their executor threads spawn
        # after the ctor and gate on _actor_ready regardless).
        for group in (spec.get("concurrency_groups") or {}):
            self._group_queues[group] = queue.Queue()
        self._q.put(("actor_ctor", spec))
        return True

    def rpc_push_actor_task(self, spec: dict):  # idempotent
        if self._is_duplicate_push(spec):
            # The caller's retry after a lost reply (sever-after-send):
            # the first delivery is (or was) executing — exactly-once
            # observable effect per incarnation.
            return True
        group = spec.get("concurrency_group")
        q = self._group_queues.get(group) if group else None
        if group and q is None:
            rec = self._record(spec, "ACTOR_TASK")
            self._store_error(
                spec,
                TaskError(
                    spec.get("method", "actor_task"),
                    f"actor has no concurrency group {group!r}",
                    "no-such-group",
                ),
            )
            self._end_borrows(spec)
            # Visible to the state API like every other failure path.
            self._finish(rec, f"no concurrency group {group!r}")
            return False
        (q or self._q).put(("actor_task", spec))
        return True

    def rpc_ping(self):
        return "pong"

    def rpc_set_failpoints(self, specs: dict):
        """Arm/disarm failpoints in this worker process (the tail of the
        head -> agents -> workers control-plane fanout)."""
        return failpoints.set_failpoints(specs)

    def rpc_list_failpoints(self):
        return failpoints.list_armed()

    def rpc_set_channel_chaos(self, rules: list, label: str = ""):
        from ray_tpu.cluster.rpc import channel_chaos

        return channel_chaos.add_rule_dicts(rules, label)

    def rpc_clear_channel_chaos(self, label: str | None = None):
        from ray_tpu.cluster.rpc import channel_chaos

        return channel_chaos.clear(label)

    # -- stack introspection (reporter-agent py-spy analog, in-process) ----

    def rpc_dump_stack(self):
        """Instantaneous stack report of every thread in this worker
        (``ray stack`` target; serves the agent/head routing chain)."""
        from ray_tpu.util import stack_sampler

        return stack_sampler.dump_stacks(
            header=f"worker {self.worker_id} (pid {os.getpid()})")

    def rpc_profile(self, duration_s: float = 1.0,
                    interval_s: float = 0.01):
        """Time-sampled profile of this worker's threads. Blocking is
        fine: the RPC server is thread-per-connection, so the executor
        keeps running the task being profiled."""
        from ray_tpu.util import stack_sampler

        prof = stack_sampler.sample(duration_s, interval_s)
        prof["worker_id"] = self.worker_id
        return prof

    def rpc_capture_profile(self, duration_s: float = 1.0,
                            interval_s: float = 0.01,
                            out_dir: str | None = None):
        """Timed profiler window over this worker: ``jax.profiler.trace``
        when this process has jax loaded (XLA host+device tracks), the
        stack sampler otherwise. With ``out_dir`` (the agent's capture
        dir — same host, shared filesystem) the trace files are written
        THERE and only a ``{kind, files: {name: size}}`` manifest rides
        the RPC; a multi-hundred-MB TPU trace never transits a frame.
        Without it, falls back to inline ``{name: bytes}``."""
        from ray_tpu.util import device_telemetry

        if out_dir is not None:
            return device_telemetry.capture_to_dir(
                out_dir, float(duration_s), float(interval_s),
                worker_id=self.worker_id)
        return device_telemetry.capture(
            float(duration_s), float(interval_s),
            worker_id=self.worker_id)

    def rpc_device_stats(self):
        """Immediate device snapshot of this worker (state API's fresh
        path; the batched flusher remains the steady-state feed)."""
        from ray_tpu.util import device_telemetry

        return device_telemetry.snapshot()

    def rpc_cancel_task(self, task_id: str, force: bool = False):
        """Cancel a task this worker holds. Queued: marked so the executor
        skips it and stores TaskCancelledError. Running: the class is
        injected into the executor thread (best-effort — delivery waits
        out any C-level block); a running COROUTINE is cancelled through
        its asyncio future instead. ``force`` is handled by the agent
        killing the process; by the time it reaches us it degrades to
        cooperative.
        """
        with self._ev_lock:
            fut = self._async_futs.get(task_id)
        if fut is not None:
            return "running" if fut.cancel() else "queued"
        running = self._cancels.cancel(task_id, TaskCancelledError)
        return "running" if running else "queued"

    # -- execution ---------------------------------------------------------

    def _begin_cancellable(self, spec) -> bool:
        """Register this thread as the runner of ``spec``. Returns False if
        the task was already cancelled (caller must not run it)."""
        return self._cancels.begin(spec.get("task_id"), threading.get_ident())

    def _end_cancellable(self, spec) -> None:
        """Unregister; if a cancel raced with completion, clear the
        injected-but-undelivered exception so it cannot land on the NEXT
        task this thread runs."""
        self._cancels.end(spec.get("task_id"), threading.get_ident())

    def _store_cancelled(self, spec, rec) -> None:
        name = spec.get("fname") or spec.get("method", "task")
        self._store_error(spec, TaskCancelledError(name))
        self._end_borrows(spec)
        rec["state"] = "CANCELLED"
        rec["end_time"] = time.time()
        rec["error"] = "cancelled"
        with self._ev_lock:
            self._task_events.append(rec)

    def _exec_loop(self, q: queue.Queue | None = None):
        q = q if q is not None else self._q
        while True:
            kind, spec = q.get()
            try:
                if kind == "task":
                    # finally: a late-delivered cancel injection escaping
                    # _run_task's handlers must not skip the lease release.
                    try:
                        self._run_task(spec)
                    finally:
                        self.agent.call("task_done", self.worker_id)
                elif kind == "actor_ctor":
                    self._run_actor_ctor(spec)
                elif kind == "actor_task":
                    self._run_actor_task(spec)
            except Exception:
                _wp_metrics.count_loop_restart("worker.exec")
                traceback.print_exc()

    def _resolve_function(self, spec):
        """Function-table lookup (reference function_manager fetch +
        cache): specs carry a content hash; the blob comes from the
        cluster KV once and the DESERIALIZED function is reused for
        every subsequent task with the same hash."""
        blob = spec.get("func")
        if blob is not None:  # legacy inline-blob spec (lineage replays)
            return ser.loads(blob)
        h = spec["func_hash"]
        func = self._fn_cache.get(h)
        if func is None:
            blob = self.backend.head.call("kv_get", h)
            if blob is None:
                raise TaskError(
                    spec.get("fname", "task"),
                    f"function {h} missing from the cluster function table",
                    "fn-table-miss",
                )
            func = ser.loads(blob)
            self._fn_cache[h] = func
            if len(self._fn_cache) > 256:
                self._fn_cache.popitem(last=False)
        else:
            self._fn_cache.move_to_end(h)
        return func

    def _resolve(self, args, kwargs):
        # Argument materialization pulls at the LOWEST priority class
        # (pull_manager.h ordering: get > wait > task args) — a worker
        # hydrating a queued task's args must not starve a user's
        # explicit ray.get. ONE batched get for all ref args: the
        # location long-poll batches and fetches run concurrently.
        refs = [a for a in args if isinstance(a, ObjectRef)] + [
            v for v in kwargs.values() if isinstance(v, ObjectRef)
        ]
        if not refs:
            return list(args), dict(kwargs)
        with self.backend.pull_priority_override(self.backend.PULL_ARGS):
            values = iter(self.backend.get(refs))
            args = [next(values) if isinstance(a, ObjectRef) else a
                    for a in args]
            kwargs = {
                k: next(values) if isinstance(v, ObjectRef) else v
                for k, v in kwargs.items()
            }
        return args, kwargs

    def _store_result(self, spec, result):
        oids, num_returns = spec["oids"], spec.get("num_returns", 1)
        if num_returns == "streaming":
            # Generator protocol: yield i -> return-index i; a _StreamEnd
            # after the last item marks the length. A mid-stream failure
            # stores the error AT the failing index (the consumer raises
            # there) — the generic oids error path is disabled since
            # index 0 may already hold a yielded item.
            from ray_tpu.core.ids import task_of_object
            from ray_tpu.core.object_ref import _StreamEnd

            task_id = task_of_object(oids[0])[0]
            from ray_tpu.core import ids as _ids

            spec["oids"] = []
            owner = spec.get("owner_addr")
            i = 0
            try:
                for item in result:
                    self.backend.put_with_id(
                        _ids.object_id_for(task_id, i), item, owner=owner)
                    i += 1
                self.backend.put_with_id(
                    _ids.object_id_for(task_id, i), _StreamEnd(),
                    owner=owner)
            except BaseException as e:  # noqa: BLE001
                self.backend.put_with_id(
                    _ids.object_id_for(task_id, i),
                    TaskError(spec.get("fname", "task"),
                              traceback.format_exc(), repr(e)),
                    is_error=True, owner=owner,
                )
                raise
            return
        if num_returns == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != num_returns:
                raise ValueError(
                    f"task declared num_returns={num_returns} but returned "
                    f"{len(values)}"
                )
        for oid, v in zip(oids, values):
            self.backend.put_with_id(oid, v, owner=spec.get("owner_addr"))

    def _store_error(self, spec, err: BaseException):
        for oid in spec["oids"]:
            self.backend.put_with_id(oid, err, is_error=True,
                                     owner=spec.get("owner_addr"))

    def _end_borrows(self, spec):
        """Release the task's arg borrows — AFTER flushing our own holder
        registrations, so a ref this task deserialized and kept can never
        be freed in the gap (borrower handoff ordering)."""
        if spec.get("borrowed") and spec.get("task_id"):
            self.backend.flush_refs()
            try:
                self.backend.head.call("ref_task_end", spec["task_id"])
            except Exception:
                pass

    def _run_task(self, spec):
        rec = self._record(spec, "NORMAL_TASK")
        if not self._begin_cancellable(spec):
            self._store_cancelled(spec, rec)
            return
        # Only plain tasks hold a per-task lease worth releasing while
        # blocked; actor lifetime resources stay held (reference semantics).
        self.backend._block_hooks = self._hooks
        err = None
        clock = _PhaseClock(rec["phases"])
        try:
            from ray_tpu.util import tracing

            func = self._resolve_function(spec)
            args, kwargs = ser.loads(spec["args"])
            args, kwargs = self._resolve(args, kwargs)
            clock.lap("get_args")
            # Chaos sites inside the try: a raise-action failpoint is
            # stored as the task's error (visible, retryable), a kill
            # action crashes the process mid-protocol — both the faults
            # the owner-side recovery machinery must absorb.
            failpoints.hit("worker.execute.before")
            # Attribution context: puts made while the task runs (its
            # returns AND nested ray_tpu.put calls in user code) carry
            # the creating task's name.
            with attribution.task_context(spec.get("fname", "task"),
                                          spec.get("callsite")):
                if spec.get("trace_ctx"):
                    tracing.enable()  # the driver traces: continue here
                    with tracing.span(
                            f"run:{spec.get('fname', 'task')}",
                            {"task_id": spec.get("task_id"),
                             "worker_id": self.worker_id},
                            parent=spec["trace_ctx"]):
                        result = func(*args, **kwargs)
                else:
                    result = func(*args, **kwargs)
                clock.lap("execute")
                self._store_result(spec, result)
                clock.lap("put_outputs")
                failpoints.hit("worker.execute.after")
        except BaseException as e:  # noqa: BLE001 — stored, not dropped
            err = repr(e)
            if isinstance(e, (TaskError, ActorError)):
                self._store_error(spec, e)
            else:
                self._store_error(
                    spec,
                    TaskError(
                        spec.get("fname", "task"), traceback.format_exc(), repr(e)
                    ),
                )
        finally:
            # Nested so a cancel injection delivered INSIDE this finally
            # (the tiny window before _end_cancellable clears it) cannot
            # abort the remaining cleanup steps.
            try:
                self._end_cancellable(spec)
            finally:
                self.backend._block_hooks = None
                try:
                    self._end_borrows(spec)
                finally:
                    self._finish(rec, err)

    def _run_actor_ctor(self, spec):
        rec = self._record(spec, "ACTOR_CREATION_TASK")
        err = None
        clock = _PhaseClock(rec["phases"])
        try:
            cls = ser.loads(spec["func"])
            args, kwargs = ser.loads(spec["args"])
            args, kwargs = self._resolve(args, kwargs)
            clock.lap("get_args")
            with attribution.task_context(
                    spec.get("fname", "actor.__init__"),
                    spec.get("callsite")):
                self._actor_instance = cls(*args, **kwargs)
            clock.lap("execute")
        except BaseException as e:  # noqa: BLE001
            err = repr(e)
            self._actor_dead_cause = traceback.format_exc()
            try:
                self.agent.call(
                    "actor_ctor_failed", self._actor_id, self._actor_dead_cause
                )
            except Exception:
                pass
        finally:
            import asyncio
            import inspect

            inst = self._actor_instance
            if inst is not None:
                # Async actor = any public coroutine method (class-level
                # scan; instance descriptors stay untouched). Reference:
                # async actors get an asyncio loop, and ALL their methods
                # run on it.
                self._actor_is_async = any(
                    asyncio.iscoroutinefunction(f)
                    for _, f in inspect.getmembers(
                        type(inst), inspect.isfunction)
                )
            self._end_borrows(spec)
            self._finish(rec, err)
            self._actor_ready.set()
            for _ in range(int(spec.get("max_concurrency", 1)) - 1):
                threading.Thread(target=self._exec_loop, daemon=True).start()
            for group, n in (spec.get("concurrency_groups") or {}).items():
                gq = self._group_queues[group]  # created at rpc_create_actor
                for _ in range(max(1, int(n))):
                    threading.Thread(
                        target=self._exec_loop, args=(gq,), daemon=True
                    ).start()

    def _ensure_aio_loop(self):
        import asyncio

        with self._aio_lock:
            if self._aio_loop is None:
                loop = asyncio.new_event_loop()
                threading.Thread(
                    target=loop.run_forever, daemon=True).start()
                self._aio_loop = loop
        return self._aio_loop

    def _run_actor_task_async(self, spec, method):
        """Async-actor call (reference async actors: EVERY method of an
        async actor runs on its ONE event loop — coroutines interleave at
        await points, sync methods block the loop while they run, so
        actor state keeps loop-serialized mutual exclusion). The executor
        thread only resolves args and schedules; completion bookkeeping
        (store/borrows/record, which do blocking RPCs) runs on a
        dedicated completion thread, never the loop."""
        import asyncio

        rec = self._record(spec, "ACTOR_TASK")
        if not self._begin_cancellable(spec):
            self._store_cancelled(spec, rec)
            return
        task_id = spec.get("task_id")
        fut = None
        clock = _PhaseClock(rec["phases"])
        try:
            args, kwargs = ser.loads(spec["args"])
            args, kwargs = self._resolve(args, kwargs)
            clock.lap("get_args")
            failpoints.hit("worker.execute.before")
            if asyncio.iscoroutinefunction(
                    getattr(method, "__func__", method)):
                coro = method(*args, **kwargs)
            else:
                # sync method of an async actor: run ON the loop (blocks
                # other coroutines for its duration — reference behavior)
                async def coro_wrapper():
                    return method(*args, **kwargs)

                coro = coro_wrapper()

            # Attribution rides the asyncio Task's context (contextvar):
            # nested ray_tpu.put calls inside the method attribute to it
            # like every sync path, without leaking to interleaved
            # coroutines at await points.
            async def attributed(inner=coro):
                with attribution.task_context(
                        spec.get("method", "actor_task"),
                        spec.get("callsite")):
                    return await inner

            fut = asyncio.run_coroutine_threadsafe(
                attributed(), self._ensure_aio_loop())
            if task_id:
                with self._ev_lock:
                    self._async_futs[task_id] = fut
        except BaseException as e:  # noqa: BLE001
            if isinstance(e, (TaskError, ActorError)):
                self._store_error(spec, e)
            else:
                self._store_error(
                    spec,
                    TaskError(spec.get("method", "actor_task"),
                              traceback.format_exc(), repr(e)),
                )
            self._end_borrows(spec)
            self._finish(rec, repr(e))
            return
        finally:
            # Registered in _async_futs (or failed): cancel now targets
            # the future, not this thread. A cancel landing inside the
            # resolve phase above still injects into this thread and is
            # handled by the except path like the sync flow.
            self._end_cancellable(spec)

        def done(f):
            if task_id:
                with self._ev_lock:
                    self._async_futs.pop(task_id, None)
            if f.cancelled():
                # Same record shape as a sync cancel: CANCELLED, not FAILED.
                self._store_cancelled(spec, rec)
                return
            # The coroutine ran between the schedule and this callback:
            # everything since the get_args lap is the execute phase
            # (includes loop queueing — the time the CALL took).
            clock.lap("execute")
            err = None
            try:
                with attribution.task_context(
                        spec.get("method", "actor_task"),
                        spec.get("callsite")):
                    self._store_result(spec, f.result())
                clock.lap("put_outputs")
                failpoints.hit("worker.execute.after")
            except BaseException as e:  # noqa: BLE001
                err = repr(e)
                if isinstance(e, (TaskError, ActorError)):
                    self._store_error(spec, e)
                else:
                    self._store_error(
                        spec,
                        TaskError(spec.get("method", "actor_task"),
                                  "".join(traceback.format_exception(e)),
                                  repr(e)),
                    )
            finally:
                try:
                    self._end_borrows(spec)
                finally:
                    self._finish(rec, err)

        # Done-callbacks fire on the thread that resolves the future (the
        # loop thread) — hand the blocking bookkeeping to the completion
        # worker so a slow head RPC can't stall every other coroutine.
        fut.add_done_callback(
            lambda f: self._async_done_q.put((done, f)))

    def _async_done_loop(self):
        while True:
            fn, fut = self._async_done_q.get()
            try:
                fn(fut)
            except Exception:
                _wp_metrics.count_loop_restart("worker.async_done")
                traceback.print_exc()

    def _run_actor_task(self, spec):
        self._actor_ready.wait(timeout=300.0)
        inst = self._actor_instance
        if inst is not None and self._actor_is_async:
            m = getattr(inst, spec.get("method", ""), None)
            if m is not None:
                return self._run_actor_task_async(spec, m)
        rec = self._record(spec, "ACTOR_TASK")
        if not self._begin_cancellable(spec):
            self._store_cancelled(spec, rec)
            return
        err = None
        clock = _PhaseClock(rec["phases"])
        try:
            if self._actor_instance is None:
                raise ActorError(
                    f"actor is dead: {self._actor_dead_cause or 'not constructed'}"
                )
            args, kwargs = ser.loads(spec["args"])
            args, kwargs = self._resolve(args, kwargs)
            clock.lap("get_args")
            failpoints.hit("worker.execute.before")
            method = getattr(self._actor_instance, spec["method"])
            with attribution.task_context(
                    spec.get("method", "actor_task"),
                    spec.get("callsite")):
                result = method(*args, **kwargs)
                clock.lap("execute")
                self._store_result(spec, result)
                clock.lap("put_outputs")
                failpoints.hit("worker.execute.after")
        except BaseException as e:  # noqa: BLE001
            err = repr(e)
            if isinstance(e, (TaskError, ActorError)):
                self._store_error(spec, e)
            else:
                self._store_error(
                    spec,
                    TaskError(
                        spec.get("method", "actor_task"),
                        traceback.format_exc(),
                        repr(e),
                    ),
                )
        finally:
            try:
                self._end_cancellable(spec)
            finally:
                try:
                    self._end_borrows(spec)
                finally:
                    self._finish(rec, err)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--head", required=True)
    parser.add_argument("--agent", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--store", required=True)
    parser.add_argument("--worker-id", required=True)
    args = parser.parse_args()

    handler = WorkerHandler(
        args.head, args.agent, args.node_id, args.store, args.worker_id
    )
    server = RpcServer(handler)
    handler.agent.call(
        "register_worker", args.worker_id, server.address,
        handler.backend.client_id,
    )
    threading.Event().wait()  # serve forever; the agent kills us


if __name__ == "__main__":
    main()
