"""Multiprocess cluster runtime: head (control plane) + node agents + workers.

This package is the native-runtime analog of the reference's process
topology (SURVEY.md §3.1):

  * ``head``        — GCS-equivalent control plane (``src/ray/gcs``): node /
                      actor / object directories, KV, placement groups,
                      cluster scheduler.
  * ``node_agent``  — raylet-equivalent per-node daemon (``src/ray/raylet``):
                      worker pool, local resource accounting, local shm
                      object store (C++), object serving to peers.
  * ``workerproc``  — worker process (core-worker equivalent,
                      ``src/ray/core_worker``): executes tasks, hosts actors,
                      stores results in the node store.
  * ``client``      — the driver/worker in-process runtime implementing the
                      same Backend surface as ``core.local_backend``.

Processes talk over length-prefixed pickled RPC on TCP (the reference uses
gRPC; the wire is an implementation detail, the protocol shape — leases,
directories, pull-based transfer — is what's mirrored). Simulated multi-node
on one host works exactly like the reference's ``cluster_utils.Cluster``:
every node agent fakes its own node id, resources, and object store segment
(SURVEY.md §4.3).
"""

from ray_tpu.cluster.cluster_utils import Cluster
