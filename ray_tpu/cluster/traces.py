"""Cross-node trace assembly: the head-side flight recorder.

Spans reach the head one process at a time (worker event batches →
``rpc_report_spans``); this module stitches them back into the request
they came from. Three jobs, all bounded:

* **Assembly** — group incoming spans by ``trace_id`` into pending
  traces; a trace finalizes once its span stream goes quiet. Cross-node
  timestamps are aligned with the per-node clock offset the agents
  estimate from RPC request/response timestamps (NTP-style probe:
  ``offset = ((t1 - t0) + (t2 - t3)) / 2``) and report on their
  heartbeat cadence.
* **Tail sampling** — the keep/drop decision happens at finalize time,
  when the whole trace is known: every errored span and every trace
  slower than ``trace_slow_threshold_s`` is kept, the rest are
  deterministically sampled at ``trace_sample_rate``. Kept traces live
  in a bounded ring; every drop is counted by cause (never a silent
  cap). Phase decompositions are recorded for EVERY finalized trace
  before the sampling decision, so windowed aggregates are unbiased.
* **Analysis** — critical-path extraction (the blocking chain: at each
  instant, the deepest active span owns the wall time) and TTFT
  decomposition (the root→first-token interval partitioned into named
  phases — queue / prefill / route / ... — summing exactly to the
  interval, so "which phase IS the TTFT" is arithmetic, not a vibe).

The store is head-state but deliberately backend-agnostic: the local
backend instantiates its own ``TraceStore`` over ``tracing.collect()``
so ``state.get_trace`` / ``state.ttft_decomposition`` answer the same
shape on both backends.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Tuple

# -- phase naming ----------------------------------------------------------
#
# Span-name prefix -> the named phase wall time is attributed to.
# Longest prefix wins; anything unmapped is "other" (which is still
# attributed — the decomposition must partition the interval, not
# cherry-pick the phases it has names for).
_PHASE_PREFIXES: List[Tuple[str, str]] = [
    ("serve.http", "ingress"),
    ("serve.route", "route"),
    ("serve.replica", "handle"),
    ("serve.stream", "stream"),
    ("llm.queue", "queue"),
    ("llm.prefill", "prefill"),
    ("llm.decode", "decode"),
    ("llm.step", "decode"),
    ("submit:", "submit"),
    ("run:", "execute"),
    ("rpc:", "rpc"),
]


def phase_of(name: str) -> str:
    best = "other"
    best_len = 0
    for prefix, phase in _PHASE_PREFIXES:
        if name.startswith(prefix) and len(prefix) > best_len:
            best, best_len = phase, len(prefix)
    return best


# -- clock alignment -------------------------------------------------------


class ClockSync:
    """Per-node clock offset, fed by NTP-style RPC timestamp exchanges.

    The agent samples ``t0`` (its send time), the head answers with
    ``(t1, t2)`` (receive / reply time), the agent samples ``t3`` on
    return and reports ``offset = ((t1 - t0) + (t2 - t3)) / 2`` — the
    estimate of (head clock - node clock). Samples ride a min-RTT
    filter: a probe that sat in a TCP queue has a symmetric-delay
    assumption violated, so only the crispest recent exchanges vote.
    """

    _WINDOW = 16

    def __init__(self):
        self._lock = threading.Lock()
        # node_id -> deque[(rtt_s, offset_s)]  guarded-by: _lock
        self._samples: Dict[str, collections.deque] = {}

    def observe(self, node_id: str, offset_s: float, rtt_s: float) -> None:
        with self._lock:
            ring = self._samples.setdefault(
                node_id, collections.deque(maxlen=self._WINDOW))
            ring.append((max(0.0, float(rtt_s)), float(offset_s)))

    def offset_s(self, node_id: Optional[str]) -> float:
        """Best current (head - node) clock offset; 0.0 when unknown
        (the head's own spans, or a node that never probed)."""
        if not node_id:
            return 0.0
        with self._lock:
            ring = self._samples.get(node_id)
            if not ring:
                return 0.0
            # Median offset of the lowest-RTT half: robust to one
            # queued probe without trusting any single exchange.
            best = sorted(ring)[: max(1, len(ring) // 2)]
            offs = sorted(o for _, o in best)
            return offs[len(offs) // 2]

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            nodes = list(self._samples)
        out = {}
        for n in nodes:
            with self._lock:
                ring = list(self._samples.get(n) or ())
            if ring:
                out[n] = {
                    "offset_s": self.offset_s(n),
                    "rtt_s": min(r for r, _ in ring),
                    "samples": len(ring),
                }
        return out


def drop_node(sync: ClockSync, node_id: str) -> None:
    """Forget a dead node's clock samples (retraction discipline)."""
    with sync._lock:
        sync._samples.pop(node_id, None)


# -- assembly + analysis (pure functions over span lists) ------------------


def _dur_ns(s: dict) -> int:
    end = s.get("end_ns") or s.get("start_ns") or 0
    return max(0, end - (s.get("start_ns") or 0))


def find_root(spans: List[dict]) -> Optional[dict]:
    """The trace's root: a span whose parent is absent from the batch
    (the driver-side request span), earliest start wins ties."""
    if not spans:
        return None
    ids = {s["span_id"] for s in spans}
    roots = [s for s in spans
             if not s.get("parent_id") or s["parent_id"] not in ids]
    return min(roots or spans, key=lambda s: s.get("start_ns") or 0)


def _children_map(spans: List[dict]) -> Dict[Optional[str], List[dict]]:
    by_parent: Dict[Optional[str], List[dict]] = {}
    for s in spans:
        by_parent.setdefault(s.get("parent_id"), []).append(s)
    for kids in by_parent.values():
        kids.sort(key=lambda s: s.get("start_ns") or 0)
    return by_parent


def critical_path(spans: List[dict]) -> List[dict]:
    """The blocking chain: partition the root's wall-clock interval so
    that at every instant the deepest active span owns the time.
    Returns ordered segments ``{name, phase, span_id, t0_ns, t1_ns,
    self_s}`` summing exactly to the root's duration."""
    root = find_root(spans)
    if root is None:
        return []
    by_parent = _children_map(spans)
    end_ns = root.get("end_ns") or max(
        (s.get("end_ns") or s.get("start_ns") or 0) for s in spans)
    segments: List[dict] = []

    def emit(span: dict, t0: int, t1: int) -> None:
        if t1 > t0:
            segments.append({
                "name": span["name"], "phase": phase_of(span["name"]),
                "span_id": span["span_id"], "t0_ns": t0, "t1_ns": t1,
                "self_s": (t1 - t0) / 1e9,
            })

    def walk(span: dict, lo: int, hi: int) -> None:
        cursor = lo
        for child in by_parent.get(span["span_id"], ()):
            c0 = max(cursor, min(hi, child.get("start_ns") or cursor))
            c1 = max(c0, min(hi, child.get("end_ns")
                             or child.get("start_ns") or c0))
            if c1 <= cursor:
                continue
            emit(span, cursor, c0)       # gap before the child: ours
            walk(child, c0, c1)
            cursor = c1
        emit(span, cursor, hi)

    walk(root, root.get("start_ns") or 0, end_ns)
    return segments


def ttft_point_ns(spans: List[dict]) -> Optional[int]:
    """When the request's first token existed: the end of the last
    prefill-phase span (continuous batching produces the first token at
    prefill exit). None for traces with no prefill span."""
    pts = [s.get("end_ns") for s in spans
           if phase_of(s["name"]) == "prefill" and s.get("end_ns")]
    return max(pts) if pts else None


def decompose(spans: List[dict],
              until_ns: Optional[int] = None) -> Optional[dict]:
    """Per-phase wall-time attribution of ``[root start, until_ns]``
    (default: the TTFT point, falling back to root end). The phases
    partition the interval, so ``sum(phases.values()) == total_s``
    by construction — the decomposition can't quietly lose time."""
    root = find_root(spans)
    if root is None or root.get("start_ns") is None:
        return None
    if until_ns is None:
        until_ns = ttft_point_ns(spans) or root.get("end_ns")
    if not until_ns or until_ns <= root["start_ns"]:
        return None
    phases: Dict[str, float] = {}
    for seg in critical_path(spans):
        t0 = seg["t0_ns"]
        t1 = min(seg["t1_ns"], until_ns)
        if t1 <= t0 or t0 >= until_ns:
            continue
        phases[seg["phase"]] = phases.get(seg["phase"], 0.0) \
            + (t1 - t0) / 1e9
    if not phases:
        return None
    total = (until_ns - root["start_ns"]) / 1e9
    dominant = max(phases.items(), key=lambda kv: kv[1])[0]
    return {"total_s": total, "phases": phases, "dominant": dominant,
            "root": root["name"]}


def render_tree(spans: List[dict]) -> str:
    """ASCII tree of an assembled trace (the ``ray-tpu trace`` view)."""
    root = find_root(spans)
    if root is None:
        return "(empty trace)"
    by_parent = _children_map(spans)
    t0 = root.get("start_ns") or 0
    lines: List[str] = []

    def fmt(span: dict, depth: int) -> None:
        off_ms = ((span.get("start_ns") or t0) - t0) / 1e6
        dur_ms = _dur_ns(span) / 1e6
        status = span.get("status") or "OK"
        mark = "" if status == "OK" else f"  !! {status}"
        where = span.get("node_id") or f"pid {span.get('pid', '?')}"
        lines.append(
            f"{'  ' * depth}{span['name']}  "
            f"[+{off_ms:.1f}ms  {dur_ms:.1f}ms  {where}]{mark}")
        for child in by_parent.get(span["span_id"], ()):
            fmt(child, depth + 1)

    fmt(root, 0)
    orphans = [s for s in spans if s is not root
               and s.get("parent_id") not in {x["span_id"] for x in spans}]
    for o in orphans:
        fmt(o, 0)
    return "\n".join(lines)


def _percentile(sorted_vals: List[float], q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


# -- the bounded store -----------------------------------------------------


class TraceStore:
    """Bounded assembly store: pending traces accumulate spans, quiet
    traces finalize through tail sampling into a kept ring. Every
    bounded decision is counted (``dropped`` by cause)."""

    def __init__(self, *, max_traces: int = 512,
                 sample_rate: float = 0.05,
                 slow_threshold_s: float = 1.0,
                 max_spans_per_trace: int = 4096,
                 quiet_s: float = 1.5,
                 decomp_retention: int = 2048,
                 exemplar_retention: int = 64):
        self.clock = ClockSync()
        self._lock = threading.Lock()
        self._max_traces = max(1, int(max_traces))
        self._sample_rate = min(1.0, max(0.0, float(sample_rate)))
        self._slow_s = float(slow_threshold_s)
        self._span_cap = max(16, int(max_spans_per_trace))
        self._quiet_s = float(quiet_s)
        # trace_id -> {"spans": [...], "last": mono_ts}  guarded-by: _lock
        self._pending: Dict[str, dict] = {}
        # trace_id -> finalized record (insertion-ordered ring)
        self._kept: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        # Every finalized trace's decomposition (pre-sampling, so the
        # windowed aggregates are unbiased): (wall_ts, dep, decomp).
        self._decomps: collections.deque = collections.deque(
            maxlen=max(16, int(decomp_retention)))
        # deployment -> deque[(wall_ts, ttft_s, trace_id)] of KEPT
        # traces only — an exemplar the CLI can't resolve is worse
        # than none.
        self._exemplars: Dict[str, collections.deque] = {}
        self._exemplar_n = max(4, int(exemplar_retention))
        self.assembled_total = 0
        self.dropped: Dict[str, int] = {
            "sampled": 0, "evicted": 0, "span_cap": 0}

    # -- ingest ------------------------------------------------------------

    def add_spans(self, spans: List[dict],
                  node_id: Optional[str] = None) -> None:
        now = time.monotonic()
        clipped = 0
        with self._lock:
            for s in spans:
                tid = s.get("trace_id")
                if not tid:
                    continue
                if node_id and not s.get("node_id"):
                    s["node_id"] = node_id
                entry = self._pending.get(tid)
                if entry is None:
                    if tid in self._kept:
                        # Straggler span for an already-kept trace:
                        # merge it (idempotently) instead of opening a
                        # second pending trace under the same id.
                        rec = self._kept[tid]
                        if len(rec["spans"]) < self._span_cap and \
                                s["span_id"] not in rec["span_ids"]:
                            self._merge_kept(rec, s)
                        continue
                    entry = self._pending[tid] = {"spans": [],
                                                  "ids": set(),
                                                  "last": now}
                if s["span_id"] in entry["ids"]:
                    continue  # idempotent: event batches can resend
                if len(entry["spans"]) >= self._span_cap:
                    clipped += 1
                    continue
                entry["ids"].add(s["span_id"])
                entry["spans"].append(s)
                entry["last"] = now
        if clipped:
            self._count_drop("span_cap", clipped)
        self.finalize_quiet(now)

    def _merge_kept(self, rec: dict, s: dict) -> None:
        # guarded-by: _lock (callers hold it)
        off = self.clock.offset_s(s.get("node_id"))
        s = self._aligned(s, off)
        rec["spans"].append(s)
        rec["span_ids"].add(s["span_id"])

    @staticmethod
    def _aligned(s: dict, offset_s: float) -> dict:
        if not offset_s:
            return s
        shift = int(offset_s * 1e9)
        s = dict(s)
        if s.get("start_ns"):
            s["start_ns"] = s["start_ns"] + shift
        if s.get("end_ns"):
            s["end_ns"] = s["end_ns"] + shift
        s["clock_offset_s"] = offset_s
        return s

    def _count_drop(self, cause: str, n: int = 1) -> None:
        with self._lock:
            self.dropped[cause] = self.dropped.get(cause, 0) + n
        try:
            from ray_tpu.util import metrics as _metrics

            _metrics.HEAD_TRACES_DROPPED.inc(n, tags={"cause": cause})
        except Exception:
            pass

    # -- finalize / tail-sample --------------------------------------------

    def finalize_quiet(self, now: Optional[float] = None,
                       force: bool = False) -> int:
        """Move quiet pending traces through the tail-sampling decision.
        ``force`` finalizes everything pending (benches, shutdown)."""
        now = time.monotonic() if now is None else now
        ripe: List[Tuple[str, dict]] = []
        with self._lock:
            for tid, entry in list(self._pending.items()):
                if force or now - entry["last"] >= self._quiet_s:
                    ripe.append((tid, self._pending.pop(tid)))
        for tid, entry in ripe:
            self._finalize_one(tid, entry["spans"])
        return len(ripe)

    def _keep_decision(self, tid: str, spans: List[dict],
                       duration_s: float) -> Tuple[bool, str]:
        if any((s.get("status") or "OK") != "OK" for s in spans):
            return True, "error"
        if duration_s >= self._slow_s:
            return True, "slow"
        # Deterministic head-of-id sampling: the same trace id makes
        # the same decision on every node (and in tests).
        try:
            bucket = int(tid[:8], 16) % 10_000
        except (ValueError, TypeError):
            bucket = 0
        if bucket < int(self._sample_rate * 10_000):
            return True, "sampled_in"
        return False, "sampled"

    def _finalize_one(self, tid: str, spans: List[dict]) -> None:
        # Clock-align BEFORE analysis: the critical path of a cross-
        # node trace is garbage if node clocks disagree by more than a
        # hop takes.
        aligned = [self._aligned(s, self.clock.offset_s(s.get("node_id")))
                   for s in spans]
        root = find_root(aligned)
        duration_s = _dur_ns(root) / 1e9 if root else 0.0
        decomp = decompose(aligned)
        dep = None
        for s in aligned:
            dep = (s.get("attributes") or {}).get("deployment") or dep
        wall_ts = time.time()
        self.assembled_total += 1
        if decomp is not None:
            with self._lock:
                self._decomps.append((wall_ts, dep, decomp))
        keep, why = self._keep_decision(tid, aligned, duration_s)
        if not keep:
            self._count_drop("sampled")
            return
        rec = {
            "trace_id": tid,
            "spans": aligned,
            "span_ids": {s["span_id"] for s in aligned},
            "root": root["name"] if root else None,
            "duration_s": duration_s,
            "ts": wall_ts,
            "kept_because": why,
            "deployment": dep,
            "decomposition": decomp,
            "errored": any((s.get("status") or "OK") != "OK"
                           for s in aligned),
        }
        evicted = 0
        with self._lock:
            self._kept[tid] = rec
            while len(self._kept) > self._max_traces:
                self._kept.popitem(last=False)
                evicted += 1
            if decomp is not None and dep is not None:
                ring = self._exemplars.setdefault(
                    dep, collections.deque(maxlen=self._exemplar_n))
                ring.append((wall_ts, decomp["total_s"], tid))
        if evicted:
            self._count_drop("evicted", evicted)

    # -- queries -----------------------------------------------------------

    def get(self, trace_id: str) -> Optional[dict]:
        self.finalize_quiet()
        with self._lock:
            rec = self._kept.get(trace_id)
            if rec is None:
                return None
            out = dict(rec)
            out["spans"] = list(rec["spans"])
            out.pop("span_ids", None)
        out["critical_path"] = critical_path(out["spans"])
        return out

    def list(self, limit: int = 50) -> List[dict]:
        self.finalize_quiet()
        with self._lock:
            recs = list(self._kept.values())[-max(1, int(limit)):]
        return [{k: r[k] for k in
                 ("trace_id", "root", "duration_s", "ts",
                  "kept_because", "deployment", "errored")}
                | {"spans": len(r["spans"]),
                   "dominant": (r["decomposition"] or {}).get("dominant")}
                for r in reversed(recs)]

    def ttft_decomposition(self, window_s: Optional[float] = None,
                           deployment: Optional[str] = None) -> dict:
        """Windowed per-phase p50/p99 over every finalized trace (pre-
        sampling, so percentiles are unbiased by the keep decision)."""
        self.finalize_quiet()
        cutoff = time.time() - window_s if window_s else None
        with self._lock:
            rows = [(ts, dep, d) for ts, dep, d in self._decomps
                    if (cutoff is None or ts >= cutoff)
                    and (deployment is None or dep == deployment)]
        totals = sorted(d["total_s"] for _, _, d in rows)
        phase_vals: Dict[str, List[float]] = {}
        for _, _, d in rows:
            for phase, sec in d["phases"].items():
                phase_vals.setdefault(phase, []).append(sec)
        phases = {}
        for phase, vals in sorted(phase_vals.items()):
            vals.sort()
            phases[phase] = {
                "p50_s": _percentile(vals, 0.5),
                "p99_s": _percentile(vals, 0.99),
                "mean_s": sum(vals) / len(vals),
                "count": len(vals),
            }
        dominant = max(phases.items(),
                       key=lambda kv: kv[1]["p50_s"] or 0.0)[0] \
            if phases else None
        return {
            "traces": len(rows),
            "ttft_p50_s": _percentile(totals, 0.5),
            "ttft_p99_s": _percentile(totals, 0.99),
            "phases": phases,
            "dominant": dominant,
            "phase_sum_p50_s": sum(
                (p["p50_s"] or 0.0) for p in phases.values()),
        }

    def exemplars(self, deployment: Optional[str] = None,
                  min_duration_s: float = 0.0,
                  limit: int = 4) -> List[dict]:
        """Recent kept-trace exemplars, slowest first — what the SLO
        plane attaches to burn events and histogram buckets so a
        burning latency objective names concrete traces."""
        with self._lock:
            rows: List[Tuple[float, float, str]] = []
            for dep, ring in self._exemplars.items():
                if deployment is not None and dep != deployment:
                    continue
                rows.extend(ring)
        rows = [r for r in rows if r[1] >= min_duration_s]
        rows.sort(key=lambda r: r[1], reverse=True)
        return [{"trace_id": tid, "ttft_s": ttft, "ts": ts}
                for ts, ttft, tid in rows[:max(1, int(limit))]]

    def stats(self) -> dict:
        with self._lock:
            return {
                "pending": len(self._pending),
                "kept": len(self._kept),
                "assembled_total": self.assembled_total,
                "dropped": dict(self.dropped),
                "max_traces": self._max_traces,
                "sample_rate": self._sample_rate,
                "slow_threshold_s": self._slow_s,
                "clock": self.clock.snapshot(),
            }
