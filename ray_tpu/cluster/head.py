"""Head server: the cluster control plane (GCS equivalent).

Mirrors the managers booted by the reference GCS
(``src/ray/gcs/gcs_server/gcs_server.cc:119-166``): node manager +
heartbeats, internal KV, actor directory, placement groups with 2-phase
commit across node agents (``gcs_placement_group_scheduler.h:265,423``),
plus the cluster-wide scheduler view. The object directory lives here too
(the reference resolves locations from owners; a central directory is the
simpler equivalent at this scale — the protocol shape toward clients is the
same: "where is object X / tell me when it exists").

Scheduling policy: hybrid — prefer the caller's node until it cannot fit
the demand, then best-fit over the cluster view
(``hybrid_scheduling_policy.cc:26``).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Optional

from ray_tpu.cluster.rpc import RpcClient, RpcServer, channel_chaos
from ray_tpu.core import ids
from ray_tpu.core.config import config
from ray_tpu.util import failpoints

# Heartbeat timeout (reference: num_heartbeats_timeout). The config knob
# scales it: death is declared after node_death_timeout_s with a floor
# that tolerates a few missed heartbeat intervals.
DEAD_AFTER_S = max(config.node_death_timeout_s,
                   10 * config.heartbeat_interval_s)

# The head's shard-lock partial order, machine-readable: cross-shard
# paths (_mark_dead, actor death, create_actor_record borrow holds)
# must acquire strictly left to right. `ray-tpu analyze` imports this
# tuple (lock-order pass, rule LO001) and flags any nesting that
# inverts it, so the documented order and the checked order cannot
# drift — this replaced the round-6 prose comment that could.
LOCK_ORDER = ("_lock", "_obj_lock", "_event_lock")


class _PersistentStore:
    """Write-BEHIND sqlite store behind the head tables (GCS fault
    tolerance: ``store_client/redis_store_client.h:28`` role — here a
    local file so the head can restart on the same address and reload,
    ``gcs_init_data.h`` analog). Namespaced key -> pickled value.

    Round 6: the store used to commit one fsync'd transaction PER WRITE
    on the caller's thread — the first thing to melt under a 100k-task
    burst (every kv_put / node register / snapshot blob serialized the
    control plane behind sqlite). Writes now land in a per-key-coalesced
    dirty queue (last write or delete per (ns, key) wins) that a
    dedicated flusher thread drains as ONE batched transaction every
    ``head_persist_flush_interval_s``, at most ``head_persist_max_batch``
    statements per transaction. Durability contract:

    * a batch commits atomically — a crash mid-flush loses whole batches
      (at most the last interval's writes), never a torn row;
    * a failed flush requeues the batch at the FRONT (newer queued
      writes for the same key win), so transient sqlite errors retry
      without reordering;
    * ``flush()`` drains synchronously — the snapshot loop calls it
      every tick (so ``head.snapshot.before_persist`` failpoints still
      gate real disk writes) and ``close()`` calls it on shutdown;
    * ``load_ns`` flushes first: readers always see their own writes.
    """

    _DELETE = object()  # queue sentinel: key deleted

    def __init__(self, path: str):
        import sqlite3

        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS t "
            "(ns TEXT, k TEXT, v BLOB, PRIMARY KEY (ns, k))"
        )
        self._conn.commit()
        # Dedicated sqlite-connection mutex: serializing commit I/O
        # is this lock's entire job, nothing else contends it.
        self._mu = threading.Lock()  # analyze: allow-blocking
        # Dirty queue: (ns, key) -> blob | _DELETE, insertion-ordered so
        # flush batches drain oldest-first.
        self._dirty: "collections.OrderedDict[tuple, object]" = (
            collections.OrderedDict()
        )
        self._dirty_mu = threading.Lock()
        # Serializes whole flush passes; holding it across the batched
        # sqlite transaction is its entire job (only flush() contends).
        self._flush_mu = threading.Lock()  # analyze: allow-blocking
        self._stop_flusher = threading.Event()
        self._n_coalesced = 0
        self._n_flushes = 0
        self._n_flush_failures = 0
        self._flusher = threading.Thread(
            target=self._flush_loop, daemon=True)
        self._flusher.start()

    def put(self, ns: str, key: str, value) -> None:
        import pickle

        self.put_blob(ns, key, pickle.dumps(value, protocol=5))

    def _enqueue(self, ns: str, key: str, value) -> None:
        from ray_tpu.util import metrics as _metrics

        with self._dirty_mu:
            if (ns, key) in self._dirty:
                # Coalesced: this key's previous pending write never
                # reaches disk — under round-6 shapes most per-key churn
                # (heartbeat-refreshed node records, snapshot blobs)
                # collapses here instead of becoming transactions.
                self._n_coalesced += 1
                try:
                    _metrics.HEAD_PERSIST_COALESCED.inc()
                except Exception:
                    pass
            self._dirty[(ns, key)] = value
            depth = len(self._dirty)
        try:
            _metrics.HEAD_PERSIST_QUEUE_DEPTH.set(depth)
        except Exception:
            pass

    def put_blob(self, ns: str, key: str, blob: bytes) -> None:
        self._enqueue(ns, key, blob)

    def delete(self, ns: str, key: str) -> None:
        self._enqueue(ns, key, self._DELETE)

    def _flush_loop(self) -> None:
        interval = max(0.005, config.head_persist_flush_interval_s)
        while not self._stop_flusher.wait(interval):
            try:
                self.flush()
            except Exception:
                continue  # requeued by flush(); next tick retries

    def flush(self) -> int:
        """Synchronously drain the dirty queue; returns statements
        written. Batches are single transactions (all-or-none)."""
        from ray_tpu.util import metrics as _metrics

        max_batch = max(1, config.head_persist_max_batch)
        written = 0
        with self._flush_mu:
            while True:
                with self._dirty_mu:
                    if not self._dirty:
                        break
                    batch = []
                    while self._dirty and len(batch) < max_batch:
                        batch.append(self._dirty.popitem(last=False))
                t0 = time.perf_counter()
                try:
                    with self._mu:
                        for (ns, key), v in batch:
                            if v is self._DELETE:
                                self._conn.execute(
                                    "DELETE FROM t WHERE ns = ? AND k = ?",
                                    (ns, key))
                            else:
                                self._conn.execute(
                                    "INSERT OR REPLACE INTO t (ns, k, v) "
                                    "VALUES (?, ?, ?)", (ns, key, v))
                        self._conn.commit()
                except Exception:
                    try:
                        with self._mu:
                            self._conn.rollback()
                    except Exception:
                        pass
                    # Requeue the whole batch at the FRONT, oldest last
                    # so order is preserved; a NEWER pending write for
                    # the same key supersedes the failed one.
                    with self._dirty_mu:
                        self._n_flush_failures += 1
                        for k, v in reversed(batch):
                            if k not in self._dirty:
                                self._dirty[k] = v
                                self._dirty.move_to_end(k, last=False)
                    raise
                self._n_flushes += 1
                written += len(batch)
                try:
                    _metrics.HEAD_PERSIST_FLUSH_SECONDS.observe(
                        time.perf_counter() - t0)
                except Exception:
                    pass
        try:
            with self._dirty_mu:
                depth = len(self._dirty)
            _metrics.HEAD_PERSIST_QUEUE_DEPTH.set(depth)
        except Exception:
            pass
        return written

    def stats(self) -> dict:
        with self._dirty_mu:
            return {
                "queued": len(self._dirty),
                "coalesced": self._n_coalesced,
                "flushes": self._n_flushes,
                "flush_failures": self._n_flush_failures,
            }

    def load_ns(self, ns: str) -> dict:
        import pickle

        try:
            self.flush()  # read-your-writes
        except Exception:
            pass
        with self._mu:
            rows = self._conn.execute(
                "SELECT k, v FROM t WHERE ns = ?", (ns,)).fetchall()
        return {k: pickle.loads(v) for k, v in rows}

    def close(self) -> None:
        self._stop_flusher.set()
        try:
            self.flush()
        except Exception:
            pass
        with self._mu:
            try:
                self._conn.commit()
                self._conn.close()
            except Exception:
                pass

    def abandon(self) -> None:
        """Crash simulation (``Cluster.kill_head``): stop the flusher and
        DROP the dirty queue — pending writes die exactly as they would
        in a process kill (whole batches lost, committed batches intact),
        and no zombie flusher keeps writing under a restarted head's
        fresh connection to the same file."""
        self._stop_flusher.set()
        with self._dirty_mu:
            self._dirty.clear()
        with self._mu:
            try:
                self._conn.close()  # uncommitted work rolls back
            except Exception:
                pass


class _ShardLock:
    """RLock that observes time spent WAITING on a contended acquire
    into ``ray_tpu_head_lock_wait_seconds{shard=...}``. An uncontended
    acquire (the overwhelming majority) costs one extra try-acquire and
    records nothing. Condition-compatible: the private RLock protocol
    methods ``threading.Condition`` probes for are delegated, so
    ``Condition(shard_lock)`` behaves exactly like ``Condition(RLock())``
    (cv re-acquires after ``wait`` bypass instrumentation — the wait
    itself isn't contention)."""

    __slots__ = ("_rl", "_shard")

    def __init__(self, shard: str):
        self._rl = threading.RLock()
        self._shard = shard

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._rl.acquire(False):
            return True
        if not blocking:
            return False
        t0 = time.perf_counter()
        ok = self._rl.acquire(True, timeout)
        try:
            from ray_tpu.util import metrics as _metrics

            _metrics.HEAD_LOCK_WAIT_SECONDS.observe(
                time.perf_counter() - t0, tags={"shard": self._shard})
        except Exception:
            pass
        return ok

    def release(self) -> None:
        self._rl.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self._rl.release()

    # threading.Condition integration (RLock protocol delegates).
    def _is_owned(self):
        return self._rl._is_owned()

    def _release_save(self):
        return self._rl._release_save()

    def _acquire_restore(self, state):
        return self._rl._acquire_restore(state)


class NodeInfo:
    def __init__(self, node_id, address, resources, store_path,
                 labels=None):
        self.node_id = node_id
        self.address = address
        self.resources = dict(resources)  # total
        self.available = dict(resources)  # latest reported view
        self.store_path = store_path
        # Provisioning metadata (node_type, spot, ...) the agent carried
        # at registration; the autoscaler's spot-aware bin-packing and
        # the status surfaces read it from the node table.
        self.labels = dict(labels or {})
        self.last_heartbeat = time.monotonic()
        self.alive = True
        # Lifecycle: ALIVE -> (DRAINING ->) DEAD. A DRAINING node keeps
        # heartbeating and serving objects but takes no new placements
        # (node_manager.proto DrainRaylet analog).
        self.state = "ALIVE"
        self.drain_reason = None
        self.drain_started = None
        self.drain_done: threading.Event | None = None
        self.drain_forced = False
        self.drain_duration = None
        self.migrated_actors: list[str] = []
        self.death_cause = None
        self.client = RpcClient(address)

    @property
    def schedulable(self) -> bool:
        return self.alive and self.state != "DRAINING"


class HeadServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 persist_path: str | None = None,
                 metrics_port: int | None = 0):
        self._store = _PersistentStore(persist_path) if persist_path else None
        # Round 6 lock sharding: the single RLock that serialized EVERY
        # head RPC is split along table boundaries so the hot planes
        # stop contending with each other. Cross-shard acquisition order
        # is the module-level LOCK_ORDER tuple (the analyzer enforces
        # it: nodes/actors/PGs -> objects/refs -> spans/logs).
        #
        # Object-plane code reads NodeInfo entries (alive/address/
        # store_path) WITHOUT the node lock: _nodes is insert-only (dead
        # nodes stay, re-registration swaps a fresh NodeInfo), dict gets
        # are GIL-atomic, and every consumer tolerates a node dying
        # between the read and the use (the same race existed across
        # RPCs under the global lock).
        self._lock = _ShardLock("nodes")
        self._obj_lock = _ShardLock("objects")
        self._event_lock = _ShardLock("events")
        self._nodes: dict[str, NodeInfo] = {}  # guarded-by: _lock
        # Incrementally-maintained cluster resource totals: rebuilt on
        # membership/lifecycle transitions (register/drain/death — rare),
        # delta-updated on heartbeats and scheduling debits, so the
        # status-poll RPCs are O(1) dict copies instead of an O(nodes)
        # rebuild under the global lock per poll.
        self._res_total: dict[str, float] = {}  # guarded-by: _lock
        self._res_avail: dict[str, float] = {}  # guarded-by: _lock
        self._kv: dict[str, Any] = {}  # guarded-by: _kv_lock
        self._kv_lock = threading.Lock()  # see rpc_kv_put — KV I/O only
        # Generalized pub/sub plane (src/ray/pubsub analog): LOGS/ACTORS/
        # NODES/ERRORS feeds with long-poll delivery (pubsub.py).
        from ray_tpu.cluster.pubsub import Publisher

        self.pubsub = Publisher()
        # Tracing span store: bounded ring (util/tracing.py feeds it
        # through the agents' worker-event batches); a 100k-task burst's
        # span upload drops oldest instead of growing head RSS, and the
        # drop count surfaces in rpc_pubsub_stats + metrics.
        self._spans: "collections.deque" = collections.deque(  # guarded-by: _event_lock
            maxlen=max(16, config.head_span_retention))
        self._spans_dropped = 0
        # worker/driver-side span truncation shipped with event batches
        # (tracing._record overflow in OTHER processes, re-attributed
        # here so one query answers "is any process clipping spans").
        self._worker_span_drops = 0  # guarded-by: _event_lock
        # object directory: oid -> {"nodes": set, "error": bool}
        self._objects: dict[str, dict] = {}  # guarded-by: _obj_lock
        self._objects_cv = threading.Condition(self._obj_lock)
        # actor directory: actor_id -> info dict
        self._actors: dict[str, dict] = {}  # guarded-by: _lock
        self._actor_specs: dict[str, dict] = {}  # guarded-by: _lock
        self._named_actors: dict[str, str] = {}  # guarded-by: _lock
        self._actors_cv = threading.Condition(self._lock)
        self._pgs: dict[str, dict] = {}  # guarded-by: _lock
        self._rr_counter = 0
        # Distributed ref-counting (reference_count.h:61 analog, centralized):
        # oid -> set of holders. A holder is a client process id ("c:...")
        # or a containing object ("obj:<oid>" — the container keeps nested
        # refs alive). An oid ABSENT from the table is conservatively kept
        # (never freed); an entry with no holders and no in-flight borrows
        # is freed cluster-wide.
        self._refs: dict[str, set] = {}  # guarded-by: _obj_lock
        # oid -> count of in-flight task-arg borrows (submitted-but-running
        # tasks whose args reference the object).
        self._inflight: dict[str, int] = {}  # guarded-by: _obj_lock
        self._inflight_by_task: dict[str, tuple] = {}  # guarded-by: _obj_lock
        self._contained: dict[str, list] = {}  # guarded-by: _obj_lock
        self._freed: dict[str, bool] = {}  # guarded-by: _obj_lock (tombstones, bounded)
        # Abandoned streaming tasks: task_id -> first unconsumed index.
        # Items at indices >= that are freed on sight — including ones
        # the (possibly still running) producer stores AFTER the release.
        self._released_streams: dict[str, int] = {}  # guarded-by: _obj_lock
        self._free_queue: list[tuple] = []  # guarded-by: _obj_lock
        self._free_cv = threading.Condition(self._obj_lock)
        # Remote-spill records: oid -> spill URI. Written when an agent
        # spills to a REMOTE target (rpc_add_spilled); read by the
        # restore plane — a dead node's spilled objects are re-fetched
        # from the URI onto a live node (rpc_restore_spilled / the
        # wait-location hook) instead of being recomputed or lost.
        self._spilled: dict[str, str] = {}  # guarded-by: _obj_lock
        # Restore work queue + in-flight dedup (one restore RPC per oid
        # at a time; waiters block on _objects_cv until the restored
        # location lands through rpc_add_location).
        self._restore_queue: list[str] = []  # guarded-by: _obj_lock
        self._restore_inflight: set[str] = set()  # guarded-by: _obj_lock
        # oid -> last FAILED attempt time: wait-location wakes fire
        # every ~1s per waiter, and without a backoff an unreachable
        # spill target turns into a restore-RPC storm that head-of-line
        # blocks the single restore thread. guarded-by: _obj_lock
        self._restore_backoff: dict[str, float] = {}
        self._restore_cv = threading.Condition(self._obj_lock)
        # Leak sweeper state: oid -> flag record (state.memory_leaks()).
        # Initialized BEFORE the RPC server: _maybe_free clears flags.
        self._leaks: dict[str, dict] = {}  # guarded-by: _obj_lock
        # Unsatisfiable demand log: the autoscaler's input signal
        # (load_metrics.py / resource_demand_scheduler.py analog).
        # Keyed by task id (anonymous misses get a synthetic key) so the
        # retry-refresh is an O(1) move-to-end, not an O(len) list
        # rebuild — at 100k parked infeasible specs the old list filter
        # was quadratic work under the node lock every retry round.
        self._demand_misses: "collections.OrderedDict[str, dict]" = (  # guarded-by: _lock
            collections.OrderedDict()
        )
        self._demand_miss_seq = 0
        # Latest autoscaler self-report (per-type quarantine/backoff
        # state): full-state replace each reconcile tick, read by
        # `ray-tpu status` and the dashboard.
        self._autoscaler_report: dict = {}  # guarded-by: _lock
        # node_id -> terminate-ack record (the autoscaler's confirmation
        # that a drained node's provider resources were released).
        self._terminate_acks: dict[str, dict] = {}  # guarded-by: _lock
        # Worker stdout/stderr ring buffer for driver log streaming
        # (log_monitor.py -> GCS pubsub -> driver analog; drivers poll
        # rpc_drain_logs with their last-seen seq).
        self._logs: "collections.deque[dict]" = collections.deque(  # guarded-by: _event_lock
            maxlen=20_000)
        self._log_seq = 0
        if self._store is not None:
            self._load_persisted()
        from ray_tpu.util import metrics as _metrics

        self._server = RpcServer(
            self, host, port, rpc_histogram=_metrics.HEAD_RPC_SECONDS)
        self.address = self._server.address
        # Chaos source identity: the head's outbound clients (per-node
        # fanouts, drain probes, free broadcasts) are tagged with the
        # head address so Cluster.partition's symmetric drop rules catch
        # head->agent traffic. Nodes reloaded from the persisted store
        # were created before the server bound, so tag them here.
        for n in self._nodes.values():
            n.client.chaos_src = self.address
        # Cluster metrics federation: one HTTP endpoint whose
        # /metrics/cluster body merges every alive agent's registry into
        # a single scrape (plus /metrics for the head's own process and
        # /metrics/targets as a Prometheus file-SD document). Pass
        # metrics_port=None to disable.
        self.metrics_address: str | None = None
        self._metrics_shutdown = None
        if metrics_port is not None:
            from ray_tpu.util import metrics as _metrics

            try:
                bound, self._metrics_shutdown = _metrics.serve_metrics(
                    host, metrics_port, routes={
                        "/metrics": (_metrics.prometheus_text,
                                     _metrics.PROM_CONTENT_TYPE),
                        "/metrics/cluster": (self.cluster_metrics_text,
                                             _metrics.PROM_CONTENT_TYPE),
                        "/metrics/targets": (self._file_sd_text,
                                             "application/json"),
                    })
                self.metrics_address = f"{host}:{bound}"
            except OSError:
                pass  # federation endpoint is best-effort; RPC plane is not
        self._stop = threading.Event()
        self._monitor = threading.Thread(target=self._monitor_loop, daemon=True)
        self._monitor.start()
        threading.Thread(target=self._free_loop, daemon=True).start()
        threading.Thread(target=self._restore_loop, daemon=True).start()
        if config.leak_sweep_interval_s > 0:
            threading.Thread(
                target=self._leak_sweep_loop, daemon=True).start()
        if self._store is not None:
            threading.Thread(target=self._snapshot_loop, daemon=True).start()
        # Signal plane: the head's own metrics history (scrape loop
        # feeds the federated exposition into a bounded ring; SLO loop
        # evaluates burn-rate state over it). 0 interval disables and
        # every history-backed surface degrades to its single-scrape
        # behaviour.
        self._signals = None
        if config.signal_scrape_interval_s > 0:
            from ray_tpu.cluster.signals import SignalPlane

            self._signals = SignalPlane(
                history_s=config.signal_history_s,
                max_series=config.signal_max_series,
                scrape_interval_s=config.signal_scrape_interval_s,
                burn_evals=config.slo_burn_evals)
            threading.Thread(
                target=self._signal_scrape_loop, daemon=True).start()
            if config.slo_eval_interval_s > 0:
                threading.Thread(
                    target=self._slo_eval_loop, daemon=True).start()
        # Trace assembly (cluster/traces.py): spans arriving via
        # rpc_report_spans stitch into whole cross-node traces with
        # tail sampling; the SLO plane reads exemplar trace_ids from it
        # so a burning latency objective names concrete traces.
        from ray_tpu.cluster.traces import TraceStore

        self._traces = TraceStore(
            max_traces=config.head_trace_retention,
            sample_rate=config.trace_sample_rate,
            slow_threshold_s=config.trace_slow_threshold_s,
            max_spans_per_trace=config.trace_max_spans,
            quiet_s=config.trace_quiet_s)
        if self._signals is not None:
            self._signals.set_exemplar_source(self._traces.exemplars)

    # -- persistence ------------------------------------------------------

    def _persist(self, ns: str, key: str, value) -> None:
        if self._store is not None:
            self._store.put(ns, key, value)

    def _persist_del(self, ns: str, key: str) -> None:
        if self._store is not None:
            self._store.delete(ns, key)

    def _load_persisted(self) -> None:
        """Rebuild head tables after a restart (``gcs_init_data.h``).

        Nodes come back provisionally alive — their agents kept running
        and the next heartbeat (or the monitor's timeout) settles truth.
        The ref table is deliberately NOT persisted: it is high-churn, and
        an oid absent from it is conservatively kept (never freed), so a
        restart degrades to no-GC for pre-restart objects instead of
        premature frees.
        """
        # Boot-time runs before the RPC server accepts a single call,
        # but the tables' guarded-by contract is honored anyway: the
        # shard locks are uncontended here and the load stays a valid
        # example of the locking discipline (sqlite reads happen
        # outside the critical sections).
        nodes = self._store.load_ns("node")
        kv = self._store.load_ns("kv")
        snap = self._store.load_ns("snap")
        with self._lock:
            for node_id, rec in nodes.items():
                info = NodeInfo(node_id, rec["address"], rec["resources"],
                                rec["store_path"],
                                labels=rec.get("labels"))
                self._nodes[node_id] = info
            self._actors.update(snap.get("actors", {}))
            for actor_id, rec in self._actors.items():
                if rec.get("name") and rec.get("state") not in ("DEAD",):
                    self._named_actors[rec["name"]] = actor_id
            self._actor_specs.update(snap.get("aspecs", {}))
            self._pgs.update(snap.get("pgs", {}))
            for pg in self._pgs.values():
                # A snapshot taken mid-reschedule persisted the
                # coordinator-active flag; the thread did not survive
                # the restart — clear it so the monitor loop starts a
                # fresh coordinator for any RESCHEDULING group.
                pg["_resched_active"] = False
            self._rebuild_res_caches()
        with self._kv_lock:
            self._kv.update(kv)
        with self._obj_lock:
            for oid, rec in snap.get("objects", {}).items():
                self._objects[oid] = {
                    "nodes": set(rec["nodes"]),
                    "error": rec["error"],
                    "size": rec["size"],
                }

    def _snapshot_loop(self) -> None:
        """Persist the high-churn tables (actors/specs/PGs/object
        locations) every snapshot interval when they changed —
        content-compared so idle clusters write nothing. Crash loss
        window <= one interval; lost object locations heal through
        lineage re-execution."""
        import pickle as _pickle

        last: dict[str, bytes] = {}
        while not self._stop.wait(config.head_snapshot_interval_s):
            try:
                failpoints.hit("head.snapshot.before_persist")
                with self._lock:
                    snap = {
                        "actors": {k: dict(v) for k, v in self._actors.items()},
                        "aspecs": dict(self._actor_specs),
                        "pgs": {k: dict(v) for k, v in self._pgs.items()},
                    }
                with self._obj_lock:
                    snap["objects"] = {
                        oid: {"nodes": sorted(e["nodes"]),
                              "error": e["error"],
                              "size": e.get("size", 0)}
                        for oid, e in self._objects.items()
                    }
                blobs: dict[str, bytes] = {}
                for key, table in snap.items():
                    blob = _pickle.dumps(table, protocol=5)
                    if last.get(key) != blob:
                        blobs[key] = blob
                        self._store.put_blob("snap", key, blob)
                if blobs:
                    # Synchronous drain: the write-behind queue must not
                    # defer snapshot durability past the tick the
                    # failpoint above gated — and ``last`` records
                    # success only after the transaction lands, so a
                    # sqlite failure (blobs requeued) retries next tick.
                    self._store.flush()
                    last.update(blobs)
            except Exception:
                continue  # next tick retries; persistence is best-effort

    # -- nodes ------------------------------------------------------------

    def _rebuild_res_caches(self) -> None:
        """Caller holds self._lock. O(nodes) — only on membership or
        lifecycle transitions (register/drain/death); heartbeats and
        scheduling debits maintain the available cache incrementally."""
        total: dict[str, float] = {}
        avail: dict[str, float] = {}
        for n in self._nodes.values():
            if not n.alive:
                continue
            for k, v in n.resources.items():
                total[k] = total.get(k, 0.0) + v
            if n.schedulable:
                for k, v in n.available.items():
                    avail[k] = avail.get(k, 0.0) + v
        self._res_total, self._res_avail = total, avail

    def rpc_register_node(self, node_id, address, resources, store_path,
                          labels=None):
        with self._lock:
            info = NodeInfo(node_id, address, resources, store_path,
                            labels=labels)
            info.client.chaos_src = self.address
            self._nodes[node_id] = info
            self._rebuild_res_caches()
        self._persist("node", node_id, {
            "address": address, "resources": dict(resources),
            "store_path": store_path, "labels": dict(labels or {}),
        })
        self.pubsub.publish("NODES", node_id, {
            "node_id": node_id, "state": "ALIVE", "address": address,
            "resources": dict(resources), "labels": dict(labels or {}),
        })
        return {"head_time": time.time()}

    def rpc_heartbeat(self, node_id, available):  # idempotent (full-state)
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node.alive:
                return {"ok": False}  # node was declared dead; it must exit
            node.last_heartbeat = time.monotonic()
            if node.schedulable:
                # Incremental availability maintenance: apply the delta
                # between the node's previous view (including any
                # optimistic _pick debits) and the fresh report.
                avail = self._res_avail
                old = node.available
                for k in old.keys() | available.keys():
                    d = available.get(k, 0.0) - old.get(k, 0.0)
                    if d:
                        avail[k] = avail.get(k, 0.0) + d
            node.available = dict(available)
            return {"ok": True}

    def rpc_drain_node(self, node_id, reason: str = "requested",
                       deadline_s: float | None = None, wait: bool = True):
        """Graceful node removal (DrainRaylet analog): the node enters
        DRAINING — excluded from every new task/actor/PG placement while
        heartbeats keep flowing — in-flight tasks get up to ``deadline_s``
        to finish, restartable actors are PROACTIVELY reconstructed on
        other nodes (budget-free: a planned drain must not consume
        ``max_restarts``), then the node is deregistered and its agent
        shut down. ``wait=False`` returns after initiating (the path a
        preempted agent takes: it must not block on its own removal)."""
        if deadline_s is None:
            deadline_s = config.drain_deadline_s
        deadline_s = max(0.0, float(deadline_s))
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None:
                return {"ok": False, "node_id": node_id, "state": "UNKNOWN"}
            if not node.alive:
                return {"ok": False, "node_id": node_id, "state": "DEAD",
                        "cause": node.death_cause}
            started = node.state != "DRAINING"
            if started:
                node.state = "DRAINING"
                node.drain_reason = reason
                node.drain_started = time.monotonic()
                node.drain_done = threading.Event()
                self._rebuild_res_caches()  # no longer schedulable
            evt = node.drain_done
        if started:
            from ray_tpu.util import metrics as _metrics

            _metrics.NODE_DRAINS_TOTAL.inc(tags={"reason": reason})
            self.pubsub.publish("NODES", node_id, {
                "node_id": node_id, "state": "DRAINING", "reason": reason,
            })
            threading.Thread(
                target=self._drain_coordinator,
                args=(node_id, reason, deadline_s), daemon=True,
            ).start()
        if wait and evt is not None:
            evt.wait(deadline_s + 30.0)
        with self._lock:
            node = self._nodes.get(node_id)
            return {
                "ok": True,
                "node_id": node_id,
                "state": node.state if node else "UNKNOWN",
                "reason": reason,
                "migrated_actors": list(node.migrated_actors) if node else [],
                "forced": bool(node.drain_forced) if node else False,
                "duration_s": node.drain_duration if node else None,
            }

    def _drain_coordinator(self, node_id: str, reason: str,
                           deadline_s: float):
        """One drain's lifecycle: migrate restartable actors off, let the
        agent quiesce (finish queued+running tasks) up to the deadline,
        then deregister. Tasks force-killed at deadline expiry recover
        through owner lineage — exempt from their retry budget because
        the death cause below marks the loss as a drain."""
        t0 = time.monotonic()
        deadline = t0 + deadline_s
        failpoints.hit("head.drain.before_migrate")
        with self._lock:
            node = self._nodes.get(node_id)
        if node is None:
            return
        node.migrated_actors = self._migrate_actors_off(node_id, reason)
        # Proactive gang migration: bundles on the draining node move to
        # healthy nodes NOW (prepare/commit elsewhere, then return the
        # old reservation), while the departing node still serves its
        # objects — the placement-group half of the actor migration
        # above. Work killed with the old bundle recovers through owner
        # lineage with the drain retry-budget exemption.
        with self._lock:
            draining_pgs = [
                pg for pg in self._pgs.values()
                if pg["state"] in ("CREATED", "RESCHEDULING") and any(
                    nid == node_id for nid, _ in pg["placement"]
                )
            ]
            for pg in draining_pgs:
                self._pg_mark_rescheduling_locked(
                    pg, f"node {node_id} draining: {reason}")
        try:
            node.client.call("drain_self", reason, deadline_s, timeout=5.0)
        except Exception:
            pass  # agent may already be gone; the mark-dead below settles it
        forced = True
        probe_misses = 0
        while time.monotonic() < deadline and not self._stop.is_set():
            with self._lock:
                if not node.alive:
                    forced = False  # heartbeat monitor raced us to DEAD
                    break
            try:
                st = node.client.call("drain_status", timeout=5.0)
            except Exception:
                # One failed probe may just be a busy agent (RPC
                # timeout); only repeated failures mean it exited on
                # its own and there is nothing left to wait for.
                probe_misses += 1
                if probe_misses >= 3:
                    forced = False
                    break
                time.sleep(0.1)
                continue
            probe_misses = 0
            if st.get("queued", 0) == 0 and st.get("running", 0) == 0 and \
                    all(self._actor_settled_elsewhere(aid, node_id)
                        for aid in node.migrated_actors):
                # Quiet, and every migrated actor is live on another node
                # (or terminally settled) BEFORE the drained agent exits.
                forced = False
                break
            time.sleep(0.1)
        node.drain_forced = forced
        node.drain_duration = round(time.monotonic() - t0, 3)
        from ray_tpu.util import metrics as _metrics

        _metrics.NODE_DRAIN_DURATION_SECONDS.observe(
            node.drain_duration, tags={"reason": reason})
        self._mark_dead(node_id, f"drained: {reason}")
        try:
            node.client.call("shutdown_node", timeout=5.0)
        except Exception:
            pass
        if node.drain_done is not None:
            node.drain_done.set()

    def _actor_settled_elsewhere(self, actor_id: str, node_id: str) -> bool:
        """Locked-free check: has a migrated actor finished leaving the
        draining node (ALIVE on another node, or terminally DEAD)?"""
        with self._lock:
            info = self._actors.get(actor_id)
            if info is None or info["state"] == "DEAD":
                return True  # restart failed/killed meanwhile: settled
            return info["state"] == "ALIVE" and info["node_id"] != node_id

    def _migrate_actors_off(self, node_id: str, reason: str) -> list[str]:
        """Proactive migration (the drain half of ReconstructActor): every
        restartable ALIVE actor on the node transitions to RESTARTING
        WITHOUT burning ``restarts_left`` — planned removal is not a crash
        — and reconstructs through the ordinary restart path, which the
        scheduler now points away from this node. The old incarnation's
        worker is detached-and-killed agent-side so its death is plain
        worker cleanup, not a second (budget-consuming) actor death."""
        moved: list[str] = []
        with self._lock:
            node = self._nodes.get(node_id)
            for info in self._actors.values():
                if info["node_id"] != node_id or info["state"] != "ALIVE":
                    continue
                rec = self._actor_specs.get(info["actor_id"])
                if rec is None or rec["restarts_left"] == 0:
                    continue  # not restartable: rides the node down
                info["state"] = "RESTARTING"
                info["death_cause"] = (
                    f"node {node_id} draining: {reason}")
                info["num_restarts"] = info.get("num_restarts", 0) + 1
                moved.append(info["actor_id"])
                self.pubsub.publish("ACTORS", info["actor_id"], dict(info))
            if moved:
                self._actors_cv.notify_all()
        if moved:
            from ray_tpu.util import metrics as _metrics

            _metrics.NODE_DRAIN_ACTORS_MIGRATED.inc(
                len(moved), tags={"reason": reason})
        for actor_id in moved:
            if node is not None:
                try:
                    node.client.call(
                        "detach_actor_worker", actor_id, timeout=5.0)
                except Exception:
                    pass
            threading.Thread(
                target=self._restart_actor, args=(actor_id,), daemon=True,
            ).start()
        return moved

    def rpc_nodes(self):  # idempotent (read-only)
        with self._lock:
            return [
                {
                    "NodeID": n.node_id,
                    "Alive": n.alive,
                    "State": n.state,
                    "DrainReason": n.drain_reason,
                    "DeathCause": n.death_cause,
                    "Address": n.address,
                    "Resources": dict(n.resources),
                    "Available": dict(n.available),
                    "StorePath": n.store_path,
                    "Labels": dict(n.labels),
                }
                for n in self._nodes.values()
            ]

    def rpc_cluster_resources(self):
        # O(keys) snapshot of the incrementally-maintained cache: status
        # pollers no longer rebuild dicts over every node under the lock.
        with self._lock:
            return dict(self._res_total)

    def rpc_available_resources(self):
        with self._lock:
            # Clamp float-delta dust from the incremental maintenance:
            # repeated add/subtract of nearly-equal heartbeat values
            # leaves ~1e-16 residue where the true sum is 0.0.
            return {k: (0.0 if -1e-9 < v < 1e-9 else v)
                    for k, v in self._res_avail.items()}

    def _monitor_loop(self):
        # Death needs BOTH (a) absolute staleness > DEAD_AFTER_S and (b)
        # N consecutive monitor ticks each observing staleness. (b) is
        # the false-positive guard for CPU-starved boxes (worker-fork
        # storms at cluster boot, parallel test suites on one core):
        # whatever starves the agents' heartbeat threads starves THIS
        # loop identically, so the required tick count stretches the
        # wall-clock window by exactly the starvation factor — a
        # machine-independent analog of num_heartbeats_timeout counting
        # MISSED heartbeats rather than wall time.
        required = max(4, int(DEAD_AFTER_S / 0.25))
        stale_after = 2 * config.heartbeat_interval_s
        missed: dict[str, int] = {}
        while not self._stop.wait(0.25):
            now = time.monotonic()
            dead = []
            with self._lock:
                for n in self._nodes.values():
                    if not n.alive:
                        missed.pop(n.node_id, None)
                        continue
                    if now - n.last_heartbeat > stale_after:
                        missed[n.node_id] = missed.get(n.node_id, 0) + 1
                        # Both gates: enough consecutive stale ticks AND
                        # absolute staleness — so detection lands at
                        # ~DEAD_AFTER_S on a healthy box, later only by
                        # however much the monitor itself was starved.
                        if missed[n.node_id] >= required and \
                                now - n.last_heartbeat > DEAD_AFTER_S:
                            dead.append(n.node_id)
                    else:
                        missed.pop(n.node_id, None)
            for node_id in dead:
                missed.pop(node_id, None)
                self._mark_dead(node_id, "heartbeat timeout")
            # Self-healing reschedule drivers: a RESCHEDULING group with
            # no live coordinator (an injected coordinator crash, or a
            # head restart that reloaded the state mid-reschedule) gets
            # a fresh one here — the group can never wedge in
            # RESCHEDULING with nothing driving it.
            stuck: list[tuple] = []
            with self._lock:
                for pg in self._pgs.values():
                    if pg["state"] == "RESCHEDULING" \
                            and not pg.get("_resched_active"):
                        pg["_resched_active"] = True
                        stuck.append(
                            (pg["placement_group_id"],
                             pg.get("reschedule_cause") or "unknown"))
            for pg_id, pg_cause in stuck:
                threading.Thread(
                    target=self._reschedule_pg,
                    args=(pg_id, pg_cause), daemon=True).start()

    def _mark_dead(self, node_id: str, cause: str):
        # Cross-shard path: node/actor/PG work under the node lock, THEN
        # the object/ref sweep under the object lock (fixed order). The
        # alive=False flag is written first, so an add_location racing
        # the sweep either sees the flag (skips the node) or lands the
        # location before the sweep removes it — never after.
        self._persist_del("node", node_id)
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node.alive:
                return  # already dead/unknown: no duplicate DEAD event
            if node.state == "DRAINING" and not cause.startswith("drained"):
                # A draining (e.g. preempted) VM can vanish before the
                # coordinator finishes — the heartbeat monitor then wins
                # the race to declare death. Fold the drain reason into
                # the cause so owners still get the retry-budget
                # exemption for exactly the loss it was built for.
                cause = f"drained: {node.drain_reason} ({cause})"
            node.alive = False
            node.state = "DEAD"
            node.death_cause = cause
            self._rebuild_res_caches()
            self.pubsub.publish("NODES", node_id, {
                "node_id": node_id, "state": "DEAD", "cause": cause,
            })
            if self._signals is not None:
                # Age the corpse's series out of the history ring on
                # the death edge — windowed averages must not blend a
                # dead node's last samples into live capacity signals.
                try:
                    self._signals.age_out_node(node_id)
                except Exception:
                    pass
            # Actors on the node die with it; restartable ones reconstruct
            # elsewhere (GcsActorManager::OnNodeDead -> ReconstructActor).
            for info in list(self._actors.values()):
                if info["node_id"] == node_id and info["state"] == "ALIVE":
                    self._on_actor_death(
                        info["actor_id"], f"node {node_id} died: {cause}",
                        True,
                    )
            # Placement groups with bundles there enter RESCHEDULING: the
            # reservation outlives the node that held it — a coordinator
            # re-runs the 2PC for the lost bundles on healthy nodes
            # (gcs_placement_group_manager reschedule-on-dead path).
            # Gangs on a preemptible fleet lose nodes as a matter of
            # course; killing the whole reservation was round-2 debt.
            to_reschedule = [
                pg for pg in self._pgs.values()
                if pg["state"] in ("CREATED", "RESCHEDULING") and any(
                    nid == node_id for nid, _ in pg["placement"]
                )
            ]
            for pg in to_reschedule:
                self._pg_mark_rescheduling_locked(
                    pg, f"node {node_id} died: {cause}")
            self._actors_cv.notify_all()
        with self._obj_lock:
            # Drop its object locations; lineage re-execution is the
            # client's job (object_recovery_manager.h:41 analog).
            for entry in self._objects.values():
                entry["nodes"].discard(node_id)
            # Ref-counting cleanup: worker processes on the node died with
            # it — drop their holds and their tasks' in-flight borrows.
            # (Driver clients use the "d:" prefix and survive node death;
            # their objects are recovered via lineage.)
            prefix = f"w:{node_id}:"
            for oid, holders in list(self._refs.items()):
                dead = {h for h in holders if h.startswith(prefix)}
                if dead:
                    holders.difference_update(dead)
                    self._maybe_free(oid)
            for task_id, (nid, _oids, _a) in list(
                self._inflight_by_task.items()
            ):
                if nid == node_id:
                    self._end_task_borrows(task_id)
            self._objects_cv.notify_all()

    # -- KV ---------------------------------------------------------------

    # The KV is a self-contained subsystem under its own lock: its
    # persistence writes can be multi-MB blobs (runtime-env packages),
    # and doing that disk I/O under the global head lock would stall
    # scheduling/heartbeats/location RPCs for the duration.

    def rpc_kv_put(self, key, value, overwrite=True):
        with self._kv_lock:
            if not overwrite and key in self._kv:
                return False
            self._kv[key] = value
            # Persist under the KV lock: concurrent writers to one key
            # must land on disk in the same order as in memory, or a
            # restart resurrects the loser.
            self._persist("kv", key, value)
        return True

    def rpc_kv_get(self, key):
        with self._kv_lock:
            return self._kv.get(key)

    def rpc_kv_del(self, key):
        with self._kv_lock:
            existed = self._kv.pop(key, None) is not None
            if existed:
                self._persist_del("kv", key)
        return existed

    def rpc_kv_keys(self, prefix=""):
        with self._kv_lock:
            return [k for k in self._kv if k.startswith(prefix)]

    # -- pubsub -----------------------------------------------------------

    def rpc_pubsub_subscribe(self, sub_id, channel, keys=None):  # idempotent
        return self.pubsub.subscribe(sub_id, channel, keys)

    def rpc_pubsub_unsubscribe(self, sub_id, channel=None):
        return self.pubsub.unsubscribe(sub_id, channel)

    def rpc_pubsub_poll(self, sub_id, timeout=10.0, max_msgs=1000):  # idempotent
        # Long-poll: safe to block — the RPC server is thread-per-
        # connection and subscribers poll from a dedicated thread (whose
        # pooled connection is its own).
        return self.pubsub.poll(sub_id, min(float(timeout), 30.0), max_msgs)

    def rpc_publish(self, channel, key, message):
        """External publishers (agents/workers) push through the head —
        e.g. error reports (``rpc_report_error``-style feeds)."""
        return self.pubsub.publish(channel, key, message)

    def rpc_pubsub_stats(self):
        """Pubsub health + the head's other bounded-retention planes
        (span ring, write-behind persistence queue): one RPC answers
        "is the head dropping/queueing anything" at any scale."""
        out = self.pubsub.stats()
        with self._event_lock:
            out["spans"] = {
                "retained": len(self._spans),
                "cap": self._spans.maxlen,
                "dropped": self._spans_dropped,
            }
        if self._store is not None:
            out["persist"] = self._store.stats()
        return out

    # -- tracing span store (util/tracing.py; OTel-shaped) ----------------

    def rpc_report_spans(self, spans, node_id=None, dropped=0):
        if dropped:
            # Worker/driver-side truncation (tracing._record overflowed
            # its bounded buffer) shipped with the batch: fold into the
            # head-scraped counter so `ray-tpu top` sees every drop no
            # matter whose process clipped.
            with self._event_lock:
                self._worker_span_drops += int(dropped)
            try:
                from ray_tpu.util import metrics as _metrics

                _metrics.TRACING_DROPPED_SPANS.inc(
                    int(dropped), tags={"node_id": node_id or "unknown"})
            except Exception:
                pass
        with self._event_lock:
            overflow = max(
                0, len(self._spans) + len(spans) - self._spans.maxlen)
            self._spans.extend(spans)
            if overflow:
                self._spans_dropped += overflow
                try:
                    from ray_tpu.util import metrics as _metrics

                    _metrics.HEAD_SPANS_DROPPED.inc(overflow)
                except Exception:
                    pass
        # Assembly path: the same batch stitches into whole traces
        # (node-attributed so clock-offset alignment knows whose clock
        # stamped each span). Outside _event_lock — the store has its
        # own lock and never calls back into head state.
        self._traces.add_spans(spans, node_id=node_id)
        return True

    def rpc_list_spans(self, trace_id=None, limit: int = 10_000):
        with self._event_lock:
            out = [s for s in self._spans
                   if trace_id is None or s["trace_id"] == trace_id]
            return out[-limit:]

    # -- trace assembly (cluster/traces.py flight recorder) ----------------

    def _drain_own_spans(self) -> None:
        """The head's own spans (rpc: handler spans opened when a
        traced client call carries a traceparent) have no event flusher
        — fold them into the ring + store on the query path."""
        from ray_tpu.util import tracing as _tracing

        if not _tracing.is_enabled():
            return
        spans = _tracing.drain()
        if spans:
            self.rpc_report_spans(spans)

    def rpc_get_trace(self, trace_id: str):
        self._drain_own_spans()
        return self._traces.get(trace_id)

    def rpc_list_traces(self, limit: int = 50):
        self._drain_own_spans()
        return self._traces.list(limit)

    def rpc_trace_stats(self):
        return self._traces.stats()

    def rpc_ttft_decomposition(self, window_s=None, deployment=None):
        self._drain_own_spans()
        return self._traces.ttft_decomposition(window_s, deployment)

    def rpc_clock_probe(self, t0: float):
        """NTP-style exchange for per-node clock-offset estimation: the
        agent sends its clock's ``t0``, we answer (receive time, reply
        time) on ours; the agent computes the offset from the round
        trip and reports it via rpc_report_clock."""
        t1 = time.time()
        return (t1, time.time())

    def rpc_report_clock(self, node_id: str, offset_s: float,
                         rtt_s: float):
        self._traces.clock.observe(node_id, offset_s, rtt_s)
        return True

    # -- distributed ref-counting -----------------------------------------

    def rpc_ref_update(self, client_id, add, remove):
        """Batched holder registration/release from one client process."""
        with self._obj_lock:
            for oid in add:
                if oid in self._freed:
                    continue  # already freed: don't create ghost holders
                self._refs.setdefault(oid, set()).add(client_id)
            for oid in remove:
                if oid in self._freed:
                    continue
                # A remove with no prior entry means the client held and
                # released entirely between flushes — materialize an empty
                # entry so the free condition can fire (otherwise the
                # pinned primary copy would be untracked and immortal).
                holders = self._refs.setdefault(oid, set())
                holders.discard(client_id)
                self._maybe_free(oid)
        return True

    def rpc_ref_task_begin(self, task_id, node_id, oids, actor_id=None):
        """Args of a submitted task borrow their objects until the task
        ends (borrower registration at submission, so the caller may drop
        its handles while the task is in flight)."""
        with self._obj_lock:
            self._end_task_borrows(task_id)  # resubmission replaces
            self._inflight_by_task[task_id] = (node_id, list(oids), actor_id)
            for oid in oids:
                self._inflight[oid] = self._inflight.get(oid, 0) + 1
        return True

    def rpc_ref_task_begin_batch(self, entries):
        """One lock pass for a submitter batch's borrow registrations."""
        with self._obj_lock:
            for task_id, node_id, oids, actor_id in entries:
                self._end_task_borrows(task_id)  # resubmission replaces
                self._inflight_by_task[task_id] = (
                    node_id, list(oids), actor_id)
                for oid in oids:
                    self._inflight[oid] = self._inflight.get(oid, 0) + 1
        return True

    def rpc_ref_task_end(self, task_id):
        with self._obj_lock:
            self._end_task_borrows(task_id)
        return True

    def _end_task_borrows(self, task_id):
        entry = self._inflight_by_task.pop(task_id, None)
        if entry is None:
            return
        _node, oids, _actor = entry
        for oid in oids:
            n = self._inflight.get(oid, 0) - 1
            if n <= 0:
                self._inflight.pop(oid, None)
            else:
                self._inflight[oid] = n
            self._maybe_free(oid)

    def _maybe_free(self, oid):
        """Free the object cluster-wide when nothing can reach it anymore.
        Caller holds self._obj_lock. Untracked oids are conservatively
        kept. NodeInfo reads below are lock-free (see the shard-order
        comment in __init__): a node dying between the alive check and
        the free fanout just costs one failed best-effort RPC."""
        if oid not in self._freed:
            holders = self._refs.get(oid)
            if holders is None or holders:
                return
            if self._inflight.get(oid, 0) > 0:
                return
        self._refs.pop(oid, None)
        self._freed[oid] = True
        if len(self._freed) > 200_000:
            for k in list(self._freed)[:100_000]:
                del self._freed[k]
        entry = self._objects.pop(oid, None)
        self._leaks.pop(oid, None)  # freed: by definition not leaked
        queued_live = False
        if entry is not None:
            created = (entry.get("attr") or {}).get("created_at")
            if created:
                # Lifetime distribution of freed objects: long tails
                # here mean refs (or leaks) outlive their usefulness.
                from ray_tpu.util import metrics as _metrics

                try:
                    _metrics.OBJECT_AGE_SECONDS.observe(
                        max(0.0, time.time() - created))
                except Exception:
                    pass
            for nid in entry["nodes"]:
                node = self._nodes.get(nid)
                if node is not None and node.alive:
                    self._free_queue.append((node, oid))
                    queued_live = True
        # Remote-spilled copy with no live holder (the spiller died):
        # any live node can delete it from the shared target — without
        # this the URI leaks one file per freed object.
        uri = self._spilled.pop(oid, None)
        if uri is not None and not queued_live:
            anynode = next(
                (n for n in self._nodes.values() if n.alive), None)
            if anynode is not None:
                self._free_queue.append((anynode, oid, uri))
        if entry is not None or uri is not None:
            self._free_cv.notify_all()
        # Cascade: the container no longer holds its nested refs.
        for inner in self._contained.pop(oid, []):
            holders = self._refs.get(inner)
            if holders is not None:
                holders.discard("obj:" + oid)
            self._maybe_free(inner)

    def _free_loop(self):
        """Fan out store deletes outside the lock (free-on-zero broadcast)."""
        while not self._stop.is_set():
            with self._free_cv:
                while not self._free_queue and not self._stop.is_set():
                    self._free_cv.wait(0.5)
                batch, self._free_queue = self._free_queue[:], []
            for item in batch:
                try:
                    if len(item) == 3:  # (node, oid, uri): URI-only copy
                        node, oid, uri = item
                        node.client.call("delete_spilled", oid, uri,
                                         timeout=5.0)
                    else:
                        node, oid = item
                        node.client.call("free_object", oid, timeout=5.0)
                except Exception:
                    # Per-item fan-out guard: a dead node's delete is
                    # moot, but the loop itself must survive and say so.
                    from ray_tpu.util import metrics as _metrics

                    _metrics.count_loop_restart("head.free")

    def rpc_ref_client_dead(self, client_id):
        """A client process died: drop every hold it registered."""
        with self._obj_lock:
            for oid, holders in list(self._refs.items()):
                if client_id in holders:
                    holders.discard(client_id)
                    self._maybe_free(oid)
        return True

    def rpc_ref_counts(self):
        """Introspection: live tracked refs (tests / debugging)."""
        with self._obj_lock:
            return {
                "tracked": len(self._refs),
                "inflight_tasks": len(self._inflight_by_task),
                "holders": {
                    oid: sorted(h) for oid, h in self._refs.items() if h
                },
            }

    # -- object directory -------------------------------------------------

    def rpc_stream_release(self, task_id: str, from_index: int):
        """Abandoned ObjectRefGenerator: free the stream's unconsumed
        items — present AND future (a still-running producer's later
        add_locations are deleted on sight)."""
        with self._obj_lock:
            self._released_streams[task_id] = int(from_index)
            if len(self._released_streams) > 100_000:
                for k in list(self._released_streams)[:50_000]:
                    del self._released_streams[k]
            doomed = [
                oid for oid in self._objects
                if oid[:32] == task_id
                and int(oid[32:], 16) >= from_index
            ]
        for oid in doomed:
            with self._obj_lock:
                self._refs.pop(oid, None)
                self._freed[oid] = True
                uri = self._spilled.pop(oid, None)
                entry = self._objects.pop(oid, None)
                queued_live = False
                if entry is not None:
                    for nid in entry["nodes"]:
                        node = self._nodes.get(nid)
                        if node is not None and node.alive:
                            self._free_queue.append((node, oid))
                            queued_live = True
                # Same dead-spiller fanout as _maybe_free: a released
                # stream object whose URI copy has no live holder must
                # still be deleted from the shared target.
                if uri is not None and not queued_live:
                    anynode = next(
                        (n for n in self._nodes.values() if n.alive),
                        None)
                    if anynode is not None:
                        self._free_queue.append((anynode, oid, uri))
                if entry is not None or uri is not None:
                    self._free_cv.notify_all()
        return len(doomed)

    def _stream_released(self, oid: str) -> bool:
        """Locked-context check: is this object part of a released
        stream's unconsumed tail?"""
        idx = self._released_streams.get(oid[:32])
        if idx is None or len(oid) < 40:
            return False
        try:
            return int(oid[32:], 16) >= idx
        except ValueError:
            return False

    def rpc_add_locations(self, items):
        """Batched location adds from a client's ref flusher. Each item:
        (oid, node_id, is_error, size, contained, owner_addr[, attr]).
        The head's directory is the FT fallback + free/spill authority;
        the latency-critical wait path resolves at owners (client.py
        owner service), so these arrive asynchronously batched.
        owner_addr is recorded as object->owner routing
        (ownership_based_object_directory.h: the GCS keeps owner
        routing, not the authoritative location set); attr is the
        put-time attribution record (owner worker id / creating task /
        callsite) feeding memory_summary and the leak sweeper."""
        for item in items:
            self.rpc_add_location(*item)
        return True

    def rpc_owner_of(self, oids):
        """{oid: owner_addr} routing for refs that lost their owner
        binding (O(1) lookup per oid; '' = unknown)."""
        with self._obj_lock:
            return {
                oid: (self._objects.get(oid) or {}).get("owner", "")
                for oid in oids
            }

    def rpc_add_location(self, oid, node_id, is_error=False, size=0,
                         contained=None, owner_addr="", attr=None):
        with self._obj_lock:
            if oid in self._freed or self._stream_released(oid):
                # Freed while the task computing it was still running:
                # delete the fresh copy straight away.
                node = self._nodes.get(node_id)
                if node is not None and node.alive:
                    self._free_queue.append((node, oid))
                    self._free_cv.notify_all()
                return True
            entry = self._objects.setdefault(
                oid, {"nodes": set(), "error": False, "size": 0}
            )
            node = self._nodes.get(node_id)
            if node is not None and node.alive:
                # A location report can arrive AFTER its node died (a
                # batched/reconnect-retried flush landing late):
                # _mark_dead already swept this node's locations, and
                # re-adding one would leave the directory pointing at a
                # store that no longer exists. The attribution/holder
                # bookkeeping below still applies — the object may have
                # live replicas elsewhere.
                entry["nodes"].add(node_id)
            entry["error"] = entry["error"] or is_error
            entry["size"] = max(entry["size"], size)
            if owner_addr:
                entry["owner"] = owner_addr
            # Creation attribution: first writer wins PER KEY (replica/
            # restore reports pass attr=None but may stamp created_at
            # first — the owner's real owner/task/callsite must still
            # land when its batched report arrives later, and the
            # earliest created_at is the creation, not the fetch);
            # attribution-unaware reporters still get a created_at so
            # ages and the leak sweeper work everywhere.
            dst = entry.setdefault("attr", {})
            if attr:
                for k, v in attr.items():
                    if k == "created_at":
                        dst["created_at"] = min(
                            dst.get("created_at", v), v)
                    else:
                        dst.setdefault(k, v)
            dst.setdefault("created_at", round(time.time(), 3))
            if contained:
                # The container holds its nested refs until it is freed.
                self._contained[oid] = list(contained)
                for inner in contained:
                    self._refs.setdefault(inner, set()).add("obj:" + oid)
            self._objects_cv.notify_all()
        return True

    def rpc_objects_on_node(self, node_id):
        """Oids the directory places on this node (spill-candidate input)."""
        with self._obj_lock:
            return [
                oid for oid, e in self._objects.items()
                if node_id in e["nodes"]
            ]

    # -- remote spill records + restore-from-URI --------------------------

    def rpc_add_spilled(self, oids, uri):
        """An agent moved these objects to a REMOTE spill target: record
        them so the copies survive the spiller's death — the restore
        plane re-fetches a dead node's spilled objects from the URI
        instead of recomputing them (external_storage.py + lineage
        recovery composed)."""
        with self._obj_lock:
            for oid in oids:
                if oid in self._freed or self._stream_released(oid):
                    continue  # freed while spilling: don't resurrect
                self._spilled[oid] = uri
        return True

    def rpc_spilled_objects(self):
        """{oid: uri} snapshot of the remote-spill records (tests,
        ``ray-tpu memory`` surfaces)."""
        with self._obj_lock:
            return dict(self._spilled)

    def _queue_restore_locked(self, oid: str) -> None:
        """Caller holds ``_obj_lock``: queue a restore for an oid whose
        only surviving copy is on the remote spill target (idempotent
        per in-flight restore, backed off per failed attempt so an
        unreachable target doesn't become an RPC storm)."""
        if oid in self._restore_inflight:
            return
        if time.monotonic() - self._restore_backoff.get(oid, 0.0) < 5.0:
            return  # recent failed attempt: let the waiter's own
            # deadline (or recomputation fallback) decide, retry later
        self._restore_inflight.add(oid)
        self._restore_queue.append(oid)
        self._restore_cv.notify_all()

    def _restore_loop(self):
        """Fan restore-from-URI RPCs out to live agents OUTSIDE the
        object lock (the free-loop shape). Waiters observe the restored
        location through the normal add_location -> _objects_cv path."""
        while not self._stop.is_set():
            with self._restore_cv:
                while not self._restore_queue and not self._stop.is_set():
                    self._restore_cv.wait(0.5)
                batch, self._restore_queue = self._restore_queue[:], []
            for oid in batch:
                self._restore_one(oid)

    def _restore_one(self, oid: str) -> bool:
        """One restore attempt: pick a live agent, have it fetch the
        object from the spill URI into its store, register the new
        location. Clears the in-flight mark either way (a failed
        attempt re-queues on the next wait-location pass). NodeInfo
        reads are lock-free per the shard-order comment in __init__."""
        with self._obj_lock:
            uri = self._spilled.get(oid)
            entry = self._objects.get(oid)
            owner = (entry or {}).get("owner", "")
            has_live = bool(entry and any(
                self._nodes.get(nid) and self._nodes[nid].alive
                for nid in entry["nodes"]))
        restored_on = None
        if uri is not None and not has_live:
            for cand in list(self._nodes.values()):
                if not cand.alive:
                    continue
                try:
                    ok = bool(cand.client.call(
                        "restore_from_uri", oid, uri, owner,
                        timeout=30.0))
                except Exception:
                    ok = False
                if ok:
                    restored_on = cand
                    break
        if restored_on is not None:
            self.rpc_add_location(oid, restored_on.node_id)
        with self._obj_lock:
            self._restore_inflight.discard(oid)
            if restored_on is not None or has_live:
                self._restore_backoff.pop(oid, None)
            else:
                if len(self._restore_backoff) > 4096:
                    self._restore_backoff.clear()
                self._restore_backoff[oid] = time.monotonic()
            self._objects_cv.notify_all()
        return restored_on is not None or has_live

    def rpc_restore_spilled(self, oid, timeout=30.0):
        """Synchronous restore entry point for lineage recovery
        (client ``_maybe_recover``): if the object has a remote-spill
        record, make sure a live copy exists — restoring from the URI
        if needed — and return its ``(node_id, address, store_path)``
        location (None = not spilled / restore failed: fall back to
        recomputation). Concurrent callers dedup on the in-flight mark
        and wait for the winner's location to land."""
        deadline = time.monotonic() + (timeout or 30.0)
        with self._obj_lock:
            if oid not in self._spilled:
                return None
            claimed = oid not in self._restore_inflight
            if claimed:
                self._restore_inflight.add(oid)
        if claimed:
            self._restore_one(oid)
        with self._obj_lock:
            while True:
                entry = self._objects.get(oid)
                for nid in (entry or {}).get("nodes", ()):
                    node = self._nodes.get(nid)
                    if node is not None and node.alive:
                        return (nid, node.address, node.store_path)
                if claimed or time.monotonic() >= deadline:
                    return None  # our own attempt failed: report now
                self._objects_cv.wait(
                    min(1.0, max(0.05, deadline - time.monotonic())))

    def rpc_remove_location(self, oid, node_id):
        with self._obj_lock:
            entry = self._objects.get(oid)
            if entry:
                entry["nodes"].discard(node_id)
                if not entry["nodes"]:
                    del self._objects[oid]
        return True

    def rpc_wait_location(self, oid, timeout=None):
        """Block until the object exists somewhere; returns
        {"nodes": [...], "error": bool} or None on timeout. The long-poll
        analog of GetObjectStatus."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._obj_lock:
            while True:
                entry = self._objects.get(oid)
                if entry and entry["nodes"]:
                    node_ids = [
                        nid
                        for nid in entry["nodes"]
                        if self._nodes.get(nid) and self._nodes[nid].alive
                    ]
                    if node_ids:
                        return {
                            "nodes": [
                                (nid, self._nodes[nid].address,
                                 self._nodes[nid].store_path)
                                for nid in node_ids
                            ],
                            "error": entry["error"],
                        }
                # No live copy — but a remote-spill record means the
                # bytes still exist on the spill target: kick off a
                # restore (dead-node recovery) and keep waiting; the
                # restored location lands through add_location.
                if oid in self._spilled:
                    self._queue_restore_locked(oid)
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return None
                self._objects_cv.wait(remaining if remaining is None else min(remaining, 1.0))

    def rpc_wait_locations(self, oids, timeout=None):
        """Batched long-poll: block until AT LEAST ONE of ``oids`` has a
        live location (or timeout); returns {oid: {"nodes", "error"}} for
        every oid currently resolvable. One lock pass + one RPC instead
        of a serial wait_location per ref (GetObjectStatus batching)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._obj_lock:
            while True:
                found = {}
                for oid in oids:
                    entry = self._objects.get(oid)
                    if not (entry and entry["nodes"]):
                        continue
                    nodes = [
                        (nid, self._nodes[nid].address,
                         self._nodes[nid].store_path)
                        for nid in entry["nodes"]
                        if self._nodes.get(nid) and self._nodes[nid].alive
                    ]
                    if nodes:
                        found[oid] = {"nodes": nodes,
                                      "error": entry["error"]}
                if found:
                    return found
                # Unresolvable oids whose bytes survive on the remote
                # spill target: trigger restores while we wait (the
                # dead-node recovery path; see rpc_wait_location).
                for oid in oids:
                    if oid in self._spilled:
                        self._queue_restore_locked(oid)
                remaining = (
                    None if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return {}
                self._objects_cv.wait(
                    remaining if remaining is None
                    else min(remaining, 1.0))

    def rpc_locations(self, oid):
        with self._obj_lock:
            entry = self._objects.get(oid)
            if not entry:
                return None
            return {
                "nodes": [
                    (nid, self._nodes[nid].address, self._nodes[nid].store_path)
                    for nid in entry["nodes"]
                    if self._nodes.get(nid) and self._nodes[nid].alive
                ],
                "error": entry["error"],
            }

    # -- actor directory --------------------------------------------------

    def rpc_create_actor_record(self, actor_id, max_restarts,
                                max_task_retries, spec):
        """Keep the creation spec so the head can reconstruct the actor on
        worker/node death (GcsActorManager::ReconstructActor state,
        gcs_actor_manager.cc:1051-1079). -1 = infinite restarts."""
        with self._lock:
            self._actor_specs[actor_id] = {
                "spec": spec,
                "restarts_left": max_restarts,
                "max_task_retries": max_task_retries,
            }
            if max_restarts != 0:
                # A restart replays the ctor, which needs its arg objects:
                # hold them for the actor's whole lifetime (released when
                # it is permanently DEAD). Nested obj-lock acquisition —
                # shard order nodes -> objects.
                with self._obj_lock:
                    for oid in spec.get("borrowed", []):
                        self._refs.setdefault(oid, set()).add(
                            "actor:" + actor_id
                        )
        return True

    def rpc_register_actor(
        self, actor_id, node_id, worker_address, class_name, name=None
    ):
        with self._lock:
            prev = self._actors.get(actor_id)
            if prev is not None and prev["state"] == "DEAD":
                # Killed while its (re)start was in flight: refuse to
                # resurrect; the agent retires the worker.
                raise ValueError(
                    f"actor {actor_id} was killed during (re)start"
                )
            if name:
                existing = self._named_actors.get(name)
                if existing is not None and existing != actor_id and \
                        self._actors[existing]["state"] != "DEAD":
                    raise ValueError(f"actor name {name!r} already taken")
                self._named_actors[name] = actor_id
            rec = self._actor_specs.get(actor_id, {})
            self._actors[actor_id] = {
                "actor_id": actor_id,
                "node_id": node_id,
                "address": worker_address,
                "class_name": class_name,
                "name": name,
                "state": "ALIVE",
                "death_cause": None,
                # Incarnation counter: callers detect restarts (and replay
                # lost calls) by comparing this against their submit-time view.
                "num_restarts": prev.get("num_restarts", 0) if prev else 0,
                # Why the previous incarnation died: callers exempt calls
                # lost to a drain/preemption from max_task_retries.
                "restart_cause": prev.get("death_cause") if prev else None,
                "max_task_retries": rec.get("max_task_retries", 0),
            }
            self._actors_cv.notify_all()
            info = dict(self._actors[actor_id])
            node = self._nodes.get(node_id)
            if node is None or not node.alive:
                # Registration raced the node's death (a drain completing
                # or heartbeat timeout landed between placement and this
                # RPC): _mark_dead's actor sweep already ran and missed
                # this record, so without this check the actor would stay
                # ALIVE at a dead address FOREVER. Process it as the
                # node-death loss it is — same cause format as the sweep,
                # so drain/preemption retry exemptions still apply — and
                # let restartable actors reconstruct elsewhere.
                cause = (node.death_cause if node is not None
                         else None) or "unknown"
                self._on_actor_death(
                    actor_id, f"node {node_id} died: {cause}", True)
                info = dict(self._actors[actor_id])
        self.pubsub.publish("ACTORS", actor_id, info)
        return True

    def rpc_get_actor(self, actor_id, timeout=10.0):
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                info = self._actors.get(actor_id)
                if info is not None:
                    return dict(info)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._actors_cv.wait(min(remaining, 1.0))

    def rpc_get_named_actor(self, name):
        with self._lock:
            actor_id = self._named_actors.get(name)
            if actor_id is None:
                return None
            return dict(self._actors[actor_id])

    def rpc_mark_actor_dead(self, actor_id, cause, allow_restart=True,
                            worker_address=None):
        """``worker_address`` (when the reporter is a node agent: the
        dead worker's RPC address) identifies WHICH incarnation died, so
        the head can drop reports about a PREVIOUS one: a drain-migrated
        actor's OLD worker dying on the node it left (e.g. the
        migration's detach RPC was lost and the worker died still bound)
        must not read as a second death — whether the new incarnation is
        still RESTARTING or already ALIVE. A death of any OTHER worker
        (in particular a restart's constructor process, even one placed
        back on the same node) is processed normally so failed restarts
        still settle to DEAD."""
        with self._lock:
            info = self._actors.get(actor_id)
            if info is not None and allow_restart and worker_address:
                if info["state"] == "ALIVE" and \
                        info.get("address") != worker_address:
                    return True  # stale: the live incarnation is another
                    # process; this report is about a predecessor
                if info["state"] == "RESTARTING" and \
                        info.get("address") == worker_address:
                    return True  # the departing incarnation's death —
                    # the restart it triggered (or the migration that
                    # abandoned it) is already in flight
            self._on_actor_death(actor_id, cause, allow_restart)
        return True

    def rpc_register_actor_failed(self, actor_id, cause):
        """The agent could not bring the actor up (name conflict, killed
        mid-start): record a dead entry so callers fail fast."""
        with self._lock:
            if actor_id not in self._actors:
                self._actors[actor_id] = {
                    "actor_id": actor_id,
                    "node_id": None,
                    "address": None,
                    "class_name": "Actor",
                    "name": None,
                    "state": "DEAD",
                    "death_cause": cause,
                    "num_restarts": 0,
                    "max_task_retries": 0,
                }
                self._actors_cv.notify_all()
            else:
                self._on_actor_death(actor_id, cause, False)
        return True

    def _on_actor_death(self, actor_id, cause, allow_restart):
        """Restart (ReconstructActor) within the max_restarts budget, else
        mark DEAD. Caller holds self._lock."""
        info = self._actors.get(actor_id)
        if info is None or info["state"] == "DEAD":
            return
        rec = self._actor_specs.get(actor_id)
        if (
            allow_restart
            and rec is not None
            and rec["restarts_left"] != 0
            and info["state"] != "RESTARTING"
        ):
            if rec["restarts_left"] > 0:
                rec["restarts_left"] -= 1
            info["state"] = "RESTARTING"
            info["death_cause"] = cause
            info["num_restarts"] = info.get("num_restarts", 0) + 1
            self._actors_cv.notify_all()
            self.pubsub.publish("ACTORS", actor_id, dict(info))
            threading.Thread(
                target=self._restart_actor, args=(actor_id,), daemon=True
            ).start()
            return
        info["state"] = "DEAD"
        info["death_cause"] = cause
        self.pubsub.publish("ACTORS", actor_id, dict(info))
        name = info.get("name")
        if name and self._named_actors.get(name) == actor_id:
            del self._named_actors[name]
        # Calls queued on the dead actor will never report task-end:
        # release their arg borrows. (Kept alive through RESTARTING so
        # replayed calls still find their args.) Nested obj-lock
        # acquisition — shard order nodes -> objects.
        rec = self._actor_specs.pop(actor_id, None)
        with self._obj_lock:
            for task_id, (_n, _o, aid) in list(
                    self._inflight_by_task.items()):
                if aid == actor_id:
                    self._end_task_borrows(task_id)
            # Release the lifetime holds on the ctor's arg objects.
            if rec is not None:
                holder = "actor:" + actor_id
                for oid in rec["spec"].get("borrowed", []):
                    holders = self._refs.get(oid)
                    if holders is not None:
                        holders.discard(holder)
                        self._maybe_free(oid)
        self._actors_cv.notify_all()

    def _restart_actor(self, actor_id):
        """Re-run the creation spec on a live node; the agent re-registers
        the actor (state -> ALIVE) once the ctor finishes."""
        with self._lock:
            rec = self._actor_specs.get(actor_id)
        if rec is None:
            return
        spec = dict(rec["spec"])
        # The original placement (PG bundle / affinity) may have died with
        # the node: restart anywhere the resources fit.
        spec["sinfo"] = {"strategy": None, "pg_id": None,
                         "bundle_index": -1, "node_affinity": None}
        spec["pg_id"], spec["bundle_index"] = None, -1
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline and not self._stop.is_set():
            failpoints.hit("head.restart_actor.tick")
            with self._lock:
                info = self._actors.get(actor_id)
                if info is None or info["state"] != "RESTARTING":
                    return  # killed (or already re-registered) meanwhile
            placed = self.rpc_schedule(spec["demand"])
            if placed is not None:
                node_id, _addr = placed
                with self._lock:
                    node = self._nodes.get(node_id)
                if node is not None:
                    try:
                        node.client.call("submit_task", spec, timeout=30.0)
                        return
                    except Exception:
                        pass
            time.sleep(0.25)
        with self._lock:
            info = self._actors.get(actor_id)
            if info is not None and info["state"] == "RESTARTING":
                self._on_actor_death(
                    actor_id, "restart failed: no placement", False
                )

    def rpc_list_actors(self):
        with self._lock:
            return [dict(v) for v in self._actors.values()]

    # -- state API aggregation + log streaming ----------------------------

    def rpc_list_tasks(self, limit: int = 1000):
        """Fan out to alive agents' task records and merge by recency
        (state_aggregator.py querying raylet GetTasksInfo analog)."""
        with self._lock:
            agents = [
                (n.node_id, n.client) for n in self._nodes.values() if n.alive
            ]
        records = []
        for node_id, client in agents:
            try:
                for rec in client.call("list_task_records", limit, timeout=5.0):
                    rec["node_id"] = node_id
                    records.append(rec)
            except Exception:
                continue  # node died mid-query: best-effort like the reference
        # Actor tasks (direct caller->worker) have no agent submit record;
        # fall back to their start time for recency ordering.
        records.sort(
            key=lambda r: r.get("submitted_at") or r.get("start_time") or 0)
        return records[-limit:]

    def rpc_list_objects(self, limit: int = 1000):
        """Object records from the directory + ref table (no agent RPC),
        sorted by size DESCENDING with the limit applied after the sort
        — ``limit=N`` is the N largest objects, and clipping is reported
        ({"objects", "truncated", "total"}), never silent. Records carry
        the put-time attribution (owner worker id, creating task,
        callsite) and age."""
        now = time.time()
        with self._obj_lock:
            out = []
            for oid, entry in self._objects.items():
                attr = entry.get("attr") or {}
                created = attr.get("created_at")
                out.append({
                    "object_id": oid,
                    "size": entry.get("size", 0),
                    "locations": sorted(entry["nodes"]),
                    "is_error": entry.get("error", False),
                    "ref_holders": len(self._refs.get(oid, ())),
                    "owner": attr.get("owner", ""),
                    "owner_addr": entry.get("owner", ""),
                    "task": attr.get("task", ""),
                    "callsite": attr.get("callsite", ""),
                    "age_s": round(now - created, 3) if created else None,
                })
        out.sort(key=lambda r: r["size"], reverse=True)
        total = len(out)
        return {"objects": out[:limit], "truncated": total > limit,
                "total": total}

    def rpc_worker_logs(self, node_id, pid, lines):
        with self._event_lock:
            for line in lines:
                self._log_seq += 1
                self._logs.append({
                    "seq": self._log_seq,
                    "node_id": node_id,
                    "pid": pid,
                    "line": line,
                })
        # Push-path for live followers (drivers long-poll the LOGS
        # channel); the ring above stays for cursor-based catch-up (CLI).
        self.pubsub.publish(
            "LOGS", node_id, {"node_id": node_id, "pid": pid, "lines": lines}
        )
        return True

    def rpc_drain_logs(self, after_seq: int = 0, limit: int = 1000):
        """Up to ``limit`` log entries newer than after_seq, oldest first;
        returns (cursor, entries) where cursor is the last delivered seq —
        pass it back to resume without loss when truncated. Seqs are
        monotone in the ring, so the common nothing-new poll scans O(1)
        from the right."""
        with self._event_lock:
            newer: list = []
            for e in reversed(self._logs):
                if e["seq"] <= after_seq:
                    break
                newer.append(e)
            newer.reverse()
            entries = newer[:limit]
            cursor = entries[-1]["seq"] if entries else self._log_seq
            return cursor, entries

    # -- node reporter routing (logs / stacks / telemetry) -----------------
    # The per-worker data lives on the agents; the head only routes —
    # the same shape as the reference dashboard head querying each
    # node's reporter agent.

    def _alive_agents(self):
        with self._lock:
            return [(n.node_id, n.client)
                    for n in self._nodes.values() if n.alive]

    def _route_worker(self, worker_id, node_id=None, need_live=False):
        """(node_id, client) of the agent that owns ``worker_id``."""
        agents = self._alive_agents()
        if node_id is not None:
            for nid, client in agents:
                if nid == node_id:
                    return nid, client
            raise ValueError(f"node {node_id!r} is not alive")
        for nid, client in agents:
            try:
                got = client.call("has_worker", worker_id, timeout=5.0)
            except Exception:
                continue
            if got.get("live") or (not need_live and got.get("known")):
                return nid, client
        raise ValueError(
            f"worker {worker_id!r} not found on any alive node")

    def rpc_list_logs(self):
        """Captured worker logs across the cluster (live + recently
        dead workers), merged from every alive agent."""
        out = []
        for _nid, client in self._alive_agents():
            try:
                out.extend(client.call("list_worker_logs", timeout=5.0))
            except Exception:
                continue  # node died mid-query: best-effort
        out.sort(key=lambda r: r.get("started_at") or 0)
        return out

    def rpc_get_log(self, worker_id, stream: str = "out",
                    offset=None, max_bytes: int = 1 << 20,
                    tail_lines=None, node_id=None):
        _nid, client = self._route_worker(worker_id, node_id)
        return client.call(
            "read_worker_log", worker_id, stream, offset, max_bytes,
            tail_lines, timeout=15.0)

    def rpc_follow_log(self, worker_id, stream: str = "out",
                       offset: int = 0, idle_timeout_s: float = 10.0,
                       node_id=None):
        """Server-streamed tail -f proxied from the owning agent (one
        streaming hop per leg of the RPC plane)."""
        _nid, client = self._route_worker(worker_id, node_id)
        return client.call_stream(
            "follow_worker_log", worker_id, stream, offset,
            idle_timeout_s, timeout=idle_timeout_s + 30.0)

    def rpc_dump_worker_stack(self, worker_id, node_id=None):
        _nid, client = self._route_worker(
            worker_id, node_id, need_live=True)
        return client.call("dump_worker_stack", worker_id, timeout=20.0)

    def rpc_profile_worker(self, worker_id, duration_s: float = 1.0,
                           interval_s: float = 0.01, node_id=None):
        _nid, client = self._route_worker(
            worker_id, node_id, need_live=True)
        return client.call(
            "profile_worker", worker_id, duration_s, interval_s,
            timeout=float(duration_s) + 45.0)

    def rpc_worker_stats(self, fresh: bool = False):
        """Per-worker CPU/RSS/uptime across the cluster."""
        out = []
        for stats in self._fanout_agents("worker_stats", fresh,
                                         timeout=10.0):
            out.extend(stats)
        return out

    def _fanout_agents(self, method: str, *args, timeout: float = 5.0,
                       agents=None, args_for=None):
        """Call one RPC on every alive agent CONCURRENTLY and return the
        successful results. The scrape-path aggregations use this so
        latency is the slowest single agent (bounded by ``timeout``),
        not the sum over the cluster — one wedged agent must not stall
        /metrics/cluster past Prometheus's scrape deadline.
        ``args_for(node_id)`` supplies per-agent call args (overriding
        ``*args``) for aggregations whose input is sharded per node,
        e.g. each node's slice of the object directory."""
        agents = self._alive_agents() if agents is None else agents
        if not agents:
            return []

        def one(item):
            nid, client = item
            call_args = args if args_for is None else args_for(nid)
            try:
                return client.call(method, *call_args, timeout=timeout)
            except Exception:
                return None  # node died/wedged mid-query: best-effort

        if len(agents) == 1:
            results = [one(agents[0])]
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                    max_workers=min(16, len(agents))) as pool:
                results = list(pool.map(one, agents))
        return [r for r in results if r is not None]

    def rpc_device_stats(self, fresh: bool = False):
        """Per-worker JAX/XLA device snapshots across the cluster
        (HBM in use/peak/limit per device + compile counters)."""
        out = []
        for snaps in self._fanout_agents("device_stats", fresh,
                                         timeout=10.0):
            out.extend(snaps)
        return out

    # -- memory observability (`ray memory` / memory_summary analog) -------

    def rpc_object_store_stats(self, node_id=None,
                               include_objects: bool = True):
        """Per-node object-store reports: each agent's shm ``stats()``
        joined with per-key ``info()`` (size/refcount/pinned) and the
        attribution embedded in each entry's meta; the head fills
        attribution gaps (e.g. spilled-and-restored copies) from its
        directory and stamps ref-holder counts."""
        with self._lock:
            agents = [
                (n.node_id, n.client) for n in self._nodes.values()
                if n.alive and (node_id is None or n.node_id == node_id)
            ]
        oids_by_node: dict[str, list] = {}
        attr_by_oid: dict[str, dict] = {}
        holders_by_oid: dict[str, int] = {}
        if include_objects:
            with self._obj_lock:
                for oid, e in self._objects.items():
                    for nid in e["nodes"]:
                        oids_by_node.setdefault(nid, []).append(oid)
                    attr_by_oid[oid] = dict(e.get("attr") or {})
                    holders_by_oid[oid] = len(self._refs.get(oid, ()))
        reports = self._fanout_agents(
            "object_store_stats", timeout=15.0, agents=agents,
            args_for=lambda nid: (oids_by_node.get(nid, []),
                                  include_objects))
        now = time.time()
        for rep in reports:
            for rec in rep.get("objects") or []:
                attr = attr_by_oid.get(rec["object_id"]) or {}
                for key in ("owner", "task", "callsite"):
                    if not rec.get(key) and attr.get(key):
                        rec[key] = attr[key]
                if rec.get("age_s") is None and attr.get("created_at"):
                    rec["age_s"] = round(now - attr["created_at"], 3)
                rec["ref_holders"] = holders_by_oid.get(
                    rec["object_id"], 0)
        return reports

    def rpc_memory_summary(self, top_k: int = 20,
                           group_by: str = "callsite"):
        """Cluster-wide memory rollup: per-node shm totals/occupancy,
        top-K resident objects (replicas deduped), and live bytes
        grouped by creation callsite / task / node / owner."""
        if group_by not in ("callsite", "task", "node", "owner"):
            raise ValueError(
                f"group_by must be callsite|task|node|owner, "
                f"got {group_by!r}")
        reports = self.rpc_object_store_stats()
        totals = {"bytes_used": 0, "bytes_capacity": 0, "objects": 0,
                  "evictions": 0, "spilled_bytes": 0, "spilled_objects": 0,
                  "nodes": len(reports)}
        nodes: dict[str, dict] = {}
        best: dict[str, dict] = {}
        for rep in reports:
            st = rep.get("stats") or {}
            nid = rep.get("node_id", "?")
            totals["bytes_used"] += st.get("used", 0)
            totals["bytes_capacity"] += st.get("capacity", 0)
            totals["objects"] += st.get("num_objects", 0)
            totals["evictions"] += st.get("num_evictions", 0)
            totals["spilled_bytes"] += st.get("spilled_bytes", 0)
            totals["spilled_objects"] += st.get("spilled_objects", 0)
            cap = st.get("capacity", 0)
            nodes[nid] = {
                "bytes_used": st.get("used", 0), "bytes_capacity": cap,
                "occupancy": round(st.get("used", 0) / cap, 4) if cap
                else 0.0,
                "objects": st.get("num_objects", 0),
                "evictions": st.get("num_evictions", 0),
                "spilled_bytes": st.get("spilled_bytes", 0),
                "oom_reports": [r.get("path")
                                for r in rep.get("oom_reports") or []],
            }
            for rec in rep.get("objects") or []:
                cur = best.get(rec["object_id"])
                if cur is None:
                    cur = best[rec["object_id"]] = dict(rec)
                    cur["nodes"] = [nid]
                else:
                    # A replica: one entry, all its homes; size is the
                    # primary's (max — replicas are byte-identical).
                    cur["nodes"].append(nid)
                    cur["size"] = max(cur["size"], rec.get("size", 0))
        objs = sorted(best.values(), key=lambda r: r.get("size", 0),
                      reverse=True)
        groups: dict[str, dict] = {}
        for rec in objs:
            if group_by == "node":
                keys = rec.get("nodes") or ["(unknown)"]
            else:
                keys = [rec.get(group_by) or "(unknown)"]
            for key in keys:
                g = groups.setdefault(
                    key, {"key": key, "bytes": 0, "objects": 0})
                g["bytes"] += rec.get("size", 0)
                g["objects"] += 1
        with self._obj_lock:
            n_leaks = len(self._leaks)
        return {
            "totals": totals,
            "nodes": nodes,
            "top_objects": objs[:top_k],
            "group_by": group_by,
            "groups": sorted(groups.values(),
                             key=lambda g: g["bytes"], reverse=True),
            "leaks": n_leaks,
        }

    def rpc_memory_leaks(self):
        """Objects the sweeper currently flags, largest first."""
        with self._obj_lock:
            leaks = [dict(v) for v in self._leaks.values()]
        leaks.sort(key=lambda r: r.get("size", 0), reverse=True)
        return leaks

    def _leak_sweep_loop(self):
        interval = max(0.25, config.leak_sweep_interval_s)
        while not self._stop.wait(interval):
            try:
                self._sweep_leaks_once()
            except Exception:
                continue  # observability must never take the head down

    def _sweep_leaks_once(self):
        """Flag objects alive past the age threshold that nothing can
        reach anymore: either NO registered holder (an owner that died
        before its ref flush leaves a pinned, untracked primary copy —
        the classic shm leak), or held refs whose every replica is gone
        (primary copy lost: the refs can never resolve again without
        lineage). Flags clear the moment a holder appears or the object
        frees."""
        threshold = config.leak_age_threshold_s
        if threshold <= 0:
            return
        now = time.time()
        with self._obj_lock:
            flagged: dict[str, dict] = {}
            for oid, entry in self._objects.items():
                attr = entry.get("attr") or {}
                created = attr.get("created_at")
                if not created or now - created < threshold:
                    continue
                holders = self._refs.get(oid)
                inflight = self._inflight.get(oid, 0)
                live_nodes = [
                    nid for nid in entry["nodes"]
                    if self._nodes.get(nid) and self._nodes[nid].alive
                ]
                if not holders and inflight == 0:
                    kind = "no_reachable_refs"
                elif holders and not live_nodes:
                    kind = "primary_copy_lost"
                else:
                    continue
                prev = self._leaks.get(oid)
                flagged[oid] = {
                    "object_id": oid,
                    "kind": kind,
                    "size": entry.get("size", 0),
                    "nodes": sorted(entry["nodes"]),
                    "age_s": round(now - created, 1),
                    "owner": attr.get("owner", ""),
                    "task": attr.get("task", ""),
                    "callsite": attr.get("callsite", ""),
                    "holders": sorted(holders or ()),
                    "first_flagged": (prev or {}).get(
                        "first_flagged", round(now, 3)),
                }
            self._leaks = flagged

    def rpc_capture_profile(self, worker_id, duration_s: float = 1.0,
                            interval_s: float = 0.01, node_id=None):
        """Route a remote profiler capture to the agent owning the
        worker; returns the capture manifest (files stream back through
        rpc_read_capture_file)."""
        _nid, client = self._route_worker(
            worker_id, node_id, need_live=True)
        return client.call(
            "capture_profile", worker_id, duration_s, interval_s,
            timeout=float(duration_s) + 90.0)

    def rpc_read_capture_file(self, node_id, capture_id, name,
                              offset: int = 0, max_bytes: int = 1 << 20):
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node.alive:
                raise ValueError(f"node {node_id!r} is not alive")
            client = node.client
        return client.call(
            "read_capture_file", capture_id, name, offset, max_bytes,
            timeout=30.0)

    # -- cluster metrics federation ----------------------------------------

    def cluster_metrics_text(self) -> str:
        """One Prometheus exposition body covering the whole cluster:
        the head's own registry merged with every alive agent's
        (``/metrics/cluster``) — one scrape config instead of one
        endpoint per process."""
        from ray_tpu.util import metrics as _metrics

        chunks = [_metrics.prometheus_text()]
        chunks.extend(self._fanout_agents("metrics_text", timeout=5.0))
        return _metrics.merge_prometheus(chunks)

    def rpc_cluster_metrics_text(self) -> str:
        return self.cluster_metrics_text()

    def _file_sd_text(self) -> str:
        import json as _json

        from ray_tpu.util import metrics as _metrics

        return _json.dumps(
            _metrics.file_sd_targets(self.metrics_address or ""), indent=1)

    def rpc_metrics_endpoint(self):
        """Where to scrape this cluster: the head's metrics HTTP server
        (None when disabled)."""
        if self.metrics_address is None:
            return None
        return {
            "address": self.metrics_address,
            "cluster_path": "/metrics/cluster",
            "targets_path": "/metrics/targets",
        }

    # -- signal plane (metrics history ring + SLO evaluation) --------------

    def _signal_scrape_loop(self):
        """Self-scrape the federated exposition into the history ring.
        The fanout inside cluster_metrics_text already tolerates dead
        agents (their chunk is skipped), so one bad node degrades the
        snapshot, never the loop."""
        interval = max(0.1, config.signal_scrape_interval_s)
        while not self._stop.wait(interval):
            try:
                self._scrape_signals_once()
            except Exception:
                from ray_tpu.util import metrics as _metrics

                _metrics.count_loop_restart("head.signal_scrape")
                continue

    def _scrape_signals_once(self):
        from ray_tpu.util import metrics as _metrics
        from ray_tpu.util import tracing as _tracing

        # Suppressed: the self-scrape fans an RPC to every agent on a
        # 2s cadence forever — with tracing enabled those control-plane
        # spans would drown the request traces the recorder exists for.
        with _tracing.suppressed():
            t0 = time.perf_counter()
            text = self.cluster_metrics_text()
            n_series = self._signals.ingest_text(time.time(), text)
        _metrics.HEAD_SIGNAL_SCRAPE_SECONDS.observe(
            time.perf_counter() - t0)
        _metrics.HEAD_SIGNAL_SERIES.set(float(n_series))

    def _slo_eval_loop(self):
        interval = max(0.1, config.slo_eval_interval_s)
        while not self._stop.wait(interval):
            try:
                self._eval_slos_once()
            except Exception:
                from ray_tpu.util import metrics as _metrics

                _metrics.count_loop_restart("head.slo_eval")
                continue

    def _eval_slos_once(self):
        events = self._signals.evaluate_slos(time.time())
        for ev in events:
            # Same plane drain/OOM events ride: channel/key/payload.
            self.pubsub.publish("SLO", ev["slo"], ev)

    def rpc_query_metrics(self, spec: dict):
        """Windowed query against the head's history ring (see
        signals.SignalPlane.query for the spec shape). Answers
        {"ok": False, "error": "..."} when the ring is disabled so
        callers can fall back without a try/except."""
        if self._signals is None:
            return {"ok": False, "error": "signal plane disabled"}
        return self._signals.query(spec)

    def rpc_slo_status(self):
        if self._signals is None:
            return {"ok": False, "error": "signal plane disabled"}
        return {"ok": True, **self._signals.slo_status()}

    def rpc_register_slo(self, name: str, expr: str):
        if self._signals is None:
            return {"ok": False, "error": "signal plane disabled"}
        try:
            return {"ok": True, "slo": self._signals.register_slo(
                name, expr)}
        except ValueError as e:
            return {"ok": False, "error": str(e)}

    def rpc_remove_slo(self, name: str):
        if self._signals is None:
            return {"ok": False, "error": "signal plane disabled"}
        return {"ok": True,
                "removed": self._signals.remove_slo(name)}

    def rpc_signal_top(self, window_s: float = 60.0):
        """The `ray-tpu top` rollup — every number a ring query, zero
        sleeps in this path by construction."""
        if self._signals is None:
            return {"ok": False, "error": "signal plane disabled"}
        out = {"ok": True,
               **self._signals.top_summary(float(window_s))}
        # Flight-recorder rollup: assembled/kept/dropped trace counts
        # and span-truncation drops, so `ray-tpu top` shows whether the
        # trace plane is whole (no-silent-caps surfaced, not buried).
        out["traces"] = self._traces.stats()
        with self._event_lock:
            out["traces"]["head_spans_dropped"] = self._spans_dropped
            out["traces"]["worker_spans_dropped"] = self._worker_span_drops
        return out

    # -- chaos / fault-injection control plane -----------------------------
    # The head is the arming point for cluster-wide deterministic fault
    # injection: failpoint specs and network-chaos rules fan out to every
    # alive agent (which fans failpoints on to its live workers), so one
    # `state.set_failpoints(...)` / `ray-tpu chaos` call arms the whole
    # cluster regardless of process layout.

    def rpc_set_failpoints(self, specs: dict, include_workers: bool = True):
        """Arm/disarm failpoints everywhere: ``{site: spec}`` (falsy spec
        disarms). Returns {"head": armed, <node_id>: armed-or-error}."""
        out = {"head": failpoints.set_failpoints(specs)}
        for nid, client in self._alive_agents():
            try:
                out[nid] = client.call(
                    "set_failpoints", specs, include_workers, timeout=10.0)
            except Exception as e:
                out[nid] = {"error": repr(e)}
        return out

    def rpc_list_failpoints(self):
        """Armed failpoints per process: {"head": {...}, <node_id>: {...}}
        (worker tables are folded in by each agent)."""
        out = {"head": failpoints.list_armed()}
        for nid, client in self._alive_agents():
            try:
                out[nid] = client.call("list_failpoints", timeout=10.0)
            except Exception as e:
                out[nid] = {"error": repr(e)}
        return out

    def rpc_set_channel_chaos(self, rules: list, label: str = ""):
        """Arm network-chaos rules (wire-shaped dicts: action/src/dst/
        method/arg/prob/times) in the head's process, every alive
        agent's, and — best-effort, via each agent — its live workers,
        so both directions of a partition/delay are observed everywhere.
        Returns the per-process count armed."""
        # Arming RPCs are chaos-exempt (rpc.CHAOS_CONTROL_METHODS), so
        # the fan-out reaches every agent even once the first in-process
        # arm lands rules in the shared table; fanning out before the
        # local arm keeps multi-process agents symmetric regardless.
        out = {}
        for nid, client in self._alive_agents():
            try:
                out[nid] = client.call(
                    "set_channel_chaos", rules, label, timeout=10.0)
            except Exception as e:
                out[nid] = {"error": repr(e)}
        out["head"] = channel_chaos.add_rule_dicts(rules, label)
        return out

    def rpc_clear_channel_chaos(self, label: str | None = None):
        """Remove network-chaos rules everywhere (all, or one label —
        e.g. "partition" for ``heal``). Returns per-process counts."""
        out = {"head": channel_chaos.clear(label)}
        for nid, client in self._alive_agents():
            try:
                out[nid] = client.call(
                    "clear_channel_chaos", label, timeout=10.0)
            except Exception as e:
                out[nid] = {"error": repr(e)}
        return out

    def rpc_list_channel_chaos(self):
        out = {"head": channel_chaos.describe()}
        for nid, client in self._alive_agents():
            try:
                out[nid] = client.call("list_channel_chaos", timeout=10.0)
            except Exception as e:
                out[nid] = [{"error": repr(e)}]
        return out

    def rpc_partition(self, groups: list):
        """Network partition between groups of endpoints: each group is a
        list of node ids (or the string "head"). Symmetric drop rules —
        (src in A, dst in B) AND (src in B, dst in A) for every pair —
        are armed in every process so heartbeats, gossip, fan-outs, and
        object traffic all observe the cut. Heal with rpc_heal()."""
        with self._lock:
            addr_of = {nid: n.address for nid, n in self._nodes.items()}
            client_of = {nid: n.client for nid, n in self._nodes.items()}
        addr_groups = []
        for group in groups:
            addrs = set()
            for member in group:
                if member == "head":
                    addrs.add(self.address)
                elif member in addr_of:
                    addrs.add(addr_of[member])
                    # A node's cut covers its workers' own RPC servers
                    # too — cross-node actor pushes and owner notifies
                    # go straight to worker addresses, not the agent's.
                    # Best-effort (pre-arming, so never chaos-dropped):
                    # an unreachable agent still gets the agent-level
                    # cut.
                    try:
                        addrs.update(client_of[member].call(
                            "worker_addresses", timeout=5.0))
                    except Exception:
                        pass
                elif ":" in member:
                    addrs.add(member)  # already a host:port address
                else:
                    # A typo'd/stale node id would arm a never-matching
                    # rule: a "partition" that silently cuts nothing.
                    raise ValueError(
                        f"unknown partition group member {member!r} "
                        f"(known node ids: {sorted(addr_of)} or 'head')")
            addr_groups.append(addrs)
        rules = []
        for i, a in enumerate(addr_groups):
            for b in addr_groups[i + 1:]:
                rules.append({"action": "drop", "src": sorted(a),
                              "dst": sorted(b), "label": "partition"})
                rules.append({"action": "drop", "src": sorted(b),
                              "dst": sorted(a), "label": "partition"})
        return self.rpc_set_channel_chaos(rules, label="partition")

    def rpc_heal(self):
        return self.rpc_clear_channel_chaos("partition")

    # -- scheduling -------------------------------------------------------

    def rpc_schedule(self, demand, caller_node=None, strategy=None,
                     node_affinity=None, task_id=None):
        """Pick a node for a task/actor; returns (node_id, address) or None
        if no alive node can ever fit the demand."""
        with self._lock:
            return self._schedule_locked(
                demand, caller_node, strategy, node_affinity, task_id)

    def rpc_schedule_batch(self, requests):
        """Place many tasks under ONE lock acquisition (the head-side half
        of lease pipelining, cf. the reference's backlog-aware
        RequestWorkerLease batching in direct_task_transport.h:57).
        ``requests``: list of dicts with the rpc_schedule kwargs; returns a
        placement (or None) per request, with the optimistic debit applied
        sequentially so a burst spreads across feasible nodes. A request
        marked ``spilled`` was just REJECTED by the caller's own node
        (leased-push admission) — the view of that node is stale-high, so
        prefer-local is suppressed and other feasible nodes win ties."""
        failpoints.hit("head.schedule.batch")
        with self._lock:
            return [
                self._schedule_locked(
                    r["demand"], r.get("caller_node"), r.get("strategy"),
                    r.get("node_affinity"), r.get("task_id"),
                    spilled=r.get("spilled", False))
                for r in requests
            ]

    def _schedule_locked(self, demand, caller_node=None, strategy=None,
                         node_affinity=None, task_id=None, spilled=False):
        # DRAINING nodes are excluded from every new placement (they only
        # finish what they already have).
        alive = [n for n in self._nodes.values() if n.schedulable]
        if node_affinity is not None:
            node = self._nodes.get(node_affinity)
            if node is not None and node.schedulable:
                return self._pick(node, demand)
            return None
        feasible = [
            n
            for n in alive
            if all(n.resources.get(k, 0.0) >= v for k, v in demand.items())
        ]
        if feasible and task_id is not None:
            # A satisfied retry retires its recorded miss immediately —
            # the autoscaler must size against live demand, not demand
            # that capacity already absorbed (stale misses otherwise
            # linger a full window and over-provision the next pass).
            self._demand_misses.pop(task_id, None)
        if not feasible:
            # One live entry per pending task: retries refresh the
            # timestamp (and slot order) instead of inflating apparent
            # demand.
            if task_id is None:
                self._demand_miss_seq += 1
                key = f"_anon:{self._demand_miss_seq}"
            else:
                key = task_id
            self._demand_misses.pop(key, None)
            self._demand_misses[key] = {
                "demand": dict(demand), "ts": time.monotonic(),
                "task_id": task_id,
            }
            while len(self._demand_misses) > 1000:
                self._demand_misses.popitem(last=False)
            return None

        def headroom(n: NodeInfo) -> float:
            return min(
                (n.available.get(k, 0.0) - v for k, v in demand.items()),
                default=1.0,
            )

        if strategy == "SPREAD" or not demand:
            # Zero-demand tasks/actors have headroom EVERYWHERE, so
            # hybrid prefer-local would pile every one of them onto the
            # caller's node (and through its worker pool) forever —
            # round-robin them instead.
            self._rr_counter += 1
            return self._pick(
                feasible[self._rr_counter % len(feasible)], demand)
        # Hybrid: prefer caller's node while it has headroom — unless
        # the caller's node itself just rejected this spec (spilled).
        if caller_node is not None and not spilled:
            local = self._nodes.get(caller_node)
            if local is not None and local.schedulable and local in feasible:
                if headroom(local) >= 0:
                    return self._pick(local, demand)
        if spilled and len(feasible) > 1:
            others = [n for n in feasible
                      if n.node_id != caller_node]
            if others:
                return self._pick(max(others, key=headroom), demand)
        best = max(feasible, key=headroom)
        return self._pick(best, demand)

    def _pick(self, node: NodeInfo, demand):
        # Optimistically debit the view so bursts spread before the next
        # heartbeat refreshes truth (the node agent's heartbeat remains
        # authoritative and restores the real availability). The cached
        # cluster-available sum tracks the same debit so status pollers
        # see it; the node's next heartbeat delta restores both together.
        debit_cache = node.schedulable
        for k, v in demand.items():
            node.available[k] = node.available.get(k, 0.0) - v
            if debit_cache:
                self._res_avail[k] = self._res_avail.get(k, 0.0) - v
        return node.node_id, node.address

    def rpc_pending_demands(self, window_s: float = 30.0):
        """Recent demands no alive node could fit (autoscaler input)."""
        cutoff = time.monotonic() - window_s
        with self._lock:
            for key in [k for k, m in self._demand_misses.items()
                        if m["ts"] < cutoff]:
                del self._demand_misses[key]
            return [dict(m["demand"])
                    for m in self._demand_misses.values()]

    def rpc_demand_snapshot(self, window_s: float = 30.0):  # idempotent (read-only)
        """Everything the autoscaler's bin-packer sizes against, in one
        consistent read (resource_demand_scheduler.py:103 input shape):
        queued task demands no node could fit, pending (RESTARTING)
        actors whose restart is still hunting for placement, and the
        unplaced bundles of PENDING/RESCHEDULING placement groups —
        with their strategy (STRICT_SPREAD bundles need N distinct
        nodes, not N bundles-worth of one node) and spot constraint."""
        cutoff = time.monotonic() - window_s
        with self._lock:
            for key in [k for k, m in self._demand_misses.items()
                        if m["ts"] < cutoff]:
                del self._demand_misses[key]
            tasks = [dict(m["demand"])
                     for m in self._demand_misses.values()]
            actors = []
            for aid, info in self._actors.items():
                if info.get("state") != "RESTARTING":
                    continue
                rec = self._actor_specs.get(aid)
                if rec is None:
                    continue
                actors.append(dict(rec["spec"].get("demand") or {}))
            pg_bundles = []
            for pg in self._pgs.values():
                if pg["state"] not in ("PENDING", "RESCHEDULING"):
                    continue
                live = {
                    bi for nid, bi in pg["placement"]
                    if self._nodes.get(nid) is not None
                    and self._nodes[nid].schedulable
                }
                lost = [i for i in range(len(pg["bundles"]))
                        if i not in live]
                if not lost:
                    continue
                pg_bundles.append({
                    "pg_id": pg["placement_group_id"],
                    "strategy": pg["strategy"],
                    "bundles": [dict(pg["bundles"][i]) for i in lost],
                    "spot": bool(pg.get("spot", True)),
                })
        return {"tasks": tasks, "actors": actors,
                "pg_bundles": pg_bundles}

    def rpc_terminate_ack(self, node_id, cause: str = ""):  # idempotent (keyed last-write-wins)
        """The autoscaler's confirmation that a node's provider
        resources were released after its drain completed. Keyed
        last-write-wins per node so a replay through a severed reply
        records once; a node still alive is NOT acked (the autoscaler
        must drain first — this is the zero-goodput-loss contract)."""
        with self._lock:
            node = self._nodes.get(node_id)
            if node is not None and node.alive:
                return {"ok": False, "state": node.state,
                        "error": "node still alive; drain before terminate"}
            self._terminate_acks[node_id] = {
                "cause": cause, "ts": time.time(),
            }
            while len(self._terminate_acks) > 1000:
                self._terminate_acks.pop(next(iter(self._terminate_acks)))
        return {"ok": True, "node_id": node_id}

    def rpc_autoscaler_report(self, report: dict):  # idempotent (full-state replace)
        """Autoscaler self-report: per-node-type quarantine/backoff/
        launch state, replaced wholesale each reconcile tick (replays
        converge on the same state)."""
        with self._lock:
            self._autoscaler_report = dict(report or {})
            self._autoscaler_report["ts"] = time.time()
        return {"ok": True}

    def rpc_autoscaler_status(self):  # idempotent (read-only)
        with self._lock:
            return dict(self._autoscaler_report)

    # -- placement groups (2-phase commit) --------------------------------

    def rpc_create_placement_group(self, bundles, strategy, name="",
                                   lifetime=None, pg_id=None, spot=True):
        if pg_id is None:  # legacy caller: server-generated id
            pg_id = ids.new_placement_group_id()
        with self._lock:
            if pg_id in self._pgs:
                # Idempotent replay (client retried through a head
                # restart): the PG already exists, don't double-reserve.
                return pg_id
            self._pgs[pg_id] = {
                "placement_group_id": pg_id,
                "bundles": bundles,
                "strategy": strategy,
                "name": name,
                "state": "PENDING",
                "placement": [],  # [(node_id, bundle_index)]
                "reschedules": 0,
                # spot=False marks the gang preemption-critical: the
                # autoscaler's bin-packer only sizes on-demand node
                # types for its unplaced bundles.
                "spot": bool(spot),
            }
        threading.Thread(
            target=self._reserve_pg, args=(pg_id,), daemon=True
        ).start()
        return pg_id

    def _pg_assign(self, bundles, strategy) -> Optional[list]:
        """Choose a node per bundle against total capacities: the
        degenerate every-bundle-lost case of the reschedule
        coordinator's partial assign — ONE bin-packing implementation
        for both the initial reserve and the migration."""
        return self._pg_assign_partial(
            bundles, strategy, [], list(range(len(bundles))))

    def _reserve_pg(self, pg_id: str):
        # Reservation retries while the PG is PENDING: a prepare that
        # fails because another group currently holds the resources is
        # TRANSIENT (reference PGs stay pending until placeable);
        # INFEASIBLE is only declared when no assignment exists against
        # node TOTALS — it can never fit.
        while True:
            with self._lock:
                pg = self._pgs.get(pg_id)
                if pg is None or pg["state"] != "PENDING":
                    return  # removed (or already settled) while retrying
                bundles, strategy = pg["bundles"], pg["strategy"]
            assignment = self._pg_assign(bundles, strategy)
            if assignment is None:
                with self._lock:
                    pg["state"] = "INFEASIBLE"
                    self._pg_event(pg)
                return
            # Phase 1: prepare every bundle on its node (blocking until
            # the node can reserve it); phase 2: commit. Rollback and
            # retry on any failure.
            prepared: list[tuple[str, int]] = []
            ok = True
            for node_id, bundle_index in assignment:
                with self._lock:
                    node = self._nodes.get(node_id)
                if node is None or not node.alive:
                    ok = False
                    break
                # Appended BEFORE the call: a prepare that LANDED
                # agent-side but whose reply was lost (severed channel,
                # timeout) must still be rolled back, or the carve-out
                # leaks when the retry round picks a different node.
                # return_bundle on a node the prepare never reached is
                # an idempotent no-op.
                prepared.append((node_id, bundle_index))
                try:
                    failpoints.hit("head.pg.prepare")
                    node.client.call(
                        "prepare_bundle", pg_id, bundle_index,
                        bundles[bundle_index],
                        # timeout-budget: outlasts config.bundle_reserve_timeout_s
                        timeout=config.bundle_reserve_timeout_s * 2,
                    )
                except Exception:
                    ok = False
                    break
            if ok:
                break
            for node_id, bundle_index in prepared:
                with self._lock:
                    node = self._nodes.get(node_id)
                if node is not None:
                    try:
                        node.client.call("return_bundle", pg_id, bundle_index)
                    except Exception:
                        from ray_tpu.util import metrics as _metrics

                        _metrics.count_loop_restart("head.reserve_pg")
            time.sleep(0.25)
        for node_id, bundle_index in assignment:
            with self._lock:
                node = self._nodes.get(node_id)
            try:
                failpoints.hit("head.pg.commit")
                node.client.call("commit_bundle", pg_id, bundle_index)
            except Exception:
                pass
        rollback = False
        with self._lock:
            if pg["state"] == "REMOVED":
                # Removed while we were reserving: give everything back
                # instead of resurrecting the group.
                rollback = True
            else:
                pg["placement"] = assignment
                pg["state"] = "CREATED"
                self._pg_event(pg)
        if rollback:
            for node_id, bundle_index in assignment:
                with self._lock:
                    node = self._nodes.get(node_id)
                if node is not None and node.alive:
                    try:
                        node.client.call("return_bundle", pg_id, bundle_index)
                    except Exception:
                        pass

    def rpc_remove_placement_group(self, pg_id):
        with self._lock:
            pg = self._pgs.get(pg_id)
            if pg is None:
                return False
            prev, pg["state"] = pg["state"], "REMOVED"
            placement = list(pg["placement"])
            self._pg_event(pg)
        if prev in ("CREATED", "RESCHEDULING"):
            # RESCHEDULING placements may include dead nodes (nothing to
            # return there) and draining nodes (return, so the drain can
            # finish); a reschedule coordinator racing this sees REMOVED
            # under the lock and rolls back its own prepared bundles.
            for node_id, bundle_index in placement:
                with self._lock:
                    node = self._nodes.get(node_id)
                if node is not None and node.alive:
                    try:
                        node.client.call("return_bundle", pg_id, bundle_index)
                    except Exception:
                        pass
        return True

    def _pg_table_entry(self, pg: dict) -> dict:
        """Caller holds self._lock. Public table view of one PG: the
        coordinator's private keys are stripped, and the bundle->node
        map plus per-bundle liveness ride along so gang holders (elastic
        trainers, `ray-tpu status`, the dashboard) can see exactly which
        bundles survived a node loss."""
        e = {k: v for k, v in pg.items() if not k.startswith("_")}
        e["placement"] = list(pg["placement"])
        e["bundle_nodes"] = {bi: nid for nid, bi in pg["placement"]}
        e["live_bundles"] = sorted(
            bi for nid, bi in pg["placement"]
            if self._nodes.get(nid) is not None
            and self._nodes[nid].schedulable
        )
        e.setdefault("reschedules", 0)
        return e

    def rpc_placement_group_table(self, pg_id=None):
        with self._lock:
            if pg_id is not None:
                pg = self._pgs.get(pg_id)
                return self._pg_table_entry(pg) if pg else None
            return {k: self._pg_table_entry(v)
                    for k, v in self._pgs.items()}

    def rpc_pg_node_for_bundle(self, pg_id, bundle_index, timeout=30.0):
        """Blocking: node that holds the given bundle (or any, if -1).
        A RESCHEDULING group parks the caller — its bundles are being
        migrated to healthy nodes, and the resolution that eventually
        returns points at the bundle's NEW home (tasks pinned to a
        migrated bundle re-resolve instead of erroring)."""
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                pg = self._pgs.get(pg_id)
                if pg is None:
                    raise ValueError(f"no such placement group {pg_id}")
                if pg["state"] == "INFEASIBLE":
                    raise ValueError(f"placement group {pg_id} is infeasible")
                if pg["state"] == "REMOVED":
                    raise ValueError(f"placement group {pg_id} was removed")
                if pg["state"] == "DEAD":  # legacy persisted state
                    raise ValueError(f"placement group {pg_id} is dead")
                if pg["state"] == "CREATED":
                    for node_id, bi in pg["placement"]:
                        if bundle_index < 0 or bi == bundle_index:
                            node = self._nodes.get(node_id)
                            if node and node.alive:
                                return node_id, node.address
                    raise ValueError(
                        f"bundle {bundle_index} of {pg_id} has no live node"
                    )
                if pg["state"] == "RESCHEDULING":
                    # Only the LOST bundles park. A surviving bundle
                    # resolves immediately — an elastic gang running at
                    # shrunk world size places its workers on the live
                    # bundles while the coordinator migrates the rest.
                    for node_id, bi in pg["placement"]:
                        if bundle_index < 0 or bi == bundle_index:
                            node = self._nodes.get(node_id)
                            if node is not None and node.schedulable:
                                return node_id, node.address
            if time.monotonic() > deadline:
                raise TimeoutError(f"placement group {pg_id} not ready")
            time.sleep(0.02)

    # -- placement-group rescheduling (reservation outlives its nodes) -----
    #
    # Podracer-style preemptible fleets lose nodes as the NORMAL case:
    # a gang reservation must migrate, not die, when a bundle's node
    # drains or crashes. The state machine is
    #
    #     CREATED --(bundle node dead/draining)--> RESCHEDULING
    #     RESCHEDULING --(2PC re-reserve on healthy nodes)--> CREATED
    #     RESCHEDULING --(remove_placement_group)--> REMOVED
    #
    # driven by one coordinator thread per group (restarted by the
    # monitor loop if it ever dies — including across a head restart
    # that reloads a RESCHEDULING group from the snapshot). Lock
    # discipline: every node RPC runs OUTSIDE the shard locks.

    @staticmethod
    def _pg_reschedule_cause(cause: str) -> str:
        """Metric cause class for a reschedule trigger: planned drains
        (including a drained node whose heartbeat-death won the race)
        vs a crash-detected node death."""
        if "drain" in cause:
            return "drain"
        return "node_death"

    def _pg_event(self, pg: dict, cause: str | None = None) -> None:
        """Caller holds self._lock. Publish the group's latest lifecycle
        state on the PLACEMENT_GROUPS channel (the NODES/ACTORS
        state-update shape: full latest state per key, coalesced for
        slow subscribers) so gang holders learn their bundles moved
        without polling the table."""
        msg = {
            "placement_group_id": pg["placement_group_id"],
            "state": pg["state"],
            "placement": list(pg["placement"]),
            "reschedules": pg.get("reschedules", 0),
        }
        if cause:
            msg["cause"] = cause
        self.pubsub.publish(
            "PLACEMENT_GROUPS", pg["placement_group_id"], msg)

    def _pg_mark_rescheduling_locked(self, pg: dict, cause: str) -> None:
        """Caller holds self._lock. Move the group to RESCHEDULING and
        ensure exactly one coordinator drives it: a second node loss
        mid-reschedule only refreshes the cause — the running
        coordinator re-derives the lost bundle set every round."""
        pg["state"] = "RESCHEDULING"
        pg["reschedule_cause"] = cause
        self._pg_event(pg, cause)
        if pg.get("_resched_active"):
            return
        pg["_resched_active"] = True
        threading.Thread(
            target=self._reschedule_pg,
            args=(pg["placement_group_id"], cause), daemon=True,
        ).start()

    def _pg_assign_partial(self, bundles, strategy, keep,
                           lost) -> Optional[list]:
        """Choose a node for each LOST bundle against node totals,
        honoring the strategy alongside the surviving placement:
        surviving bundles' demand counts into the plan (no
        double-booking their nodes), SPREAD ranks surviving nodes last,
        STRICT_SPREAD excludes them, STRICT_PACK targets the surviving
        node (or one fresh node for a full loss)."""
        with self._lock:
            alive = [n for n in self._nodes.values() if n.schedulable]
        if not alive:
            return None
        planned: dict[str, dict[str, float]] = {
            n.node_id: {} for n in alive}
        keep_nodes: set[str] = set()
        for nid, bi in keep:
            keep_nodes.add(nid)
            add = planned.get(nid)
            if add is not None:
                for k, v in bundles[bi].items():
                    add[k] = add.get(k, 0.0) + v

        def fits(n: NodeInfo, b: dict) -> bool:
            add = planned[n.node_id]
            return all(
                n.resources.get(k, 0.0) >= add.get(k, 0.0) + v
                for k, v in b.items()
            )

        def commit(n: NodeInfo, b: dict):
            add = planned[n.node_id]
            for k, v in b.items():
                add[k] = add.get(k, 0.0) + v

        assignment: list[tuple[str, int]] = []
        if strategy in ("PACK", "STRICT_PACK"):
            order = sorted(alive, key=lambda n: -sum(n.resources.values()))
            if strategy == "STRICT_PACK":
                # Everything on ONE node: the survivors' node if any
                # bundle survived, else the single best fresh node.
                if keep_nodes:
                    order = [n for n in order if n.node_id in keep_nodes]
                order = order[:1]
            for bi in lost:
                b = bundles[bi]
                for n in order:
                    if fits(n, b):
                        commit(n, b)
                        assignment.append((n.node_id, bi))
                        break
                else:
                    return None
        elif strategy in ("SPREAD", "STRICT_SPREAD"):
            used = set(keep_nodes)
            for bi in lost:
                b = bundles[bi]
                ranked = sorted(
                    alive,
                    key=lambda n: (n.node_id in used,
                                   -sum(n.resources.values())),
                )
                placed = False
                for n in ranked:
                    if strategy == "STRICT_SPREAD" and n.node_id in used:
                        continue
                    if fits(n, b):
                        commit(n, b)
                        used.add(n.node_id)
                        assignment.append((n.node_id, bi))
                        placed = True
                        break
                if not placed:
                    return None
        else:
            return None
        return assignment

    def _pg_rollback(self, pg_id: str, prepared: list) -> None:
        """Return every bundle a failed 2PC round prepared — per node,
        best-effort (a dead node's reservation died with it) — so a
        partial prepare can never leak a per-node reservation."""
        for node_id, bi in prepared:
            with self._lock:
                node = self._nodes.get(node_id)
            if node is not None and node.alive:
                try:
                    node.client.call("return_bundle", pg_id, bi,
                                     timeout=30.0)
                except Exception:
                    pass

    def _pg_commit_assignment(self, pg_id: str, assignment: list) -> bool:
        """Phase 2 on the replacement nodes. ``commit_bundle`` is
        idempotent agent-side and ``prepare_bundle`` replays are
        absorbed there too, so a commit whose reply was severed
        mid-channel retries safely — exactly-once reservation. Returns
        False when a target died mid-commit (caller re-derives)."""
        for node_id, bi in assignment:
            for attempt in range(3):
                with self._lock:
                    node = self._nodes.get(node_id)
                    node_alive = node is not None and node.alive
                if not node_alive:
                    return False
                try:
                    failpoints.hit("head.pg.commit")
                    node.client.call("commit_bundle", pg_id, bi,
                                     timeout=30.0)
                    break
                except Exception:
                    if attempt == 2:
                        return False
                    time.sleep(0.1)
        return True

    def _reschedule_pg(self, pg_id: str, cause: str) -> None:
        """One group's reschedule lifecycle: re-run the reserve 2PC for
        its lost bundles on healthy nodes — prepare every replacement
        (rollback on partial failure), commit, install the new
        placement — re-queuing behind capacity with the round-6 backoff
        discipline (the gang was feasible once; it waits for a
        replacement node rather than dying). Old reservations on
        still-alive DRAINING nodes are returned only AFTER their
        replacement committed, so the gang always holds a reservation
        somewhere. No node RPC ever runs under a shard lock."""
        t0 = time.monotonic()
        try:
            failpoints.hit("head.pg.before_reschedule")
        except failpoints.FailpointError:
            # Injected coordinator crash: DIE (the finally below clears
            # _resched_active) and let the monitor loop restart a fresh
            # coordinator — swallowing the raise would make the
            # injection a no-op and the recovery path untestable.
            with self._lock:
                pg = self._pgs.get(pg_id)
                if pg is not None:
                    pg["_resched_active"] = False
            return
        backoff = config.submit_retry_base_s
        try:
            while not self._stop.is_set():
                with self._lock:
                    pg = self._pgs.get(pg_id)
                    if pg is None or pg["state"] != "RESCHEDULING":
                        return  # removed / settled while retrying
                    bundles, strategy = pg["bundles"], pg["strategy"]
                    keep: list[tuple] = []
                    lost: list[int] = []
                    vacate: list[tuple] = []
                    for nid, bi in pg["placement"]:
                        n = self._nodes.get(nid)
                        if n is not None and n.schedulable:
                            keep.append((nid, bi))
                        else:
                            lost.append(bi)
                            if n is not None and n.alive:
                                # DRAINING: reservation still held there;
                                # return it after the replacement lands.
                                vacate.append((n, bi))
                if not lost:
                    # Every bundle is back on a schedulable node (e.g. a
                    # transient drain view): settle without a 2PC round.
                    if self._pg_install(pg_id, keep, [], [], t0, cause):
                        return
                    continue
                assignment = self._pg_assign_partial(
                    bundles, strategy, keep, lost)
                if assignment is None:
                    time.sleep(backoff)
                    backoff = min(config.submit_retry_max_s,
                                  backoff * 2.0)
                    continue
                prepared: list[tuple] = []
                ok = True
                for node_id, bi in assignment:
                    with self._lock:
                        node = self._nodes.get(node_id)
                        node_ok = node is not None and node.schedulable
                    if not node_ok:
                        ok = False
                        break
                    # Appended BEFORE the call (see _reserve_pg): a
                    # prepare that landed but lost its reply must roll
                    # back too, or the reservation leaks when the next
                    # round assigns a different node.
                    prepared.append((node_id, bi))
                    try:
                        failpoints.hit("head.pg.prepare")
                        node.client.call(
                            "prepare_bundle", pg_id, bi, bundles[bi],
                            # timeout-budget: outlasts config.bundle_reserve_timeout_s
                            timeout=config.bundle_reserve_timeout_s * 2)
                    except Exception:
                        ok = False
                        break
                if ok:
                    ok = self._pg_commit_assignment(pg_id, assignment)
                if not ok:
                    self._pg_rollback(pg_id, prepared)
                    time.sleep(backoff)
                    backoff = min(config.submit_retry_max_s,
                                  backoff * 2.0)
                    continue
                if self._pg_install(
                        pg_id, keep, assignment, vacate, t0, cause):
                    return
                # A keep-node died mid-2PC: the committed replacements
                # are already installed in the placement; loop to
                # re-derive and re-reserve only the newly lost bundles.
        finally:
            with self._lock:
                pg = self._pgs.get(pg_id)
                if pg is not None:
                    pg["_resched_active"] = False

    def _pg_install(self, pg_id: str, keep: list, assignment: list,
                    vacate: list, t0: float, cause: str) -> bool:
        """Install keep+assignment as the group's placement. Returns
        True when the reschedule is DONE (group CREATED again, or
        removed meanwhile — prepared bundles rolled back); False when a
        surviving node died mid-2PC and the coordinator must re-derive
        (the commit landed: the placement keeps it either way)."""
        placement = sorted(keep + assignment, key=lambda p: p[1])
        removed = False
        done = False
        with self._lock:
            pg = self._pgs.get(pg_id)
            if pg is None or pg["state"] != "RESCHEDULING":
                removed = True
            else:
                pg["placement"] = placement
                still_lost = [
                    nid for nid, _bi in placement
                    if not (self._nodes.get(nid) is not None
                            and self._nodes[nid].schedulable)
                ]
                if not still_lost:
                    pg["state"] = "CREATED"
                    pg["reschedules"] = pg.get("reschedules", 0) + 1
                    pg.pop("reschedule_cause", None)
                    self._pg_event(pg, cause)
                    done = True
        if removed:
            self._pg_rollback(pg_id, assignment)
            return True
        # Vacate the old reservations on draining nodes now that their
        # replacements are committed (kills bundle tasks still there;
        # owners recover them with the drain retry exemption).
        for node, bi in vacate:
            try:
                node.client.call("return_bundle", pg_id, bi, timeout=30.0)
            except Exception:
                pass
        if done:
            from ray_tpu.util import metrics as _metrics

            try:
                _metrics.PG_RESCHEDULES_TOTAL.inc(
                    tags={"cause": self._pg_reschedule_cause(cause)})
                _metrics.PG_RESCHEDULE_SECONDS.observe(
                    time.monotonic() - t0)
            except Exception:
                pass
        return done

    # -- lifecycle --------------------------------------------------------

    def rpc_ping(self):
        return "pong"

    def rpc_event_stats(self):
        """Per-RPC-handler timing stats (event_stats.h analog): the
        control plane's own instrumentation, for finding hot/slow
        handlers without external profilers."""
        return self._server.handler_stats()

    def rpc_shutdown_cluster(self):
        with self._lock:
            nodes = [n for n in self._nodes.values() if n.alive]
        for n in nodes:
            try:
                n.client.call("shutdown_node", timeout=5.0)
            except Exception:
                pass
        return True

    def stop(self):
        self._stop.set()
        with self._free_cv:
            self._free_cv.notify_all()
            self._restore_cv.notify_all()
        from ray_tpu.util import metrics as _metrics

        # Dead head = dead loops: their restart series leave the scrape.
        _metrics.retract_loop_series(["head.free", "head.reserve_pg",
                                      "head.signal_scrape",
                                      "head.slo_eval"])
        if self._metrics_shutdown is not None:
            try:
                self._metrics_shutdown()
            except Exception:
                pass
        self._server.stop()
        if self._store is not None:
            self._store.close()


def main():
    import argparse
    import signal
    import sys

    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    args = parser.parse_args()
    from ray_tpu.cluster.rpc import ensure_cluster_token

    token = ensure_cluster_token()
    head = HeadServer(args.host, args.port)
    print(f"HEAD_ADDRESS={head.address}", flush=True)
    if head.metrics_address:
        # Point Prometheus here with metrics_path=/metrics/cluster (or
        # fetch /metrics/targets as a file-SD document).
        print(f"METRICS_ADDRESS={head.metrics_address}", flush=True)
    if token:
        # Joining nodes/drivers need this in RAY_TPU_CLUSTER_TOKEN.
        print(f"CLUSTER_TOKEN={token}", flush=True)
    signal.sigwait({signal.SIGTERM, signal.SIGINT})
    head.stop()
    sys.exit(0)


if __name__ == "__main__":
    main()
