"""Typed control-plane wire codec: schema'd msgpack frames, no pickle
needed for the hot path.

Plays the role of the reference's protobuf message layer
(``src/ray/protobuf/core_worker.proto``, ``gcs.proto`` — 20 .proto files
whose generated types gRPC frames carry). Instead of codegen we use
msgpack (a C-extension, schema-less binary format) for the envelope and
let the hot-path messages — task specs for ``submit_tasks_leased`` /
``submit_tasks``, ``schedule_batch`` requests, heartbeats,
``wait_locations``, object-transfer chunks — travel as pure
primitive structures (str/bytes/int/float/bool/list/dict), which msgpack
encodes natively, fast, and **without any code-execution surface**: user
payloads (function blobs, task args) are already opaque cloudpickle
``bytes`` produced and consumed only at the worker boundary
(reference parity: the proto's ``bytes args`` fields).

Three extension types cover the non-primitive long tail:

- tuples / sets / frozensets (``EXT_TUPLE``/``EXT_SET``/``EXT_FROZENSET``)
  — structural, recursively safe;
- exceptions (``EXT_EXC``) — encoded as (module, qualname, args, state,
  traceback-string) and reconstructed **only** for whitelisted modules
  (``builtins`` and ``ray_tpu.*``) without calling ``__init__`` (no
  side effects); anything else resurfaces as ``RemoteError``;
- ``EXT_PICKLE`` — arbitrary-object fallback for rare rich-object RPCs.
  Encoded only when the connection profile allows it, and **decoded only
  on authenticated connections** (the peer proved the cluster token in
  the pre-frame handshake, ``rpc.py``). A peer that has not proven the
  token can never reach a pickle deserializer — closing the ShadowRay
  class of issues the reference historically shipped with.
"""

from __future__ import annotations

import importlib
import io
import pickle
from typing import Any

import msgpack

EXT_TUPLE = 1
EXT_SET = 2
EXT_FROZENSET = 3
EXT_EXC = 4
EXT_PICKLE = 127

#: Exception modules the decoder will reconstruct real classes from.
#: Everything else becomes RemoteError (still raisable, still carries
#: the original repr + traceback).
_EXC_MODULE_ALLOW = ("builtins", "ray_tpu")


class WireError(Exception):
    """Malformed or disallowed frame content."""


class RemoteError(Exception):
    """A peer raised an exception type this process refuses to (or
    cannot) reconstruct; carries its printable form."""

    def __init__(self, qualname: str, message: str, traceback_str: str = ""):
        super().__init__(f"{qualname}: {message}")
        self.qualname = qualname
        self.remote_traceback = traceback_str


class _SafePickleUnpickler(pickle.Unpickler):
    """Pickle restricted to an ALLOWLIST of module roots: defense in
    depth behind the auth wall. A blocklist is bypassable by re-entry
    gadgets (e.g. ``REDUCE(pickle.loads, inner_bytes)`` — module
    'pickle' was never on any blocklist), so only modules whose classes
    legitimately ride the control plane resolve at all; builtins
    callables that are themselves gadgets stay blocked by name."""

    _ALLOW_ROOTS = frozenset({"ray_tpu", "builtins", "collections",
                              "numpy", "datetime", "copyreg"})
    _BLOCK_NAMES = frozenset({"eval", "exec", "compile", "open", "input",
                              "__import__", "getattr", "setattr",
                              "delattr", "breakpoint", "vars",
                              "classmethod", "staticmethod"})

    def find_class(self, module: str, name: str):
        root = module.split(".", 1)[0]
        if root not in self._ALLOW_ROOTS or name in self._BLOCK_NAMES:
            raise WireError(
                f"wire pickle refuses {module}.{name} (outside the "
                f"control-plane allowlist)")
        return super().find_class(module, name)


def _exc_payload(e: BaseException) -> bytes:
    cls = type(e)
    try:
        args = [_scrub(a) for a in e.args]
        state = {k: _scrub(v) for k, v in vars(e).items()
                 if not k.startswith("_")}
    except Exception:
        args, state = [str(a) for a in e.args], {}
    return msgpack.packb(
        [cls.__module__, cls.__qualname__, args, state,
         getattr(e, "remote_traceback", "") or ""],
        use_bin_type=True)


def _scrub(v: Any) -> Any:
    """Best-effort primitive projection for exception args/state (these
    must decode even on strict no-pickle profiles)."""
    if v is None or isinstance(v, (bool, int, float, str, bytes)):
        return v
    if isinstance(v, (list, tuple)):
        return [_scrub(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _scrub(x) for k, x in v.items()}
    return repr(v)


def _decode_exc(payload: bytes) -> BaseException:
    try:
        module, qualname, args, state, tb = msgpack.unpackb(
            payload, raw=False, strict_map_key=False)
    except Exception as e:
        raise WireError(f"malformed exception frame: {e!r}") from e
    root = module.split(".", 1)[0]
    if root in _EXC_MODULE_ALLOW or module in _EXC_MODULE_ALLOW:
        try:
            mod = importlib.import_module(module)
            cls: Any = mod
            for part in qualname.split("."):
                cls = getattr(cls, part)
            if (isinstance(cls, type)
                    and issubclass(cls, BaseException)):
                e = cls.__new__(cls)
                e.args = tuple(args)
                if isinstance(state, dict):
                    try:
                        e.__dict__.update(state)
                    except Exception:
                        pass
                if tb:
                    e.remote_traceback = tb
                return e
        except Exception:
            pass
    return RemoteError(f"{module}.{qualname}",
                       ", ".join(repr(a) for a in args), tb)


class WireCodec:
    """One codec per connection profile.

    ``allow_pickle`` mirrors the connection's authentication state: True
    only after the peer proved the cluster token (or on the in-process
    loopback profile tests use). Encoding and decoding are symmetric so
    a strict peer fails fast locally instead of poisoning the remote.
    """

    def __init__(self, allow_pickle: bool):
        self.allow_pickle = allow_pickle
        # One Packer per codec (≈10% per-message encode saving vs packb's
        # fresh-Packer-per-call). Codecs are per-connection/per-thread in
        # rpc.py, so this needs no lock.
        self._packer = msgpack.Packer(
            default=self._default, use_bin_type=True, strict_types=True)

    # -- encode ------------------------------------------------------------

    def _nested(self, obj: Any) -> bytes:
        """Ext payload encoding. MUST NOT reuse self._packer: _default
        fires DURING its pack(), and a reentrant pack corrupts the
        in-progress buffer."""
        return msgpack.packb(
            obj, default=self._default, use_bin_type=True,
            strict_types=True)

    def _default(self, obj: Any):
        if isinstance(obj, tuple):
            if hasattr(obj, "_fields") and self.allow_pickle:
                # namedtuple: field access on the receiver needs the type.
                return msgpack.ExtType(
                    EXT_PICKLE, pickle.dumps(obj, protocol=5))
            return msgpack.ExtType(EXT_TUPLE, self._nested(list(obj)))
        if isinstance(obj, set):
            return msgpack.ExtType(
                EXT_SET, self._nested(sorted_or_list(obj)))
        if isinstance(obj, frozenset):
            return msgpack.ExtType(
                EXT_FROZENSET, self._nested(sorted_or_list(obj)))
        if isinstance(obj, BaseException):
            return msgpack.ExtType(EXT_EXC, _exc_payload(obj))
        if isinstance(obj, dict):          # dict subclass (defaultdict, …)
            return dict(obj)
        if isinstance(obj, (list,)):       # list subclass
            return list(obj)
        if isinstance(obj, str):
            return str(obj)
        if isinstance(obj, (bytes, bytearray, memoryview)):
            return bytes(obj)
        if self.allow_pickle:
            return msgpack.ExtType(
                EXT_PICKLE, pickle.dumps(obj, protocol=5))
        raise WireError(
            f"{type(obj).__name__} is not wire-encodable on an "
            f"unauthenticated connection (primitives, tuples/sets, "
            f"exceptions and bytes only)")

    def packb(self, obj: Any) -> bytes:
        blob = self._packer.pack(obj)
        if len(blob) > (1 << 20):
            # The Packer keeps its grown internal buffer after autoreset;
            # a connection that served one 4 MiB object chunk would pin
            # that capacity for its lifetime. Recreate after big frames —
            # the alloc cost is trivial relative to the frame itself.
            self._packer = msgpack.Packer(
                default=self._default, use_bin_type=True,
                strict_types=True)
        return blob

    # -- decode ------------------------------------------------------------

    def _ext_hook(self, code: int, data: bytes):
        if code == EXT_TUPLE:
            return tuple(self.unpackb(data))
        if code == EXT_SET:
            return set(self.unpackb(data))
        if code == EXT_FROZENSET:
            return frozenset(self.unpackb(data))
        if code == EXT_EXC:
            return _decode_exc(data)
        if code == EXT_PICKLE:
            if not self.allow_pickle:
                raise WireError(
                    "peer sent a pickled object on an unauthenticated "
                    "connection — refused")
            return _SafePickleUnpickler(io.BytesIO(data)).load()
        raise WireError(f"unknown wire extension type {code}")

    def unpackb(self, blob: bytes) -> Any:
        try:
            return msgpack.unpackb(
                blob, raw=False, strict_map_key=False,
                ext_hook=self._ext_hook, use_list=True)
        except WireError:
            raise
        except Exception as e:
            raise WireError(f"malformed frame: {e!r}") from e


def sorted_or_list(s) -> list:
    """Deterministic set encoding when elements are orderable."""
    try:
        return sorted(s)
    except TypeError:
        return list(s)
